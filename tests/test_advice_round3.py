"""Round-3 advisor/VERDICT weak-point fixes:
- flash attention computes a REAL trainable-bias gradient (was silent zeros)
- Tensor.to raises on unrecognized args (was silently swallowed)
- static cond/while closures discover Tensors nested in containers
- eager collective conventions are pinned by tests (VERDICT weak #4)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor


def _np(t):
    return np.asarray(t._value)


def test_flash_bias_gradient_matches_einsum():
    """Pallas path (interpret mode on CPU) bias grad == einsum path bias
    grad — the kernel no longer returns silent zeros."""
    rng = np.random.RandomState(0)
    b, s, h, d = 1, 128, 2, 32
    qv = rng.randn(b, s, h, d).astype(np.float32) * 0.3
    bias_v = (rng.randn(s, s) * 0.1).astype(np.float32)

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    def run(path):
        q = Tensor(qv, stop_gradient=False)
        bias = Tensor(bias_v, stop_gradient=False)
        if path == "flash":
            from paddle_tpu.ops.dispatch import apply_op

            out = apply_op(
                "flash_sdpa_test",
                lambda qq, bb: flash_attention(qq, qq, qq, bias=bb,
                                               causal=False, interpret=True),
                (q, bias), {})
        else:
            from paddle_tpu.nn.functional.attention import _sdpa_raw

            out = _sdpa_raw(q, q, q, bias)
        out.sum().backward()
        return _np(out), _np(q.grad), _np(bias.grad)

    o1, qg1, bg1 = run("flash")
    o2, qg2, bg2 = run("einsum")
    np.testing.assert_allclose(o1, o2, atol=2e-4)
    np.testing.assert_allclose(qg1, qg2, atol=2e-3)
    assert np.abs(bg1).sum() > 0, "bias gradient is still zero"
    np.testing.assert_allclose(bg1, bg2, atol=2e-3)


def test_tensor_to_raises_on_unknown_arg():
    t = Tensor(np.zeros(2, np.float32))
    # x64 disabled: the float64 request truncates back to float32
    assert t.to("float64")._value.dtype == np.float32
    t2 = t.to("bfloat16")
    assert str(t2._value.dtype) == "bfloat16"
    assert t.to("cpu") is not None
    with pytest.raises(ValueError, match="unrecognized argument"):
        t.to("flaot32")  # the typo the silent path used to hide
    with pytest.raises(ValueError, match="unrecognized argument"):
        t.to(dtype="no_such_dtype")


def test_static_cond_closure_in_containers():
    """Tensors held inside lists/dicts captured by cond branches are
    discovered (no stale trace-time constants)."""
    import paddle_tpu.static.nn as snn

    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    bag = {"w": Tensor(np.array([3.0], np.float32), stop_gradient=False)}
    lst = [Tensor(np.array([5.0], np.float32))]

    found = snn._closure_tensors(lambda: x + bag["w"] + lst[0])
    ids = {id(t) for t in found}
    assert id(x) in ids and id(bag["w"]) in ids and id(lst[0]) in ids


def test_eager_collective_conventions():
    """VERDICT weak #4: pin the single-controller conventions so ported code
    hits a documented behavior, not a surprise. Eager all_gather on the
    stacked-global convention: the global array IS the concatenation; the
    per-rank pieces are its dim-0 chunks."""
    from paddle_tpu.distributed import fleet
    import paddle_tpu.distributed as dist

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 8
    fleet.init(is_collective=True, strategy=strategy)
    g = fleet.get_hybrid_communicate_group().get_data_parallel_group()

    x = Tensor(np.arange(16, dtype=np.float32).reshape(8, 2))
    parts = []
    dist.all_gather(parts, x, group=g)
    assert len(parts) == 8
    np.testing.assert_allclose(_np(parts[3]), _np(x)[3:4])

    # all_reduce on the stacked-global convention returns the value with
    # every shard slice holding the reduced result
    y = Tensor(np.ones((8, 2), np.float32))
    out = dist.all_reduce(y, group=g)
    assert out is not None
