"""Meta-optimizers: gradient merge, LocalSGD, DGC.

Reference parity targets: ``fleet/meta_optimizers/gradient_merge_optimizer.py``
(k-step accumulation == one big batch), ``localsgd_optimizer.py`` (params
averaged across the data group every k steps), ``dgc_optimizer.py`` (momentum
correction + error feedback: the sum of communicated gradients converges to
the sum of true gradients).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_optimizers import (
    DGCMomentumOptimizer,
    GradientMergeOptimizer,
    LocalSGDOptimizer,
)


def _make_net(seed=0):
    rng = np.random.RandomState(seed)
    net = nn.Linear(4, 3)
    net.weight.set_value(paddle.to_tensor(rng.randn(4, 3).astype(np.float32)))
    net.bias.set_value(paddle.to_tensor(np.zeros(3, np.float32)))
    return net


def _loss(net, x):
    return (net(x) ** 2).mean()


def test_gradient_merge_equals_big_batch():
    rng = np.random.RandomState(1)
    xs = [paddle.to_tensor(rng.randn(8, 4).astype(np.float32)) for _ in range(4)]

    # merged: 4 micro-steps with k_steps=4 (avg)
    net_a = _make_net()
    opt_a = GradientMergeOptimizer(
        paddle.optimizer.SGD(0.1, parameters=net_a.parameters()),
        k_steps=4, avg=True)
    for x in xs:
        loss = _loss(net_a, x)
        loss.backward()
        opt_a.step()
        opt_a.clear_grad()

    # equivalent single step on the averaged gradient
    net_b = _make_net()
    opt_b = paddle.optimizer.SGD(0.1, parameters=net_b.parameters())
    for x in xs:
        (_loss(net_b, x) / 4.0).backward()  # grads accumulate across calls
    opt_b.step()
    opt_b.clear_grad()

    np.testing.assert_allclose(net_a.weight.numpy(), net_b.weight.numpy(),
                               rtol=1e-5, atol=1e-6)
    # params must NOT move before the k-th micro step
    net_c = _make_net()
    opt_c = GradientMergeOptimizer(
        paddle.optimizer.SGD(0.1, parameters=net_c.parameters()), k_steps=4)
    w0 = net_c.weight.numpy().copy()
    _loss(net_c, xs[0]).backward()
    opt_c.step()
    np.testing.assert_array_equal(net_c.weight.numpy(), w0)


def test_localsgd_single_process_is_plain_sgd():
    """world_size==1: LocalSGD must degrade to the inner optimizer exactly."""
    rng = np.random.RandomState(2)
    xs = [paddle.to_tensor(rng.randn(8, 4).astype(np.float32)) for _ in range(5)]
    net_a, net_b = _make_net(), _make_net()
    opt_a = LocalSGDOptimizer(
        paddle.optimizer.SGD(0.05, parameters=net_a.parameters()), k_steps=2)
    opt_b = paddle.optimizer.SGD(0.05, parameters=net_b.parameters())
    for x in xs:
        _loss(net_a, x).backward()
        opt_a.step()
        opt_a.clear_grad()
        _loss(net_b, x).backward()
        opt_b.step()
        opt_b.clear_grad()
    np.testing.assert_allclose(net_a.weight.numpy(), net_b.weight.numpy(),
                               rtol=1e-6)


def test_dgc_dense_warmup_matches_momentum():
    """Before rampup_begin_step DGC is exactly dense momentum."""
    rng = np.random.RandomState(3)
    xs = [paddle.to_tensor(rng.randn(8, 4).astype(np.float32)) for _ in range(3)]
    net_a, net_b = _make_net(), _make_net()
    opt_a = DGCMomentumOptimizer(0.05, momentum=0.9, rampup_begin_step=100,
                                 parameters=net_a.parameters())
    opt_b = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                      parameters=net_b.parameters())
    for x in xs:
        _loss(net_a, x).backward()
        opt_a.step()
        opt_a.clear_grad()
        _loss(net_b, x).backward()
        opt_b.step()
        opt_b.clear_grad()
    np.testing.assert_allclose(net_a.weight.numpy(), net_b.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_dgc_error_feedback_conserves_gradient_mass():
    """Sparse phase: whatever is not sent stays in the error buffer, so
    (applied updates) + (residual buffers) == dense momentum trajectory."""
    net = _make_net()
    opt = DGCMomentumOptimizer(0.1, momentum=0.0, rampup_begin_step=0,
                               sparsity=[0.5], parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(4).randn(8, 4).astype(np.float32))
    w0 = net.weight.numpy().astype(np.float64).copy()
    loss = _loss(net, x)
    loss.backward()
    g = net.weight.grad.numpy().astype(np.float64).copy()
    opt.step()
    w1 = net.weight.numpy().astype(np.float64)
    applied = (w0 - w1) / 0.1
    residual = opt._accumulators["v_error"][opt._pkey(net.weight)]
    total = applied + np.asarray(residual, dtype=np.float64)
    np.testing.assert_allclose(total, g, rtol=1e-4, atol=1e-5)
    # and something was actually held back (sparsity bites)
    assert np.abs(np.asarray(residual)).sum() > 0


def test_fleet_strategy_chains_meta_optimizers():
    import paddle_tpu.distributed.fleet as fleet_mod

    fleet = fleet_mod.fleet
    strat = paddle.distributed.fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs["k_steps"] = 2
    strat.localsgd = True
    fleet.init(is_collective=True, strategy=strat)
    net = _make_net()
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(0.1, parameters=net.parameters()))
    inner = opt._inner_opt
    assert isinstance(inner, LocalSGDOptimizer)
    assert isinstance(inner._inner_opt, GradientMergeOptimizer)
    # smoke a couple of steps through the whole chain
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(2):
        _loss(net, x).backward()
        opt.step()
        opt.clear_grad()


def test_fleet_strategy_dgc_replaces_momentum():
    import paddle_tpu.distributed.fleet as fleet_mod

    fleet = fleet_mod.fleet
    strat = paddle.distributed.fleet.DistributedStrategy()
    strat.dgc = True
    strat.dgc_configs["rampup_begin_step"] = 1
    fleet.init(is_collective=True, strategy=strat)
    net = _make_net()
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(0.1, momentum=0.9,
                                  parameters=net.parameters()))
    assert isinstance(opt._inner_opt, DGCMomentumOptimizer)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(3):  # crosses rampup_begin_step into the sparse phase
        _loss(net, x).backward()
        opt.step()
        opt.clear_grad()
