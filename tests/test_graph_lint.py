"""paddle_tpu.analysis graph lint: one positive + one clean case per rule,
finding provenance, CLI JSONL round-trip, framework wiring (CompiledStep
warn-on-compile, hapi/Engine one-shot lint), and the lint-vs-telemetry
crosscheck on the Adam lazy-accumulator retrace (pre-fix fixture) plus the
recompile_count=0 regression for the fixed tree."""
import importlib.util
import json
import os
import re
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import analysis
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.profiler import telemetry


def _plain_step(fn, **kw):
    kw.setdefault("stateful", ())
    kw.setdefault("donate_state", False)
    return CompiledStep(fn, **kw)


class _LazyAdam(paddle.optimizer.Adam):
    """Pre-fix fixture: restore the lazy accumulator materialization that
    caused the Adam/AdamW double-trace."""

    def _ensure_accumulators(self):
        pass


def _adam_setup(opt_cls, name="train_step"):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 2))
    opt = opt_cls(learning_rate=0.1, parameters=net.parameters())

    def train_step(x, y):
        loss = F.cross_entropy(net(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # telemetry keys compile counts by step NAME: give each fixture its own
    train_step.__name__ = name
    step = CompiledStep(train_step, stateful=[net, opt])
    x = Tensor(np.random.RandomState(0).randn(16, 8).astype(np.float32))
    y = Tensor(np.random.RandomState(1).randint(0, 2, (16, 1)).astype(np.int64))
    return step, opt, x, y


# ---------------------------------------------------------------------------
# retrace-state-structure (+ the eager-init fix)
# ---------------------------------------------------------------------------
def test_retrace_state_structure_positive_lazy_adam():
    step, _, x, y = _adam_setup(_LazyAdam)
    report = step.analyze(x, y)
    findings = report.by_rule("retrace-state-structure")
    assert findings and findings[0].severity == "error"
    assert not report.ok
    # provenance: the exact state pytree paths that appear mid-step
    assert "accumulators" in findings[0].path
    assert any("moment1" in p for p in findings[0].data["added"])


def test_retrace_state_structure_clean_fixed_adam():
    step, opt, x, y = _adam_setup(paddle.optimizer.Adam)
    # the fix: accumulators exist before the first trace
    assert sorted(opt._accumulators) == ["beta1_pow", "beta2_pow",
                                         "moment1", "moment2"]
    report = step.analyze(x, y)
    assert not report.by_rule("retrace-state-structure")
    assert report.ok


def test_eager_accumulators_match_lazy_state():
    """Contract: eager init lands the SAME (name, shape, dtype) state one
    lazy step would — for every optimizer that declares specs."""
    from paddle_tpu.utils import unique_name

    for opt_cls, kw in [(paddle.optimizer.Momentum, {}),
                        (paddle.optimizer.Adam, {}),
                        (paddle.optimizer.AdamW, {}),
                        (paddle.optimizer.Adamax, {}),
                        (paddle.optimizer.Adadelta, {}),
                        (paddle.optimizer.RMSProp, {"centered": True}),
                        (paddle.optimizer.Lamb, {})]:
        with unique_name.guard():
            paddle.seed(0)
            lin_e = paddle.nn.Linear(4, 3)
            eager = opt_cls(learning_rate=0.1, parameters=lin_e.parameters(),
                            **kw)
            eager._ensure_accumulators()
        with unique_name.guard():
            paddle.seed(0)
            lin_l = paddle.nn.Linear(4, 3)
            lazy = opt_cls(learning_rate=0.1, parameters=lin_l.parameters(),
                           **kw)
            out = lin_l(Tensor(np.ones((2, 4), np.float32)))
            out.mean().backward()
            lazy.step()

        def sig(opt):
            return {(name, key, tuple(v.shape), str(v.dtype))
                    for name, store in opt._accumulators.items()
                    for key, v in store.items()}

        assert sig(eager) == sig(lazy), opt_cls.__name__


def test_adam_recompile_count_zero_regression():
    """BENCH acceptance: fixed Adam compiles exactly once over many steps."""
    step, _, x, y = _adam_setup(paddle.optimizer.Adam)
    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            step(x, y)
        s = telemetry.summary()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert s["compiles"] == {"train_step": 1}
    assert s["recompile_count"] == 0


def test_lint_vs_telemetry_crosscheck_adam():
    """The static 'will retrace' prediction must agree with the runtime
    recompile counter — both ways (pre-fix fixture vs fixed tree)."""
    lazy_step, _, x, y = _adam_setup(_LazyAdam, name="lazy_train_step")
    lazy_report = lazy_step.analyze(x, y)
    fixed_step, _, _, _ = _adam_setup(paddle.optimizer.Adam,
                                      name="fixed_train_step")
    fixed_report = fixed_step.analyze(x, y)

    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            lazy_step(x, y)
            fixed_step(x, y)
        summary = telemetry.summary()
    finally:
        telemetry.disable()
        telemetry.reset()

    (lazy_check,) = analysis.crosscheck_telemetry(lazy_report, summary)
    assert lazy_check["predicted_retrace"] is True
    assert lazy_check["observed_compiles"] == 2
    assert lazy_check["agrees"] is True

    (fixed_check,) = analysis.crosscheck_telemetry(fixed_report, summary)
    assert fixed_check["predicted_retrace"] is False
    assert fixed_check["observed_compiles"] == 1
    assert fixed_check["agrees"] is True


def test_analyze_leaves_eager_state_intact():
    """The abstract trace must not leak tracers into framework state: the
    step still runs (and numerically progresses) after analyze()."""
    step, opt, x, y = _adam_setup(paddle.optimizer.Adam)
    step.analyze(x, y)
    l0 = float(np.asarray(step(x, y)._value))
    l1 = float(np.asarray(step(x, y)._value))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0


# ---------------------------------------------------------------------------
# retrace-state-dtype
# ---------------------------------------------------------------------------
def _buffer_net(promote):
    net = paddle.nn.Linear(4, 4)
    net.register_buffer("scale", Tensor(jnp.ones((4,), jnp.float32)))

    def step(x):
        out = net(x) * net.scale
        new = net.scale._value * 0.5
        net.scale._value = new.astype(jnp.bfloat16) if promote else new
        return out.mean()

    return CompiledStep(step, stateful=[net])


def test_retrace_state_dtype_positive_and_clean():
    x = Tensor(np.ones((2, 4), np.float32))
    dirty = _buffer_net(promote=True).analyze(x)
    hits = dirty.by_rule("retrace-state-dtype")
    assert hits and "scale" in hits[0].path and "bfloat16" in hits[0].message
    clean = _buffer_net(promote=False).analyze(x)
    assert not clean.by_rule("retrace-state-dtype")


# ---------------------------------------------------------------------------
# retrace-static-scalar / retrace-static-value / retrace-shape-churn
# ---------------------------------------------------------------------------
def test_retrace_static_scalar_positive_and_clean():
    step = _plain_step(lambda x, k: x * k)
    x = Tensor(np.ones((4,), np.float32))
    report = step.analyze(x, 0.5)
    hits = report.by_rule("retrace-static-scalar")
    assert hits and hits[0].path == "args[1]"
    clean = _plain_step(lambda x, k: x * k).analyze(
        x, Tensor(np.float32(0.5)))
    assert not clean.by_rule("retrace-static-scalar")


def test_retrace_static_value_across_batches():
    step = _plain_step(lambda x, k: x * k)
    x = Tensor(np.ones((4,), np.float32))
    report = analysis.lint_step(step, x, 0.5, extra_args=[(x, 0.75)])
    hits = report.by_rule("retrace-static-value")
    assert hits and hits[0].severity == "error" and hits[0].path == "args[1]"
    same = analysis.lint_step(step, x, 0.5, extra_args=[(x, 0.5)])
    assert not same.by_rule("retrace-static-value")


def test_retrace_shape_churn_across_batches():
    step = _plain_step(lambda x: (x * 2).sum())
    b1 = Tensor(np.ones((8, 4), np.float32))
    b2 = Tensor(np.ones((6, 4), np.float32))
    report = analysis.lint_step(step, b1, extra_args=[(b2,)])
    hits = report.by_rule("retrace-shape-churn")
    assert hits and hits[0].path == "args[0]"
    assert "[8, 4]" in hits[0].message and "[6, 4]" in hits[0].message
    same = analysis.lint_step(step, b1, extra_args=[(b1,)])
    assert not same.by_rule("retrace-shape-churn")


def test_retrace_weak_type():
    step = _plain_step(lambda x, s: x * s)
    x = Tensor(np.ones((4,), np.float32))
    report = step.analyze(x, Tensor(jnp.asarray(2.0)))  # weakly typed scalar
    hits = report.by_rule("retrace-weak-type")
    assert hits and hits[0].path == "args[1]"
    clean = step.analyze(x, Tensor(jnp.asarray(2.0, jnp.float32)))
    assert not clean.by_rule("retrace-weak-type")


# ---------------------------------------------------------------------------
# host-sync-callback
# ---------------------------------------------------------------------------
def test_host_sync_callback_positive_and_clean():
    def noisy(x):
        arr = x._value if isinstance(x, Tensor) else x
        arr = jax.pure_callback(
            lambda a: np.asarray(a) * 2.0,
            jax.ShapeDtypeStruct(arr.shape, arr.dtype), arr)
        return arr.sum()

    report = _plain_step(noisy).analyze(Tensor(np.ones((4,), np.float32)))
    hits = report.by_rule("host-sync-callback")
    assert hits and hits[0].severity == "warning"
    assert "pure_callback" in hits[0].message
    assert re.match(r".+\.py:\d+$", hits[0].where)  # eqn provenance

    clean = _plain_step(lambda x: (x * 2).sum()).analyze(
        Tensor(np.ones((4,), np.float32)))
    assert not clean.by_rule("host-sync-callback")


# ---------------------------------------------------------------------------
# hbm-undonated-input + donate_inputs pytree paths
# ---------------------------------------------------------------------------
def test_undonated_input_finding_names_exact_path():
    step = _plain_step(lambda a, b: a * 2 + b.sum())
    big = Tensor(jnp.ones((512, 513), jnp.float32))  # aliasable to output
    small = Tensor(jnp.ones((8,), jnp.float32))
    report = step.analyze(big, small)
    hits = report.by_rule("hbm-undonated-input")
    assert len(hits) == 1 and hits[0].path == "args[0]"
    assert 'donate_inputs=["args[0]"]' in hits[0].hint


def test_undonated_input_clean_when_donated():
    step = _plain_step(lambda a, b: a * 2 + b.sum(), donate_inputs=True)
    report = step.analyze(Tensor(jnp.ones((512, 513), jnp.float32)),
                          Tensor(jnp.ones((8,), jnp.float32)))
    assert not report.by_rule("hbm-undonated-input")


def test_donate_inputs_by_path_consumes_only_named_leaf():
    """The finding's path string round-trips into donate_inputs=[…]: the
    named leaf is donated (buffer deleted), the rest stay alive."""
    step = _plain_step(lambda a, b: a * 2 + b.sum(),
                       donate_inputs=["args[0]"])
    xa = jnp.ones((256, 256), jnp.float32)
    xb = jnp.ones((8,), jnp.float32)
    out = step(Tensor(xa), Tensor(xb))
    np.asarray(out._value)
    assert xa.is_deleted()
    assert not xb.is_deleted()
    # and the lint sees the path as donated
    report = step.analyze(Tensor(jnp.ones((256, 256), jnp.float32)),
                          Tensor(jnp.ones((8,), jnp.float32)))
    assert not report.by_rule("hbm-undonated-input")


# ---------------------------------------------------------------------------
# hbm-const-folded
# ---------------------------------------------------------------------------
def test_const_folded_positive_and_clean():
    big = jnp.ones((600, 600), jnp.float32)  # ~1.4 MiB > 1 MiB floor

    report = _plain_step(lambda x: (x @ big).sum()).analyze(
        Tensor(np.ones((2, 600), np.float32)))
    hits = report.by_rule("hbm-const-folded")
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["nbytes"] == 600 * 600 * 4

    small = jnp.ones((4, 4), jnp.float32)
    clean = _plain_step(lambda x: (x @ small).sum()).analyze(
        Tensor(np.ones((2, 4), np.float32)))
    assert not clean.by_rule("hbm-const-folded")


# ---------------------------------------------------------------------------
# hbm-f64-promotion
# ---------------------------------------------------------------------------
def test_f64_promotion_positive_and_clean():
    jax.config.update("jax_enable_x64", True)
    try:
        report = _plain_step(
            lambda x: x._value.astype(jnp.float64).sum()).analyze(
            Tensor(np.ones((4,), np.float32)))
        hits = report.by_rule("hbm-f64-promotion")
        assert hits and "float64" in hits[0].message
    finally:
        jax.config.update("jax_enable_x64", False)
    clean = _plain_step(lambda x: (x * 2).sum()).analyze(
        Tensor(np.ones((4,), np.float32)))
    assert not clean.by_rule("hbm-f64-promotion")


# ---------------------------------------------------------------------------
# tpu-gather-scatter
# ---------------------------------------------------------------------------
def test_gather_scatter_positive_and_clean():
    idx = jnp.asarray([0, 2, 1], jnp.int32)

    report = _plain_step(
        lambda x: jnp.take(x._value, idx, axis=0).sum()).analyze(
        Tensor(np.ones((4, 3), np.float32)))
    hits = report.by_rule("tpu-gather-scatter")
    assert hits and hits[0].severity == "info"
    assert hits[0].data["count"] >= 1
    assert re.match(r".+\.py:\d+$", hits[0].where)

    clean = _plain_step(lambda x: (x * 2 + 1).mean()).analyze(
        Tensor(np.ones((4, 3), np.float32)))
    assert not clean.by_rule("tpu-gather-scatter")


# ---------------------------------------------------------------------------
# rule silencing
# ---------------------------------------------------------------------------
def test_ignore_silences_rule():
    step, _, x, y = _adam_setup(_LazyAdam)
    report = analysis.lint_step(step, x, y,
                                ignore=("retrace-state-structure",))
    assert not report.by_rule("retrace-state-structure")


def test_env_ignore_silences_rule(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LINT_IGNORE",
                       "retrace-state-structure, tpu-gather-scatter")
    step, _, x, y = _adam_setup(_LazyAdam)
    report = step.analyze(x, y)
    assert not report.by_rule("retrace-state-structure")
    assert not report.by_rule("tpu-gather-scatter")


# ---------------------------------------------------------------------------
# framework wiring
# ---------------------------------------------------------------------------
def test_warn_on_compile_opt_in():
    step, _, x, y = _adam_setup(_LazyAdam)
    analysis.enable_lint_on_compile(True)
    try:
        with pytest.warns(RuntimeWarning, match=r"graph-lint.*retrace"):
            step(x, y)
        # once per step object: subsequent compiles don't re-warn
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            step(x, y)
    finally:
        analysis.enable_lint_on_compile(False)


def test_lint_on_compile_disabled_is_silent():
    step, _, x, y = _adam_setup(_LazyAdam)
    assert not analysis.lint_on_compile_enabled()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        step(x, y)


def test_hapi_prepare_graph_lint_warns_at_first_fit():
    class _DS:
        def __getitem__(self, i):
            r = np.random.RandomState(i)
            return (r.randn(8).astype(np.float32),
                    np.asarray([i % 2], np.int64))

        def __len__(self):
            return 16

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 4))
    model = paddle.Model(net)
    from paddle_tpu.nn import CrossEntropyLoss

    model.prepare(_LazyAdam(learning_rate=0.1, parameters=net.parameters()),
                  CrossEntropyLoss(), graph_lint=True)
    with pytest.warns(RuntimeWarning, match=r"graph-lint.*retrace"):
        model.fit(_DS(), batch_size=8, epochs=1, verbose=0)
    assert model._graph_linted


def test_engine_graph_lint_runs_once_at_first_fit():
    from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

    paddle.seed(0)
    net = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    mesh = ProcessMesh(np.arange(len(jax.devices())), dim_names=["dp"])
    eng = Engine(model=net, loss=loss_fn, optimizer=opt, process_mesh=mesh,
                 graph_lint=True)
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    eng.fit(list(zip(x, y)), batch_size=8, epochs=1, prefetch=0)
    assert eng._graph_linted


# ---------------------------------------------------------------------------
# CLI: JSONL round-trip + fixture gate
# ---------------------------------------------------------------------------
def _load_cli():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "graph_lint.py")
    spec = importlib.util.spec_from_file_location("graph_lint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_jsonl_round_trip(tmp_path, capsys):
    cli = _load_cli()
    out = tmp_path / "findings.jsonl"
    rc = cli.main(["--models", "mlp", "--jsonl", str(out),
                   "--fail-on", "never"])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines() if l]
    assert lines, "mlp zoo entry produced no findings (gather is expected)"
    for d in lines:
        assert d["model"] == "mlp"
        f = analysis.Finding.from_dict(d)
        # forward-compatible round trip: unknown top-level keys (the CLI's
        # `model` side-band here) are preserved, not dropped
        assert f.extra == {"model": "mlp"}
        assert f.as_dict() == d
    table = capsys.readouterr().out
    assert "mlp_train_step" in table and "graph lint:" in table


def test_cli_adam_lazy_fixture_fails_the_gate(tmp_path):
    cli = _load_cli()
    out = tmp_path / "lazy.jsonl"
    rc = cli.main(["--models", "mlp", "--fixture", "adam-lazy",
                   "--jsonl", str(out)])
    assert rc == 1
    rules = {json.loads(l)["rule"] for l in out.read_text().splitlines() if l}
    assert "retrace-state-structure" in rules


def test_cli_clean_zoo_passes_the_gate():
    cli = _load_cli()
    assert cli.main(["--models", "mlp"]) == 0
