"""Rank script for the elastic kill-rejoin test (round-3 VERDICT missing #4).

2-rank DP training with: TCPStore-backed heartbeats (ElasticManager), a
background watch thread (the elastic-agent role: a rank hung inside a
collective whose peer died cannot poll — the agent must kill it),
auto_checkpoint epoch resume, and a mid-epoch SIGKILL of rank 1 on the
first attempt. The launcher's --max_restart respawns the job; training
resumes from the last checkpoint; the final state must equal an
uninterrupted run's.
"""
import json
import os
import signal
import threading
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.elastic import ElasticManager, ElasticStatus
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.incubate.checkpoint import auto_checkpoint as acp

out_dir = os.environ["LAUNCH_TEST_OUT"]
kill_marker = os.path.join(out_dir, "killed.marker")
do_kill = os.environ.get("ELASTIC_TEST_KILL") == "1"

env = dist.init_parallel_env()
rank, world = env.rank, env.world_size
assert world == 2, world
ckpt_dir = os.path.join(out_dir, f"acp_rank{rank}")

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("dp",))

em = ElasticManager(timeout=4.0)
assert em._store is not None, "test requires the TCPStore heartbeat backend"
em.register()
_done = threading.Event()


def _agent():
    """Heartbeat + dead-peer watch. os._exit on RESTART: the trainer may be
    blocked inside a collective with the dead peer and can never return."""
    while not _done.is_set():
        try:
            em.heartbeat()
            if em.watch() == ElasticStatus.RESTART:
                print(f"rank {rank}: peer failure detected via store watch",
                      flush=True)
                os._exit(23)
        except Exception:
            pass
        time.sleep(0.5)


threading.Thread(target=_agent, daemon=True).start()

paddle.seed(0)
lin = paddle.nn.Linear(8, 4)
opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=lin.parameters())
acp.reset()
acp.register(model=lin, optimizer=opt)

from paddle_tpu.jit.functionalize import CompiledStep


def step(x):
    loss = lin(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss


cs = CompiledStep(step, stateful=[lin, opt], donate_state=False)

epochs_run = []
losses = []
for epoch in acp.train_epoch_range(4, save_dir=ckpt_dir):
    epochs_run.append(epoch)
    for it in range(3):
        # deterministic per-(epoch, iter, rank) data
        rng = np.random.RandomState(1000 * epoch + 10 * it + rank)
        x_local = rng.randn(2, 8).astype(np.float32)
        x = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("dp")), x_local, (4, 8))

        if (do_kill and rank == 1 and epoch == 1 and it == 1
                and not os.path.exists(kill_marker)):
            with open(kill_marker, "w") as f:
                f.write("killed")
            os.kill(os.getpid(), signal.SIGKILL)  # simulated node failure

        loss = cs(Tensor(x))
        losses.append(float(np.asarray(jax.device_get(loss._value))))

_done.set()
try:
    em.exit(completed=True)
except Exception:
    # rank 0 hosts the store in-process; if it already exited, the final
    # status write has nowhere to land — not a training failure
    pass
attempt = "restarted" if os.path.exists(kill_marker) else "clean"
w = np.asarray(jax.device_get(lin.weight._value)).ravel().tolist()
b = np.asarray(jax.device_get(lin.bias._value)).ravel().tolist()
with open(os.path.join(out_dir, f"final_rank{rank}.json"), "w") as f:
    json.dump({"rank": rank, "attempt": attempt, "epochs": epochs_run,
               "w": w, "b": b, "last_loss": losses[-1]}, f)
print(f"rank {rank} DONE epochs={epochs_run}", flush=True)
