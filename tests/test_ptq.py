"""Post-training quantization (round-5 VERDICT item 7): KL threshold
math, observer algos, per-channel weight quantization, end-to-end PTQ'd
LeNet within 1% top-1 of fp32 on synthetic eval data.
Reference: fluid/contrib/slim/quantization/post_training_quantization.py,
cal_kl_threshold.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.quantization import (
    PostTrainingQuantization,
    QuantizedInferenceConv2D,
    QuantizedInferenceLinear,
    cal_kl_threshold,
)


def _np(t):
    return np.asarray(t._value)


def test_cal_kl_threshold_prefers_bulk_over_outlier():
    """A distribution with 99.9% of mass near zero and a lone outlier:
    the KL threshold must clip well below the outlier."""
    rng = np.random.RandomState(0)
    hist = np.zeros(2048)
    # smoothly decaying bulk: coarse 16-bin buckets cannot reconstruct it,
    # so keeping the full range (for one outlier) must cost KL
    hist[:128] = np.exp(-np.arange(128) / 20.0) * 1000.0 * \
        (1.0 + 0.2 * rng.rand(128))
    hist[-1] = 1.0             # outlier at the far end
    width = 0.01
    thr = cal_kl_threshold(hist, width, 8)
    assert thr < 0.5 * width * 2048, thr
    assert thr >= width * 127   # must still cover the bulk


def test_observer_algos():
    from paddle_tpu.quantization import _Observer

    data = [np.random.RandomState(i).randn(256).astype(np.float32)
            for i in range(4)]
    for algo in ("abs_max", "min_max", "avg", "hist", "KL"):
        obs = _Observer(algo)
        for d in data:
            obs.observe(d)
        thr = obs.threshold(8)
        gmax = max(float(np.abs(d).max()) for d in data)
        assert 0 < thr <= gmax * 1.01, (algo, thr, gmax)
    # abs_max is exactly the global max; avg is below it
    oa, ov = _Observer("abs_max"), _Observer("avg")
    for d in data:
        oa.observe(d)
        ov.observe(d)
    assert oa.threshold() == pytest.approx(gmax)
    assert ov.threshold() < oa.threshold()


def test_channel_wise_weight_quantization_roundtrip():
    paddle.seed(0)
    lin = paddle.nn.Linear(16, 8)
    # give channels very different scales: per-channel must track both
    w = _np(lin.weight).copy()
    w[:, 0] *= 100.0
    lin.weight._value = __import__("jax.numpy", fromlist=["asarray"]).asarray(w)
    q = QuantizedInferenceLinear(lin, act_threshold=3.0)
    wq = _np(q.weight_int8)
    assert wq.dtype == np.int8
    deq = wq.astype(np.float32) * _np(q.weight_scale)
    err = np.abs(deq - w).max(axis=0) / (np.abs(w).max(axis=0) + 1e-9)
    assert err.max() < 0.01, err.max()  # int8 per-channel: <1% of range


def _lenet_and_data():
    from paddle_tpu.vision.models import LeNet

    paddle.seed(7)
    model = LeNet(num_classes=10)
    rng = np.random.RandomState(0)
    # synthetic "digits": class-dependent blobs so fp32 accuracy is high
    xs, ys = [], []
    for i in range(400):
        c = i % 10
        img = rng.randn(1, 28, 28).astype(np.float32) * 0.3
        img[0, 2 + 2 * (c % 5):6 + 2 * (c % 5), 4 + 2 * (c // 5):10] += 2.0
        xs.append(img)
        ys.append(c)
    xs = np.stack(xs)
    ys = np.array(ys, np.int64)
    # quick train to a usable accuracy
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    from paddle_tpu.jit.functionalize import CompiledStep

    def step(x, y):
        import paddle_tpu.nn.functional as F

        loss = F.cross_entropy(model(x), y.reshape([-1, 1])).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cstep = CompiledStep(step, stateful=[model, opt], donate_state=False)
    for ep in range(6):
        for i in range(0, 400, 50):
            cstep(Tensor(xs[i:i + 50]), Tensor(ys[i:i + 50]))
    return model, xs, ys


def _top1(model, xs, ys):
    model.eval()
    preds = []
    for i in range(0, len(xs), 100):
        logits = model(Tensor(xs[i:i + 100]))
        preds.append(np.argmax(_np(logits), -1))
    return float((np.concatenate(preds) == ys).mean())


def test_ptq_lenet_within_one_percent():
    model, xs, ys = _lenet_and_data()
    acc_fp32 = _top1(model, xs, ys)
    assert acc_fp32 > 0.9, f"fp32 baseline too weak ({acc_fp32})"

    calib = [(Tensor(xs[i:i + 50]),) for i in range(0, 200, 50)]
    ptq = PostTrainingQuantization(model=model, data_loader=calib,
                                   algo="KL")
    qmodel = ptq.quantize()
    # every Linear/Conv2D was swapped for its int8 twin
    kinds = [type(s).__name__ for _, s in qmodel.named_sublayers()]
    assert "QuantizedInferenceLinear" in kinds
    assert "QuantizedInferenceConv2D" in kinds
    assert not any(k in ("Linear", "Conv2D") for k in kinds), kinds

    acc_q = _top1(qmodel, xs, ys)
    assert acc_q >= acc_fp32 - 0.01, (acc_fp32, acc_q)


def test_ptq_rejects_bad_algo():
    with pytest.raises(ValueError):
        PostTrainingQuantization(model=paddle.nn.Linear(2, 2),
                                 data_loader=[], algo="magic")


def test_ptq_saves_through_jit(tmp_path):
    model, xs, _ = _lenet_and_data()
    calib = [(Tensor(xs[:50]),)]
    ptq = PostTrainingQuantization(model=model, data_loader=calib,
                                   algo="abs_max")
    qmodel = ptq.quantize()
    ref = _np(qmodel(Tensor(xs[:8])))
    from paddle_tpu.jit.save_load import InputSpec

    path = str(tmp_path / "qlenet")
    ptq.save_quantized_model(
        path, input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    loaded = paddle.jit.load(path)
    out = _np(loaded(Tensor(xs[:8])))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ptq_skips_unobserved_layer_with_warning():
    """Review regression: a layer the calibration batches never exercise
    must stay fp32 (not get a zero threshold that collapses activations)."""
    import warnings

    class TwoHeads(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.used = paddle.nn.Linear(4, 4)
            self.unused = paddle.nn.Linear(4, 4)

        def forward(self, x):
            return self.used(x)

    paddle.seed(3)
    m = TwoHeads()
    x = Tensor(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    ref = np.asarray(m(x)._value)
    ptq = PostTrainingQuantization(model=m, data_loader=[(x,)],
                                   algo="abs_max")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        qm = ptq.quantize()
    assert any("unused" in str(x.message) for x in w)
    kinds = {n: type(s).__name__ for n, s in qm.named_sublayers()}
    assert kinds["used"] == "QuantizedInferenceLinear"
    assert kinds["unused"] == "Linear"        # untouched
    got = np.asarray(qm(x)._value)
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)


def test_weight_only_quantization():
    """Reference WeightQuantization surface: int8 weights, fp32
    activations, no calibration pass needed."""
    from paddle_tpu.quantization import WeightQuantization

    paddle.seed(5)
    m = paddle.nn.Sequential(paddle.nn.Conv2D(1, 4, 3, padding=1),
                             paddle.nn.ReLU(),
                             paddle.nn.Flatten(),
                             paddle.nn.Linear(4 * 8 * 8, 10))
    x = Tensor(np.random.RandomState(0).randn(2, 1, 8, 8).astype(np.float32))
    ref = _np(m(x))
    qm = WeightQuantization(model=m).quantize_weight_to_int()
    kinds = [type(s).__name__ for _, s in qm.named_sublayers()]
    assert "QuantizedInferenceLinear" in kinds
    assert "QuantizedInferenceConv2D" in kinds
    got = _np(qm(x))
    # int8 weights only: outputs stay within quantization error of fp32
    assert np.abs(got - ref).max() < 0.05 * (np.abs(ref).max() + 1e-6)
