"""Shard lint (ISSUE 7): abstract SPMD propagation, the spmd-* rules, the
predicted-vs-HLO-measured comm crosscheck on the MULTICHIP zoo configs,
Engine wiring (+ comm-aware plan tie-break), the SARIF/JSONL exports, and
the ignore-list / Finding round-trip satellites.

Acceptance (ISSUE 7): on the dp×mp and MoE MULTICHIP configs the
predicted per-axis collective bytes agree with devprof's HLO-measured
``comm.bytes.<axis>`` within 10% — exactly, for explicit shard_map
collectives — via the extended crosscheck.
"""
import importlib.util
import json
import os
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import shard_lint
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.profiler import devprof, telemetry
from paddle_tpu.utils import unique_name

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _load_cli():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "shard_lint.py")
    spec = importlib.util.spec_from_file_location("shard_lint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()
    yield
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()


def _dp_mp_step(fixture=None):
    cli = _load_cli()
    return cli.build_dp_mp(fixture=fixture)


# ---------------------------------------------------------------------------
# propagation primitives
# ---------------------------------------------------------------------------

def test_spec_from_sharding_shapes():
    mesh = build_mesh({"dp": 2, "mp": 2})
    sh = NamedSharding(mesh, P("dp", None))
    assert shard_lint.spec_from_sharding(sh, 2) == (("dp",), ())
    # trailing dims beyond the spec are replicated
    assert shard_lint.spec_from_sharding(sh, 3) == (("dp",), (), ())
    # multi-axis dims survive
    sh2 = NamedSharding(mesh, P(("dp", "mp"),))
    assert shard_lint.spec_from_sharding(sh2, 1) == ((("dp", "mp"))[0:2],)
    assert shard_lint.spec_from_sharding(None, 2) == ((), ())


def test_dot_contraction_predicts_allreduce_local_bytes():
    """x[16,32]@(dp,·) · w[32,8] sharded (mp,·): contraction over mp →
    all-reduce over mp of the LOCAL [8,8] result (matches what the
    partitioned HLO reports)."""
    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x, w):
        return (x._value @ w._value).sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((16, 32), jnp.float32),
                              NamedSharding(mesh, P("dp", "mp"))))
    w = Tensor(jax.device_put(jnp.ones((32, 8), jnp.float32),
                              NamedSharding(mesh, P("mp", None))))
    sa = shard_lint.analyze_sharding(step, x, w, mesh=mesh)
    by_axis = sa.bytes_by_axis()
    # [16,8] f32 logical, dp shards dim0 → local 8*8*4 = 256 B, ring
    # factor 2(S−1)/S = 1 at S=2
    mm = [p for p in sa.predicted if p.prim == "dot_general"]
    assert mm and mm[0].op == "all-reduce" and mm[0].axes == ("mp",)
    assert mm[0].bytes == 256.0
    assert by_axis["mp"] >= 256.0


def test_constraint_removal_predicts_allgather():
    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x):
        y = jax.lax.with_sharding_constraint(
            x._value, NamedSharding(mesh, P(None, None)))
        return (y * 2).sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((8, 16), jnp.float32),
                              NamedSharding(mesh, P("dp", None))))
    sa = shard_lint.analyze_sharding(step, x, mesh=mesh)
    ag = [p for p in sa.predicted if p.op == "all-gather"]
    assert ag and ag[0].axes == ("dp",)
    # gathered result is the full [8,16] f32 = 512 B; (S−1)/S = 1/2
    assert ag[0].bytes == 256.0
    assert sa.reshards and sa.reshards[0].kind == "constraint"


def test_scan_multiplies_collective_counts():
    """A ppermute inside lax.scan over T ticks is predicted T times (the
    pipeline schedule's tick loop)."""
    from jax import lax

    mesh = build_mesh({"pp": 2})
    T = 5

    def fn(x):
        def body(c, _):
            return lax.ppermute(c, "pp", [(0, 1), (1, 0)]), ()

        def inner(v):
            out, _ = lax.scan(body, v, jnp.arange(T))
            return out

        return jax.shard_map(inner, mesh=mesh, in_specs=P("pp"),
                             out_specs=P("pp"), check_vma=False)(
            x._value).sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((8, 4), jnp.float32),
                              NamedSharding(mesh, P("pp", None))))
    sa = shard_lint.analyze_sharding(step, x, mesh=mesh)
    st = sa.collectives.by_axis["pp"]
    assert st["prims"]["collective-permute"] >= T
    # local block [4,4] f32 = 64 B per hop
    assert st["bytes"] >= T * 64.0


def test_analyze_returns_none_without_mesh():
    step = CompiledStep(lambda x: (x._value * 2).sum(), stateful=(),
                        donate_state=False)
    x = Tensor(np.ones((4, 4), np.float32))
    assert shard_lint.analyze_sharding(step, x) is None


# ---------------------------------------------------------------------------
# ACCEPTANCE: predicted vs HLO-measured per-axis bytes (dp×mp + MoE zoo)
# ---------------------------------------------------------------------------

@needs_8_devices
def test_dp_mp_zoo_predicted_matches_measured_within_10pct():
    step, batch, mesh, measurable = _dp_mp_step()
    assert measurable
    report = analysis.lint_step(step, *batch, mesh=mesh)
    # the clean config lints with ZERO spmd findings
    assert not [f for f in report if f.rule.startswith("spmd-")], \
        [str(f) for f in report]
    sa = report.sharding
    assert sa is not None and sa.comm_bytes > 0
    rep = devprof.device_report(step, *batch, register=False)
    rows = analysis.crosscheck_comm(sa, rep)
    assert rows, "no axes on either side"
    for r in rows:
        assert r["agrees"], rows
        assert r["measured_bytes"] > 0
        assert abs(r["predicted_bytes"] - r["measured_bytes"]) \
            <= 0.10 * r["measured_bytes"]
    axes = {r["axis"] for r in rows}
    assert "dp" in axes and "mp" in axes


@needs_8_devices
def test_moe_zoo_predicted_exact_for_explicit_shard_map():
    cli = _load_cli()
    step, batch, mesh, measurable = cli.build_moe()
    assert measurable
    report = analysis.lint_step(step, *batch, mesh=mesh)
    assert not [f for f in report if f.rule.startswith("spmd-")]
    sa = report.sharding
    rep = devprof.device_report(step, *batch, register=False)
    rows = analysis.crosscheck_comm(sa, rep)
    (row,) = [r for r in rows if r["axis"] == "ep"]
    # EXACT: every collective is an explicit shard_map op priced by the
    # same ring model devprof uses
    assert row["predicted_bytes"] == row["measured_bytes"] > 0
    assert row["agrees"]
    prims = sa.collectives.by_axis["ep"]["prims"]
    assert prims.get("all-to-all", 0) >= 2  # dispatch + combine


@needs_8_devices
def test_crosscheck_comm_pulls_telemetry_counters():
    """measured=None joins against the comm.bytes.<axis> counters the
    devprof harvest registered — the CI-facing accuracy loop."""
    step, batch, mesh, _ = _dp_mp_step()
    sa = shard_lint.analyze_sharding(step, *batch, mesh=mesh)
    telemetry.enable()
    devprof.device_report(step, *batch)  # registers counters
    rows = analysis.crosscheck_comm(sa)  # ← telemetry pull
    assert {r["axis"] for r in rows} >= {"dp", "mp"}
    assert all(r["agrees"] for r in rows), rows


def test_crosscheck_comm_disagreement_and_one_sided_axes():
    rows = analysis.crosscheck_comm(
        {"dp": 1000.0, "mp": 500.0}, {"dp": 1099.0, "sep": 10.0})
    by = {r["axis"]: r for r in rows}
    assert by["dp"]["agrees"]  # within 10%
    assert by["dp"]["ratio"] == pytest.approx(1000.0 / 1099.0)
    assert not by["mp"]["agrees"] and by["mp"]["measured_bytes"] == 0.0
    assert not by["sep"]["agrees"] and by["sep"]["predicted_bytes"] == 0.0
    # custom tolerance
    loose = analysis.crosscheck_comm({"dp": 1000.0}, {"dp": 1500.0},
                                     rtol=0.6)
    assert loose[0]["agrees"]


# ---------------------------------------------------------------------------
# spmd-* rules
# ---------------------------------------------------------------------------

@needs_8_devices
def test_implicit_resharding_flags_mismatched_constraint_fixture():
    step, batch, mesh, _ = _dp_mp_step(fixture="mismatched-constraint")
    report = analysis.lint_step(step, *batch, mesh=mesh)
    hits = report.by_rule("spmd-implicit-resharding")
    assert hits and all(f.severity == "error" for f in hits)
    # the constraint-site finding carries the axis, bytes, and a
    # copy-pasteable constraint hint
    con = [f for f in hits if f.data.get("kind") == "constraint"]
    assert con, [f.data for f in hits]
    f = con[0]
    assert f.data["axis"] in ("dp", "mp", "dp+mp")
    assert f.data["bytes"] > 0
    assert "with_sharding_constraint" in f.hint
    assert "NamedSharding(mesh, P(" in f.hint
    assert not report.ok


def test_sharding_mismatch_flags_input_first_use():
    """An input staged sharded over the wrong dim for its first use (a
    constraint demanding another layout) = silent full reshard at step
    entry."""
    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x):
        y = jax.lax.with_sharding_constraint(
            x._value, NamedSharding(mesh, P("dp", None)))
        return (y * y).sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((8, 16), jnp.float32),
                              NamedSharding(mesh, P("mp", None))))
    report = analysis.lint_step(step, x, mesh=mesh)
    hits = report.by_rule("spmd-sharding-mismatch")
    assert hits and hits[0].severity == "error"
    assert hits[0].path == "args[0]"
    assert "device_put" in hits[0].hint
    # input-valued conflicts are NOT double-reported by the generic rule
    assert not report.by_rule("spmd-implicit-resharding")


def test_replicated_optimizer_state_positive_and_clean():
    mesh = build_mesh({"dp": 2, "mp": 2})
    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(64, 64)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())

    def train_step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[net, opt], donate_state=True)
    mk = lambda: Tensor(jax.device_put(  # noqa: E731
        jnp.ones((8, 64), jnp.float32), NamedSharding(mesh, P("dp", None))))
    # accumulators are replicated; drop the byte floor so the tiny model
    # trips the rule
    report = analysis.lint_step(step, mk(), mk(), mesh=mesh,
                                config={"zero_min_bytes": 1024})
    hits = report.by_rule("spmd-replicated-optimizer-state")
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["axis"] == "dp"
    assert hits[0].data["bytes"] > 0
    assert "group_sharded_parallel" in hits[0].hint
    assert "state['optimizers']" in hits[0].path
    # default 1 MiB floor: the same tiny model stays silent
    clean = analysis.lint_step(step, mk(), mk(), mesh=mesh)
    assert not clean.by_rule("spmd-replicated-optimizer-state")


def test_comm_bound_step_threshold():
    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x):
        # nearly pure communication: gather a sharded value, no compute
        y = jax.lax.with_sharding_constraint(
            x._value, NamedSharding(mesh, P(None, None)))
        return y.sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((64, 64), jnp.float32),
                              NamedSharding(mesh, P("dp", "mp"))))
    report = analysis.lint_step(step, x, mesh=mesh,
                                config={"comm_bound_fraction": 0.05})
    hits = report.by_rule("spmd-comm-bound-step")
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["comm_fraction"] > 0.05
    assert hits[0].data["bytes_by_axis"]
    # default threshold: the dp×mp training zoo config is NOT comm-bound
    step2, batch2, mesh2, _ = _dp_mp_step()
    rep2 = analysis.lint_step(step2, *batch2, mesh=mesh2)
    assert not rep2.by_rule("spmd-comm-bound-step")


def test_spmd_rules_silent_without_mesh():
    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())

    def train_step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[net, opt], donate_state=True)
    x = Tensor(np.ones((4, 8), np.float32))
    report = analysis.lint_step(step, x, x,
                                config={"zero_min_bytes": 1})
    assert not [f for f in report if f.rule.startswith("spmd-")]


# ---------------------------------------------------------------------------
# ignore= / PADDLE_TPU_LINT_IGNORE edge cases (satellite)
# ---------------------------------------------------------------------------

def _tiny_step():
    return CompiledStep(lambda x: (x._value * 2).sum(), stateful=(),
                        donate_state=False), Tensor(np.ones((4,),
                                                            np.float32))


def test_unknown_ignore_id_warns_once():
    from paddle_tpu.analysis import graph_lint as gl

    gl._WARNED_UNKNOWN_IGNORE.discard("no-such-rule")
    step, x = _tiny_step()
    with pytest.warns(RuntimeWarning, match=r"unknown rule id "
                                            r"'no-such-rule'"):
        analysis.lint_step(step, x, ignore=("no-such-rule",))
    # second occurrence is silent (once per process, not per lint)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        analysis.lint_step(step, x, ignore=("no-such-rule",))


def test_env_ignore_comma_whitespace_parsing(monkeypatch):
    from paddle_tpu.analysis.graph_lint import _env_ignore

    monkeypatch.setenv("PADDLE_TPU_LINT_IGNORE",
                       " tpu-gather-scatter ,  ,hbm-const-folded,")
    assert _env_ignore() == ("tpu-gather-scatter", "hbm-const-folded")
    monkeypatch.setenv("PADDLE_TPU_LINT_IGNORE", "")
    assert _env_ignore() == ()


def test_env_unknown_id_warns_with_source(monkeypatch):
    from paddle_tpu.analysis import graph_lint as gl

    gl._WARNED_UNKNOWN_IGNORE.discard("env-typo-rule")
    monkeypatch.setenv("PADDLE_TPU_LINT_IGNORE", "env-typo-rule")
    step, x = _tiny_step()
    with pytest.warns(RuntimeWarning,
                      match=r"PADDLE_TPU_LINT_IGNORE.*env-typo-rule"):
        analysis.lint_step(step, x)


def test_per_call_and_env_ignores_union(monkeypatch):
    """Per-call ignore works with no env set; the env var ADDS to (never
    replaces) the per-call list."""
    idx = jnp.asarray([0, 2, 1], jnp.int32)
    step = CompiledStep(
        lambda x: jnp.take(x._value, idx, axis=0).sum(),
        stateful=(), donate_state=False)
    x = Tensor(np.ones((4, 3), np.float32))
    monkeypatch.delenv("PADDLE_TPU_LINT_IGNORE", raising=False)
    assert analysis.lint_step(step, x).by_rule("tpu-gather-scatter")
    assert not analysis.lint_step(
        step, x, ignore=("tpu-gather-scatter",)).by_rule(
        "tpu-gather-scatter")
    # env silences one rule, per-call another — both apply (union)
    big = jnp.ones((600, 600), jnp.float32)
    step2 = CompiledStep(lambda x: (jnp.take(x._value, idx, axis=0).sum()
                                    + big.sum()),
                         stateful=(), donate_state=False)
    monkeypatch.setenv("PADDLE_TPU_LINT_IGNORE", "hbm-const-folded")
    rep = analysis.lint_step(step2, x, ignore=("tpu-gather-scatter",))
    assert not rep.by_rule("tpu-gather-scatter")
    assert not rep.by_rule("hbm-const-folded")


# ---------------------------------------------------------------------------
# Finding round-trip with the new payloads (satellite)
# ---------------------------------------------------------------------------

def test_finding_round_trips_axis_bytes_payload_and_unknown_keys():
    d = {"rule": "spmd-implicit-resharding", "severity": "error",
         "message": "m", "step": "s", "path": "", "where": "f.py:3",
         "hint": "h", "data": {"axis": "mp", "bytes": 4096.0,
                               "op": "all-gather"},
         "model": "dp-mp", "future_field": [1, 2]}
    f = analysis.Finding.from_dict(d)
    assert f.data["axis"] == "mp" and f.data["bytes"] == 4096.0
    assert f.extra == {"model": "dp-mp", "future_field": [1, 2]}
    assert f.as_dict() == d  # lossless, unknown keys preserved
    f2 = analysis.Finding.from_dict(f.as_dict())
    assert f2 == f


@needs_8_devices
def test_shard_lint_jsonl_reloads_losslessly(tmp_path):
    cli = _load_cli()
    out = tmp_path / "findings.jsonl"
    rc = cli.main(["--models", "dp-mp", "--fixture",
                   "mismatched-constraint", "--jsonl", str(out)])
    assert rc == 1  # the injected defect fails the gate
    lines = [json.loads(l) for l in out.read_text().splitlines() if l]
    assert lines
    for d in lines:
        f = analysis.Finding.from_dict(d)
        assert f.as_dict() == d
    rules = {d["rule"] for d in lines}
    assert "spmd-implicit-resharding" in rules


# ---------------------------------------------------------------------------
# CLI: zoo gate + SARIF
# ---------------------------------------------------------------------------

@needs_8_devices
def test_cli_clean_zoo_passes_the_gate(capsys):
    cli = _load_cli()
    assert cli.main(["--models", "dp-mp", "moe"]) == 0
    out = capsys.readouterr().out
    assert "predicted collectives" in out
    assert "shard lint: 0 error(s)" in out


@needs_8_devices
def test_cli_sarif_output(capsys):
    cli = _load_cli()
    rc = cli.main(["--models", "dp-mp", "--fixture",
                   "mismatched-constraint", "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "paddle-tpu-shard-lint"
    results = run["results"]
    assert results
    assert any(r["ruleId"] == "spmd-implicit-resharding"
               and r["level"] == "error" for r in results)
    located = [r for r in results if r.get("locations")]
    assert located
    region = located[0]["locations"][0]["physicalLocation"]
    assert region["artifactLocation"]["uri"].endswith(".py")
    assert region["region"]["startLine"] >= 1


def test_graph_lint_cli_sarif(capsys):
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "graph_lint.py")
    spec = importlib.util.spec_from_file_location("graph_lint_cli2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--models", "mlp", "--fixture", "adam-lazy",
                   "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["tool"]["driver"]["name"] == \
        "paddle-tpu-graph-lint"
    assert any(r["ruleId"] == "retrace-state-structure"
               for r in doc["runs"][0]["results"])


def test_sarif_report_levels_and_rules_index():
    fs = [analysis.Finding(rule="a-rule", severity="error", message="m",
                           where="x.py:10"),
          analysis.Finding(rule="b-rule", severity="info", message="n",
                           path="args[0]")]
    doc = analysis.sarif_report(fs, tool="t")
    run = doc["runs"][0]
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == \
        ["a-rule", "b-rule"]
    assert run["results"][0]["level"] == "error"
    assert run["results"][1]["level"] == "note"
    assert "locations" not in run["results"][1]  # pytree path only
    assert run["results"][1]["properties"]["path"] == "args[0]"


# ---------------------------------------------------------------------------
# Engine wiring: shard lint at first fit + comm-aware plan tie-break
# ---------------------------------------------------------------------------

@needs_8_devices
def test_engine_graph_lint_runs_shard_lint_under_mesh():
    from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    eng = Engine(model=net, loss=loss_fn, optimizer=opt, process_mesh=mesh,
                 graph_lint=True)
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    eng.fit(list(zip(x, y)), batch_size=8, epochs=1, prefetch=0)
    assert eng._graph_linted
    assert eng.lint_report_ is not None
    sa = eng.lint_report_.sharding
    assert sa is not None, "mesh present but no sharding analysis"
    # dp training: the propagation sees the gradient all-reduces
    assert any("dp" in a for a in sa.collectives.axes()), sa.bytes_by_axis()


@needs_8_devices
def test_plan_tie_break_prefers_lower_predicted_comm():
    """Candidates the analytic model can't separate are re-ranked by
    shard-lint's predicted comm bytes over the model's real forward
    jaxpr."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.planner import Plan, Planner

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(32, 32)
    eng = Engine.__new__(Engine)  # wiring-only: no mesh/fit needed
    eng.model = net

    def fwd_loss(xa, ya):
        out = net(Tensor(xa))
        return (((out - Tensor(ya)) ** 2).mean())._value

    x = Tensor(np.random.RandomState(0).randn(16, 32).astype(np.float32))
    y = Tensor(np.random.RandomState(1).randn(16, 32).astype(np.float32))

    stats = {"step_flops": 1e6, "param_bytes": 32 * 32 * 4,
             "act_bytes": 16 * 32 * 4, "layers": 1, "batch": 16,
             "param_shapes": [(32 * 32 * 4, (32, 32))]}

    tied = [Plan(dp=8, mp=1, est_step_time=1.0, feasible=True),
            Plan(dp=4, mp=2, est_step_time=1.0, feasible=True)]

    class _TiedPlanner(Planner):
        """Force an exact tie between pure-dp and dp×mp candidates."""

        def enumerate_plans(self):
            return list(tied)

    planner = _TiedPlanner(8, stats)
    chosen = eng._break_plan_tie(planner, tied[0], fwd_loss, x, y)
    # both candidates were scored, and the winner is the cheaper one —
    # dp=8 all-reduces the whole 4 KiB gradient at ring factor 2·7/8,
    # dp=4×mp=2 halves the dp ring AND the per-device gradient shard
    assert all(p.predicted_comm_bytes > 0 for p in tied)
    assert chosen is min(tied, key=lambda p: p.predicted_comm_bytes)
    assert chosen.mp == 2


def test_plan_tie_break_survives_failure():
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.planner import Plan

    eng = Engine.__new__(Engine)
    eng.model = None  # named_parameters() will raise inside the helper

    class _Boom:
        def enumerate_plans(self):
            return [Plan(dp=2, est_step_time=1.0, feasible=True),
                    Plan(dp=1, mp=2, est_step_time=1.0, feasible=True)]

    best = _Boom().enumerate_plans()[0]
    assert eng._break_plan_tie(_Boom(), best, None, None, None) is best


# ---------------------------------------------------------------------------
# satellite: guarded replicate constraint (dryrun standalone fix)
# ---------------------------------------------------------------------------

def test_replicate_activation_guarded_without_mesh():
    """PR 5's dryrun_multichip failure: `_replicate_activation` took the
    bare-P() branch during the pipeline trace, which the 0.4.x runtime
    rejects without a concrete `with Mesh` context. It must fall back to
    the explicit NamedSharding (or skip entirely on a trivial mesh)."""
    from paddle_tpu.distributed.meta_parallel.mp_layers import (
        _replicate_activation,
    )

    v = jnp.ones((4, 4), jnp.float32)
    # trivial/absent mesh: constraint skipped, value unchanged
    assert _replicate_activation(v, None) is v
    mesh1 = build_mesh({"mp": 1})
    assert _replicate_activation(v, mesh1) is v
    # real mesh, no ambient abstract mesh: explicit-sharding form applies
    mesh = build_mesh({"mp": 2})
    out = _replicate_activation(v, mesh)
    assert np.asarray(out).shape == (4, 4)
    # under the ambient abstract mesh (what the pipeline trace installs)
    # the bare-P() attempt must not escape on this jax version
    try:
        ctx = jax.sharding.use_abstract_mesh(mesh.abstract_mesh)
    except Exception:
        pytest.skip("no abstract-mesh context on this jax")
    with ctx:
        out2 = _replicate_activation(v, mesh)
    assert np.asarray(out2).shape == (4, 4)


@needs_8_devices
def test_pipelined_gpt_traces_standalone():
    """The dryrun's pipeline step must at least TRACE in a plain process
    (the compile still needs a PartitionId-capable backend): the
    empty-mesh constraint guard is what un-breaks this."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.meta_parallel import build_pipelined_gpt
    from paddle_tpu.models import GPTConfig

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 2
    strategy.hybrid_configs["mp_degree"] = 2
    strategy.hybrid_configs["pp_degree"] = 2
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    cfg.use_tp = True
    with unique_name.guard():
        paddle.seed(1)
        model = build_pipelined_gpt(cfg, hcg, num_microbatches=2)
    ids = np.random.RandomState(0).randint(0, 64, (4, 16)).astype(np.int64)

    def fwd(ids_arr):
        return model.loss(Tensor(ids_arr), Tensor(ids_arr.copy()))._value

    # the pipeline draws a per-step RNG root inside the trace: snapshot/
    # restore the global generator or its key leaks out as a tracer
    from paddle_tpu.framework import random as rnd

    rng_state = rnd.default_generator.get_state()
    try:
        jaxpr = jax.make_jaxpr(fwd)(ids)  # RuntimeError before the fix
    finally:
        rnd.default_generator.set_state(rng_state)
    assert jaxpr.jaxpr.eqns
