"""incubate.nn fused transformer layers (reference
incubate/nn/layer/fused_transformer.py): parity vs the composed unfused
layers, train/eval behavior, gradient flow."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.incubate.nn import (
    FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedTransformerEncoderLayer,
)


def _np(t):
    return np.asarray(t._value)


def test_fused_attention_matches_composed():
    paddle.seed(0)
    d, h = 16, 4
    attn = FusedMultiHeadAttention(d, h, dropout_rate=0.0,
                                   attn_dropout_rate=0.0,
                                   normalize_before=True)
    attn.eval()
    x = Tensor(np.random.RandomState(0).randn(2, 6, d).astype(np.float32))
    out = attn(x)

    # composed reference with the same parameters
    import paddle_tpu.nn.functional as F

    y = attn.pre_ln(x)
    b, s, _ = y.shape
    qkv = attn.qkv_proj(y).reshape([b, s, 3, h, d // h])
    ref = F.scaled_dot_product_attention(qkv[:, :, 0], qkv[:, :, 1],
                                         qkv[:, :, 2], training=False)
    ref = x + attn.out_proj(ref.reshape([b, s, d]))
    np.testing.assert_allclose(_np(out), _np(ref), atol=1e-5)
    with pytest.raises(ValueError):
        FusedMultiHeadAttention(10, 3)


def test_fused_ffn_matches_composed():
    paddle.seed(1)
    ffn = FusedFeedForward(8, 32, dropout_rate=0.0, normalize_before=False)
    ffn.eval()
    x = Tensor(np.random.RandomState(1).randn(2, 5, 8).astype(np.float32))
    out = ffn(x)
    import paddle_tpu.nn.functional as F

    ref = ffn.ln2(x + ffn.linear2(F.relu(ffn.linear1(x))))
    np.testing.assert_allclose(_np(out), _np(ref), atol=1e-5)


def test_bias_dropout_residual_ln():
    paddle.seed(2)
    m = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    m.eval()
    x = Tensor(np.random.RandomState(2).randn(2, 3, 8).astype(np.float32))
    r = Tensor(np.random.RandomState(3).randn(2, 3, 8).astype(np.float32))
    out = m(x, r)
    ref = m.norm(r + x + m.linear_bias)
    np.testing.assert_allclose(_np(out), _np(ref), atol=1e-5)


def test_encoder_layer_trains():
    paddle.seed(3)
    layer = FusedTransformerEncoderLayer(16, 4, 64, dropout_rate=0.1)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=layer.parameters())
    x = Tensor(np.random.RandomState(4).randn(4, 8, 16).astype(np.float32))
    losses = []
    for _ in range(5):
        out = layer(x)
        loss = (out * out).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]
    layer.eval()
    a = _np(layer(x))
    b = _np(layer(x))
    np.testing.assert_allclose(a, b)  # eval deterministic
