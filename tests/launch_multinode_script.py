"""Rank script for the two-node launch test: 2 nodes x 2 procs = world 4.

Exercises the multi-node path (reference
``launch/controllers/collective.py`` + ``gen_comm_id_helper.cc``
bootstrap): two SEPARATE launcher invocations (--rank 0 / --rank 1) share
one coordinator, the hybrid mesh gets an explicit dcn axis whose blocks
are the nodes, and collectives run across the node boundary.
"""
import json
import os

import numpy as np

import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
rank, world = env.rank, env.world_size
assert world == 4, world

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 4
node = rank // 2  # 2 procs per node

from paddle_tpu.distributed import fleet

strategy = fleet.DistributedStrategy()
strategy.hybrid_configs["dcn_degree"] = 2   # = nnodes: DP over DCN
strategy.hybrid_configs["dp_degree"] = 2    # intra-node
fleet.init(is_collective=True, strategy=strategy)
hcg = fleet.get_hybrid_communicate_group()
assert hcg.get_dcn_parallel_world_size() == 2
mesh = hcg.mesh
assert mesh.axis_names[0] == "dcn"  # outermost: only dcn traffic crosses DCN

# device order: jax global devices are sorted by process, so the dcn axis
# blocks correspond exactly to the two nodes
devs = np.asarray(mesh.devices).reshape(2, -1)
for b in range(2):
    assert all(d.process_index in (2 * b, 2 * b + 1)
               for d in devs[b].ravel()), devs

# cross-node collective: each process contributes (rank+1); psum over the
# FULL mesh must cross the node boundary
local = np.full((1, 4), float(rank + 1), np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(("dcn", "pp", "dp"))), local, (4, 4))
total = jax.jit(lambda a: a.sum(),
                out_shardings=NamedSharding(mesh, P()))(arr)
got = float(np.asarray(jax.device_get(total)))
assert got == 40.0, got  # (1+2+3+4) * 4 lanes

# dcn-axis-only reduction: shard over dcn, psum along dcn => pairs of
# node sums; verifies the dcn axis is a real comm group
from jax import shard_map

def body(x):
    return jax.lax.psum(x, "dcn")

f = jax.jit(shard_map(
    body, mesh=mesh,
    in_specs=(P(("dcn",)),), out_specs=P(("dcn",)), check_vma=False))
arr2 = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(("dcn",))), np.full((1, 2), float(node), np.float32),
    (2, 2))
out2 = np.asarray(jax.device_get(
    jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))(f(arr2))))
assert np.allclose(out2, 1.0), out2  # node0 + node1 = 0 + 1

out_dir = os.environ["LAUNCH_TEST_OUT"]
with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f2:
    json.dump({"rank": rank, "node": node, "world": world, "psum": got}, f2)
print(f"rank {rank} (node {node}) OK", flush=True)
dist.barrier()
