"""Selective-remat autopilot (ISSUE 15): mem-lint ``delta_if_remat``,
the greedy site planner, block wrapping through fleet recompute, and the
``Model.prepare(remat=...)`` / auto_parallel ``Engine(remat=...)`` knobs.

Contracts under test:
  * ``delta_if_remat`` — predicted peak reduction is non-negative, never
    exceeds the bytes of the chosen buffers (the relive window keeps the
    backward-consumer recompute honest), and is 0 for params/outputs;
  * ``plan_remat`` — the greedy planner gets the PREDICTED peak under an
    achievable budget and chooses nothing under a generous one;
  * ``auto_remat`` — wraps repeated blocks until the RE-TRACED peak fits;
    the first train step's loss is bit-identical to the unwrapped model
    (jax.checkpoint changes memory, never math) and ``clear_remat``
    restores the original forwards;
  * the ``hbm-remat-candidate`` finding quotes the planner's
    ``delta_if_remat`` prediction and points at the autopilot knob.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import remat_plan
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import unique_name


def _mlp_step(batch=16, din=32, dh=64):
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(din, dh)
        l2 = paddle.nn.Linear(dh, din)
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(l1.parameters()) + list(l2.parameters()))

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        out = l2(h)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[l1, l2, opt],
                        donate_state=True)
    rng = np.random.RandomState(0)
    x = Tensor(rng.randn(batch, din).astype(np.float32))
    y = Tensor(rng.randn(batch, din).astype(np.float32))
    return step, (x, y)


_GPT_CFG = dict(vocab_size=128, hidden_size=64, num_layers=4, num_heads=2,
                max_position_embeddings=128, hidden_dropout=0.0,
                attention_dropout=0.0)


def _gpt_and_step(seed=0):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(GPTConfig(**_GPT_CFG))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def make_step():
        def train_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return CompiledStep(train_step, stateful=[model, opt],
                            donate_state=True)

    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, 128, (2, 128)).astype(np.int64))
    return model, make_step, (ids, ids)


# ---------------------------------------------------------------------------
# delta_if_remat
# ---------------------------------------------------------------------------
def test_delta_if_remat_bounds():
    step, (x, y) = _mlp_step()
    tl = analysis.analyze_memory(step, x, y)
    cands = tl.long_lived(1.0, 0.0)
    assert cands, "tiny MLP must expose at least one long-lived temp"
    keys = [b.key for b in cands]
    d = tl.delta_if_remat(keys)
    assert 0.0 <= d <= sum(b.nbytes for b in cands)
    # single-key form accepts a bare int and is no better than the union
    assert 0.0 <= tl.delta_if_remat(keys[0]) <= d + 1e-9


def test_delta_if_remat_ignores_params_and_outputs():
    step, (x, y) = _mlp_step()
    tl = analysis.analyze_memory(step, x, y)
    skip = [b.key for b in tl.buffers
            if b.kind != "temp" or b.is_output or b.aliases is not None]
    assert skip
    assert tl.delta_if_remat(skip) == 0.0


# ---------------------------------------------------------------------------
# candidate grouping + the greedy planner
# ---------------------------------------------------------------------------
def test_candidate_sites_group_repeated_layers():
    _, make_step, args = _gpt_and_step()
    tl = analysis.analyze_memory(make_step(), *args)
    sites = remat_plan.candidate_sites(tl, min_bytes=1.0, min_span=0.0)
    assert sites
    # sorted largest-first, and the 4 identical blocks share source lines:
    # at least one site aggregates buffers from several layers
    assert sites == sorted(sites, key=lambda s: -s.nbytes)
    assert max(s.n_buffers for s in sites) >= 2


def test_plan_remat_meets_achievable_budget():
    _, make_step, args = _gpt_and_step()
    tl = analysis.analyze_memory(make_step(), *args)
    full = remat_plan.plan_remat(tl, budget_bytes=None, min_bytes=1.0,
                                 min_span=0.0)
    assert full.peak_after <= full.peak_before
    assert full.ok  # no budget: always "fits"
    floor = full.peak_after
    budget = floor + 0.5 * (tl.peak_bytes - floor)
    plan = remat_plan.plan_remat(tl, budget_bytes=budget, min_bytes=1.0,
                                 min_span=0.0)
    assert plan.ok and plan.sites
    assert plan.peak_after <= budget
    assert plan.delta > 0
    d = plan.as_dict()
    assert d["ok"] and d["sites"] and "peak_after" in d
    assert "fits" in plan.table()


def test_plan_remat_generous_budget_chooses_nothing():
    _, make_step, args = _gpt_and_step()
    tl = analysis.analyze_memory(make_step(), *args)
    plan = remat_plan.plan_remat(tl, budget_bytes=2.0 * tl.peak_bytes,
                                 min_bytes=1.0, min_span=0.0)
    assert plan.ok and not plan.sites
    assert plan.peak_after == plan.peak_before


def test_plan_remat_impossible_budget_reports_not_ok():
    _, make_step, args = _gpt_and_step()
    tl = analysis.analyze_memory(make_step(), *args)
    plan = remat_plan.plan_remat(tl, budget_bytes=1.0, min_bytes=1.0,
                                 min_span=0.0)
    assert not plan.ok
    assert "DOES NOT FIT" in plan.table()


# ---------------------------------------------------------------------------
# application: wrapping, parity, unwrap
# ---------------------------------------------------------------------------
def test_find_repeated_blocks_is_the_decoder_stack():
    model, _, _ = _gpt_and_step()
    blocks = remat_plan.find_repeated_blocks(model)
    assert len(blocks) == 4
    assert all(type(b).__name__ == "GPTDecoderLayer" for b in blocks)


def test_auto_remat_wraps_until_retraced_peak_fits():
    model, make_step, args = _gpt_and_step()
    tl0 = analysis.analyze_memory(make_step(), *args)
    budget = 0.7 * tl0.peak_bytes
    rep = analysis.auto_remat(model, budget, make_step, args,
                              name="gpt_remat_test")
    try:
        assert rep.ok, rep.table()
        assert rep.blocks_wrapped >= 1
        assert rep.blocks_total == 4
        assert rep.peak_after <= budget
        # the reported peak is the applied program's own timeline
        assert rep.timeline.peak_bytes == rep.peak_after
        assert rep.as_dict()["blocks_wrapped"] == rep.blocks_wrapped
    finally:
        n = remat_plan.clear_remat(model)
    assert n == rep.blocks_wrapped


def test_remat_loss_bit_identical_and_clear_restores():
    model, make_step, args = _gpt_and_step(seed=3)
    base = float(np.asarray(make_step()(*args)._value))

    model2, make_step2, args2 = _gpt_and_step(seed=3)
    tl0 = analysis.analyze_memory(make_step2(), *args2)
    rep = analysis.auto_remat(model2, 0.7 * tl0.peak_bytes, make_step2,
                              args2, name="gpt_remat_parity")
    assert rep.blocks_wrapped >= 1
    got = float(np.asarray(make_step2()(*args2)._value))
    assert got == base, "jax.checkpoint must not change the math"
    remat_plan.clear_remat(model2)
    assert not any(getattr(l, "_remat_wrapped", False)
                   for l in model2.sublayers(include_self=True))


def test_wrap_block_bypasses_eval_and_cache_calls():
    model, _, _ = _gpt_and_step(seed=5)
    block = remat_plan.find_repeated_blocks(model)[0]
    rng = np.random.RandomState(0)
    x = Tensor(rng.randn(2, 8, 64).astype(np.float32))
    model.eval()
    want = np.asarray(block(x)._value)
    remat_plan.wrap_block(block)
    assert block._remat_wrapped
    remat_plan.wrap_block(block)  # idempotent
    got = np.asarray(block(x)._value)  # eval mode: original path
    np.testing.assert_array_equal(got, want)
    remat_plan.unwrap_block(block)
    assert not block._remat_wrapped


def test_resolve_budget_forms():
    assert remat_plan.resolve_budget(None) is None
    assert remat_plan.resolve_budget(False) is None
    assert remat_plan.resolve_budget(123) == 123.0
    cap = remat_plan.resolve_budget("auto")
    assert cap is None or cap > 0  # None on plain XLA:CPU


# ---------------------------------------------------------------------------
# the user-facing knobs
# ---------------------------------------------------------------------------
def test_model_prepare_remat_applies_once():
    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(**_GPT_CFG))
    m = paddle.Model(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (2, 128)).astype(np.int64)

    import paddle_tpu.nn.functional as F

    def ce(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, 128]), labels.reshape([-1])).mean()

    m.prepare(opt, loss=ce, remat=int(40 << 20))
    assert m._remat == int(40 << 20) and not m._remat_applied
    (l0,) = m.train_batch([ids], [ids.astype(np.int64)])
    assert np.isfinite(l0)
    assert m._remat_applied
    rep = m._remat_report
    assert rep is not None and rep.blocks_total == 4
    # second batch must not re-apply
    m.train_batch([ids], [ids])
    assert m._remat_report is rep
    remat_plan.clear_remat(model)


def test_engine_remat_kwarg_stored():
    net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    eng = Engine(model=net, loss=paddle.nn.MSELoss(), optimizer=opt,
                 remat=int(1 << 30))
    assert eng._remat == int(1 << 30)
    assert eng.remat_report_ is None and not eng._remat_applied


# ---------------------------------------------------------------------------
# the lint finding quotes the autopilot
# ---------------------------------------------------------------------------
def test_remat_candidate_finding_quotes_predicted_delta():
    step, (x, y) = _mlp_step()
    rep = analysis.lint_step(step, x, y,
                             config={"remat_min_bytes": 1.0,
                                     "remat_min_span": 0.0})
    hits = rep.by_rule("hbm-remat-candidate")
    assert hits
    f = hits[0]
    assert "rematerializing" in f.message
    assert f.data.get("delta_if_remat") is not None
    assert f.data["delta_if_remat"] >= 0.0
    assert 'remat="auto"' in f.hint and "plan_remat" in f.hint
