"""paddle.set_flags/get_flags + FLAGS_check_nan_inf debug mode.
Reference: python/paddle/fluid/framework.py:7125, platform/flags.cc."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def test_set_get_roundtrip():
    assert paddle.get_flags("check_nan_inf") == {"check_nan_inf": False}
    paddle.set_flags({"check_nan_inf": True})
    try:
        assert paddle.get_flags(["check_nan_inf"])["check_nan_inf"] is True
    finally:
        paddle.set_flags({"check_nan_inf": False})


def test_unknown_flag_raises():
    with pytest.raises(ValueError):
        paddle.set_flags({"no_such_flag": 1})
    with pytest.raises(ValueError):
        paddle.get_flags("no_such_flag")
    with pytest.raises(TypeError):
        paddle.set_flags("check_nan_inf")


def test_bool_coercion_from_strings():
    paddle.set_flags({"check_nan_inf": "true"})
    assert paddle.get_flags("check_nan_inf")["check_nan_inf"] is True
    paddle.set_flags({"check_nan_inf": "0"})
    assert paddle.get_flags("check_nan_inf")["check_nan_inf"] is False


def test_check_nan_inf_raises_on_nan():
    paddle.set_flags({"check_nan_inf": True})
    try:
        x = Tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="Inf/Nan"):
            _ = x / x  # 0/0 -> nan
        # clean values pass
        _ = x + x
    finally:
        paddle.set_flags({"check_nan_inf": False})


def test_check_nan_inf_skips_traced_values():
    """Inside jit, outputs are tracers — the flag must not break compilation."""
    from paddle_tpu.jit.functionalize import CompiledStep

    paddle.set_flags({"check_nan_inf": True})
    try:
        def f(x):
            return (x * 0.0) / (x * 0.0)  # nan inside jit: not host-checkable

        step = CompiledStep(f, stateful=[])
        out = step(Tensor(np.ones(2, np.float32)))
        assert np.isnan(np.asarray(out._value)).all()
    finally:
        paddle.set_flags({"check_nan_inf": False})


def test_disable_flash_flag_routes_to_einsum():
    import paddle_tpu.nn.functional as F

    q = Tensor(np.random.RandomState(0).randn(2, 128, 4, 64).astype(np.float32))
    out1 = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    paddle.set_flags({"disable_flash_attention": True})
    try:
        out2 = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    finally:
        paddle.set_flags({"disable_flash_attention": False})
    np.testing.assert_allclose(np.asarray(out1._value), np.asarray(out2._value),
                               atol=2e-2)
