"""Regression tests for the round-1 advisor findings (ADVICE.md):
SyncBatchNorm forward, optimizer state restore portability, paddle.save
checkpoint format, trace-safe GradScaler, p2p channel keying."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.utils import unique_name


def test_sync_batch_norm_forward_eager():
    # ADVICE high #1: forward used to raise AttributeError on the undefined
    # coll._in_spmd_context(); in single-device eager it must equal BatchNorm.
    paddle.seed(0)
    x = Tensor(np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32))
    sbn = nn.SyncBatchNorm(3)
    bn = nn.BatchNorm2D(3)
    out = sbn(x)
    ref = bn(x)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_sync_batch_norm_spmd_pmean_and_running_stats():
    # spmd path: per-shard batches, stats pmean'd over the mesh axis must
    # equal global-batch stats, and running buffers must learn them.
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.collective import _default_group

    g = _default_group()
    paddle.seed(0)
    sbn = nn.SyncBatchNorm(3, momentum=0.5)
    x_full = np.random.RandomState(0).randn(8, 3, 4, 4).astype(np.float32)

    def body(x):
        out = sbn(Tensor(x))
        # thread the mutated buffers out of the spmd region (the contract
        # any state-threading orchestrator implements)
        return out._value, sbn._mean._value, sbn._variance._value

    f = shard_map(
        body,
        mesh=g.mesh,
        in_specs=(P(g.axis_name),),
        out_specs=(P(g.axis_name), P(), P()),
        check_vma=False,
    )
    out, mean_buf, var_buf = f(x_full)
    sbn._mean._value = mean_buf
    sbn._variance._value = var_buf

    # reference: plain BatchNorm over the *global* batch
    paddle.seed(0)
    bn = nn.BatchNorm2D(3, momentum=0.5)
    ref = bn(Tensor(x_full))
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(mean_buf), bn._mean.numpy(), rtol=1e-4, atol=1e-5
    )

    # eval must now consume the learned (updated) stats
    sbn.eval()
    bn.eval()
    e1 = sbn(Tensor(x_full[:2]))
    e2 = bn(Tensor(x_full[:2]))
    np.testing.assert_allclose(e1.numpy(), e2.numpy(), rtol=1e-4, atol=1e-5)


def test_sync_batch_norm_convert():
    model = nn.Sequential(nn.Conv2D(1, 4, 3), nn.BatchNorm2D(4))
    converted = nn.SyncBatchNorm.convert_sync_batchnorm(model)
    kinds = [type(m).__name__ for _, m in converted.named_sublayers()]
    assert "SyncBatchNorm" in kinds and "BatchNorm2D" not in kinds


def _tiny_model_and_opt():
    model = nn.Linear(4, 3)
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    return model, opt


def _one_step(model, opt):
    x = Tensor(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    loss = model(x).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()


def test_optimizer_state_restore_before_first_step():
    # ADVICE high #2: restoring into a fresh optimizer whose accumulators are
    # created lazily on the first step must pick up the loaded moments, not
    # reinitialize to zeros.
    paddle.seed(0)
    with unique_name.guard():
        model, opt = _tiny_model_and_opt()
    for _ in range(3):
        _one_step(model, opt)
    sd = opt.state_dict()

    paddle.seed(0)
    with unique_name.guard():
        model2, opt2 = _tiny_model_and_opt()
    model2.set_state_dict(model.state_dict())
    opt2.set_state_dict(sd)
    assert opt2._step_count == opt._step_count
    _one_step(model2, opt2)
    _one_step(model, opt)
    for name in ("moment1", "moment2"):
        for key, v in opt._accumulators[name].items():
            np.testing.assert_allclose(
                np.asarray(v),
                np.asarray(opt2._accumulators[name][key]),
                rtol=1e-6,
                atol=1e-6,
                err_msg=f"{name}/{key} diverged after restore",
            )


def test_optimizer_state_keys_are_portable_names():
    # keys must come from stable auto-generated param names, never id()
    with unique_name.guard():
        model, opt = _tiny_model_and_opt()
    _one_step(model, opt)
    for k in opt.state_dict():
        if k in ("@step", "LR_Scheduler"):
            continue
        assert "@" not in k, f"memory-address key leaked: {k}"


def test_save_format_is_bare_ndarrays(tmp_path):
    # ADVICE medium #3: .pdparams must pickle state_dict values as plain
    # numpy arrays (reference paddle.save format), not wrapper dicts.
    import pickle

    model = nn.Linear(4, 3)
    path = str(tmp_path / "m.pdparams")
    paddle.save(model.state_dict(), path)
    with open(path, "rb") as f:
        raw = pickle.load(f)
    assert isinstance(raw, dict)
    for k, v in raw.items():
        assert isinstance(v, np.ndarray), f"{k} serialized as {type(v)}"
    loaded = paddle.load(path)
    for k, v in loaded.items():
        assert isinstance(v, Tensor)
    model2 = nn.Linear(4, 3)
    model2.set_state_dict(loaded)
    x = Tensor(np.random.RandomState(1).randn(2, 4).astype(np.float32))
    np.testing.assert_allclose(model(x).numpy(), model2(x).numpy(), rtol=1e-6)


def test_grad_scaler_traced_inside_compiled_step():
    # ADVICE medium #4: scaler state must stay traced — the whole
    # scale/backward/step/update cycle compiles into one XLA step.
    paddle.seed(0)
    model = nn.Linear(4, 3)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0, incr_every_n_steps=2)

    def train_step(x):
        loss = model(x).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt, scaler], donate_state=False)
    x = Tensor(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    l1 = float(step(x).numpy())
    l2 = float(step(x).numpy())
    assert np.isfinite(l1) and l2 < l1
    # dynamic scaling grew after incr_every_n_steps good steps
    assert scaler.get_init_loss_scaling() == 256.0


def test_grad_scaler_skips_update_on_inf():
    paddle.seed(0)
    model = nn.Linear(2, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
    before = {k: v.numpy().copy() for k, v in model.state_dict().items()}

    x = Tensor(np.array([[np.inf, 1.0], [1.0, 1.0]], np.float32))
    loss = model(x).mean()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()

    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(v.numpy(), before[k])
    # moments must not be poisoned either
    for store in opt._accumulators.values():
        for v in store.values():
            assert np.all(np.isfinite(np.asarray(v)))
    # and the scale halved
    assert scaler.get_init_loss_scaling() == 32.0

    # a following finite step must actually update
    x = Tensor(np.ones((2, 2), np.float32))
    loss = model(x).mean()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()
    changed = any(
        not np.array_equal(v.numpy(), before[k]) for k, v in model.state_dict().items()
    )
    assert changed


def test_p2p_channel_keyed_by_destination():
    # ADVICE low #5: interleaved sends to different destinations must not be
    # delivered to the wrong recv.
    import paddle_tpu.distributed as dist

    from paddle_tpu.distributed import collective as coll

    a = Tensor(np.array([1.0], np.float32))
    b = Tensor(np.array([2.0], np.float32))
    try:
        # sole pending destination: recv plays that rank (classic simulation)
        dist.send(a, dst=1)
        out = dist.recv(Tensor(np.zeros(1, np.float32)), src=0)
        np.testing.assert_array_equal(out.numpy(), a.numpy())
        # two pending destinations: misdelivery is impossible to rule out,
        # so recv must refuse instead of handing over the wrong payload
        dist.send(a, dst=3)
        dist.send(b, dst=0)
        with pytest.raises(RuntimeError, match="ambiguous"):
            dist.recv(Tensor(np.zeros(1, np.float32)), src=1)
    finally:
        coll._P2P_CHANNEL.clear()
