"""paddle.flops, paddle.text datasets, incubate.autotune, onnx gating."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def test_flops_linear_and_conv():
    # reference conventions: MAC = 1 op, conv counts bias
    net = paddle.nn.Linear(8, 16)
    n = paddle.flops(net, (4, 8))
    assert n == 8 * 4 * 16

    conv = paddle.nn.Conv2D(3, 8, 3, padding=1)
    n = paddle.flops(conv, (1, 3, 16, 16), print_detail=True)
    assert n == (3 * 9 + 1) * 8 * 16 * 16


def test_flops_custom_ops():
    net = paddle.nn.ReLU()
    n = paddle.flops(net, (2, 4),
                     custom_ops={paddle.nn.ReLU: lambda l, x, o: 42})
    assert n == 42


def test_text_datasets():
    from paddle_tpu.text import Imdb, UCIHousing

    h = UCIHousing(mode="train")
    x, y = h[0]
    assert x.shape == (13,) and y.shape == (1,)
    d = Imdb(mode="test", seq_len=32)
    doc, lab = d[5]
    assert doc.shape == (32,) and lab in (0, 1)
    # deterministic across constructions
    d2 = Imdb(mode="test", seq_len=32)
    np.testing.assert_array_equal(d[5][0], d2[5][0])


def test_autotune_config():
    from paddle_tpu.incubate import autotune

    autotune.set_config({"kernel": {"enable": False}})
    try:
        # disabling tuned kernels actually changes attention routing
        assert paddle.get_flags("disable_flash_attention")["disable_flash_attention"] is True
        assert autotune.get_status()["kernel"]["enable"] is False
        autotune.set_config({"kernel": {"enable": True}})
        assert paddle.get_flags("disable_flash_attention")["disable_flash_attention"] is False
        autotune.set_config({"kernel": None})  # None section is a no-op
    finally:
        paddle.set_flags({"disable_flash_attention": False})
    autotune.set_config(None)
    with pytest.raises(ValueError):
        autotune.set_config({"nope": {}})
    with pytest.raises(TypeError):
        autotune.set_config(3)


def test_onnx_exports_stablehlo(tmp_path):
    import os

    from paddle_tpu.jit.save_load import InputSpec

    lin = paddle.nn.Linear(4, 2)
    path = str(tmp_path / "m")
    # round-5: the default onnx format now writes a real .onnx artifact
    out = paddle.onnx.export(lin, path,
                             input_spec=[InputSpec([2, 4], "float32")])
    assert out == path + ".onnx" and os.path.exists(out)
    # explicit StableHLO opt-in writes the portable artifact
    out = paddle.onnx.export(lin, path, format_="stablehlo",
                             input_spec=[InputSpec([2, 4], "float32")])
    assert out == path and os.path.exists(path + ".pdmodel")
