"""Round-4 API-surface audit additions: every name in the reference's
``paddle``/``paddle.nn``/``paddle.nn.functional``/``paddle.linalg``/
``paddle.distributed`` ``__all__`` now exists here — these tests pin the
semantics of the newly added ones (torch-cpu as the oracle where its op
matches the reference definition)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name

rng = np.random.RandomState(0)


def t(x):
    return Tensor(np.asarray(x))


# -- tensor ops --------------------------------------------------------------

def test_tensordot_paddle_axes_forms():
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    # flat list: contract the SAME axes of both tensors
    got = paddle.tensordot(t(x), t(y), axes=[0, 1]).numpy()
    np.testing.assert_allclose(got, (x * y).sum(), rtol=1e-5)
    got = paddle.tensordot(t(x), t(y), axes=[[0, 1]]).numpy()
    np.testing.assert_allclose(got, (x * y).sum(), rtol=1e-5)
    z = rng.randn(4, 3).astype(np.float32)
    got = paddle.tensordot(t(x), t(z), axes=[[0, 1], [1, 0]]).numpy()
    np.testing.assert_allclose(got, np.tensordot(x, z, axes=([0, 1], [1, 0])),
                               rtol=1e-5)


def test_max_pool_mask_ceil_mode_shapes():
    x = t(rng.randn(1, 1, 5, 5).astype(np.float32))
    out, mask = F.max_pool2d(x, 2, 2, return_mask=True, ceil_mode=True)
    assert list(out.shape) == list(mask.shape) == [1, 1, 3, 3]


def test_margin_ce_label_column_shape():
    logits = np.tanh(rng.randn(4, 10)).astype(np.float32)
    label = rng.randint(0, 10, (4, 1))
    out = F.margin_cross_entropy(t(logits), t(label), reduction="none")
    assert list(out.shape) == [4, 1]


def test_cross_diff_tensordot_unbind_reverse():
    a = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(paddle.cross(t(a), t(b)).numpy(),
                               np.cross(a, b), rtol=1e-6)
    x = rng.randn(5, 7).astype(np.float32)
    np.testing.assert_allclose(paddle.diff(t(x)).numpy(),
                               np.diff(x), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.diff(t(x), n=2, axis=0).numpy(), np.diff(x, n=2, axis=0),
        rtol=1e-6)
    y = rng.randn(7, 6).astype(np.float32)
    np.testing.assert_allclose(
        paddle.tensordot(t(x), t(y), axes=1).numpy(),
        np.tensordot(x, y, axes=1), rtol=1e-5)
    parts = paddle.unbind(t(x), axis=1)
    assert len(parts) == 7 and parts[0].shape == [5]
    np.testing.assert_allclose(parts[3].numpy(), x[:, 3])
    np.testing.assert_allclose(paddle.reverse(t(x), axis=[0]).numpy(),
                               x[::-1])


def test_logcumsumexp_and_renorm():
    x = rng.randn(4, 6).astype(np.float32)
    got = paddle.logcumsumexp(t(x), axis=1).numpy()
    want = np.log(np.cumsum(np.exp(x), axis=1))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    r = paddle.renorm(t(x), p=2.0, axis=0, max_norm=1.0).numpy()
    norms = np.linalg.norm(r, axis=1)
    assert (norms <= 1.0 + 1e-5).all()
    # untouched rows keep their values
    small = np.linalg.norm(x, axis=1) <= 1.0
    np.testing.assert_allclose(r[small], x[small], rtol=1e-6)


def test_shard_index():
    label = t(np.array([[16], [1]], np.int64))
    out = paddle.shard_index(label, index_num=20, nshards=2, shard_id=0)
    np.testing.assert_array_equal(out.numpy(), [[-1], [1]])
    out1 = paddle.shard_index(label, index_num=20, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out1.numpy(), [[6], [-1]])
    with pytest.raises(ValueError):
        paddle.shard_index(label, 20, 2, 5)


def test_dtype_predicates_and_aliases():
    assert paddle.is_floating_point(t(np.zeros(3, np.float32)))
    assert not paddle.is_floating_point(t(np.zeros(3, np.int32)))
    assert paddle.is_integer(t(np.zeros(3, np.int64)))
    assert not paddle.is_complex(t(np.zeros(3, np.float32)))
    assert paddle.is_complex(t(np.zeros(3, np.complex64)))
    assert paddle.dtype("float32") == paddle.float32
    assert paddle.bool == paddle.bool_
    assert paddle.NPUPlace(0) is not None
    paddle.check_shape([2, -1, 3])
    with pytest.raises(TypeError):
        paddle.check_shape([2, "x"])
    paddle.disable_signal_handler()
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    xv = np.random.randn(3).astype(np.float32)
    x = t(xv)
    paddle.tanh_(x)
    np.testing.assert_allclose(x.numpy(), np.tanh(xv), rtol=1e-6)


# -- functional --------------------------------------------------------------

def test_diag_embed_and_zeropad2d():
    x = rng.randn(2, 3).astype(np.float32)
    got = F.diag_embed(t(x)).numpy()
    want = torch.diag_embed(torch.tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)
    got = F.diag_embed(t(x), offset=1).numpy()
    want = torch.diag_embed(torch.tensor(x), offset=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)

    img = rng.randn(1, 2, 3, 4).astype(np.float32)
    got = F.zeropad2d(t(img), [1, 2, 3, 4]).numpy()
    want = tF.pad(torch.tensor(img), (1, 2, 3, 4)).numpy()
    np.testing.assert_allclose(got, want)


def test_temporal_shift():
    x = rng.randn(4, 8, 2, 2).astype(np.float32)  # N*T=4 (T=2), C=8
    out = F.temporal_shift(t(x), seg_num=2, shift_ratio=0.25).numpy()
    xr = x.reshape(2, 2, 8, 2, 2)
    o = out.reshape(2, 2, 8, 2, 2)
    fold = 2
    # back-shift: segment t holds t+1's first fold channels
    np.testing.assert_allclose(o[:, 0, :fold], xr[:, 1, :fold])
    np.testing.assert_allclose(o[:, 1, :fold], 0.0)
    # forward-shift: segment t holds t-1's second fold
    np.testing.assert_allclose(o[:, 1, fold:2 * fold], xr[:, 0, fold:2 * fold])
    np.testing.assert_allclose(o[:, 0, fold:2 * fold], 0.0)
    np.testing.assert_allclose(o[:, :, 2 * fold:], xr[:, :, 2 * fold:])


def test_max_pool_mask_and_unpool():
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    out, mask = F.max_pool2d(t(x), 2, 2, return_mask=True)
    m, o = mask.numpy(), out.numpy()
    for n in range(2):
        for c in range(3):
            for i in range(4):
                for j in range(4):
                    fi = m[n, c, i, j]
                    assert x[n, c, fi // 8, fi % 8] == \
                        x[n, c, 2 * i:2 * i + 2, 2 * j:2 * j + 2].max()
    un = F.max_unpool2d(out, mask, 2, 2).numpy()
    want = tF.max_unpool2d(
        *[torch.tensor(v) for v in
          (o, m.astype(np.int64))], kernel_size=2, stride=2).numpy()
    np.testing.assert_allclose(un, want)


def test_losses_match_torch():
    x = rng.randn(5, 7).astype(np.float32)
    y = (rng.rand(5, 7) > 0.5).astype(np.float32)
    got = F.multi_label_soft_margin_loss(t(x), t(y)).numpy()
    want = tF.multilabel_soft_margin_loss(
        torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)

    a, p, n = (rng.randn(4, 8).astype(np.float32) for _ in range(3))
    got = F.triplet_margin_with_distance_loss(t(a), t(p), t(n),
                                              margin=0.5).numpy()
    want = tF.triplet_margin_with_distance_loss(
        torch.tensor(a), torch.tensor(p), torch.tensor(n),
        margin=0.5).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_margin_cross_entropy_reduces_to_plain_ce():
    # with margins (1, 0, 0) and scale s it's plain CE over s*logits
    logits = np.tanh(rng.randn(6, 10)).astype(np.float32)
    label = rng.randint(0, 10, (6,))
    got = F.margin_cross_entropy(t(logits), t(label), margin1=1.0,
                                 margin2=0.0, margin3=0.0, scale=4.0).numpy()
    want = tF.cross_entropy(torch.tensor(logits * 4.0),
                            torch.tensor(label)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # a real margin must increase the target-class loss
    harder = F.margin_cross_entropy(t(logits), t(label), margin2=0.3,
                                    scale=4.0).numpy()
    assert harder > got


def test_hsigmoid_loss_trains():
    paddle.seed(0)
    with unique_name.guard():
        layer = paddle.nn.HSigmoidLoss(16, num_classes=10)
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=layer.parameters())
    x = t(rng.randn(32, 16).astype(np.float32))
    y = t(rng.randint(0, 10, (32,)).astype(np.int64))
    losses = []
    for _ in range(25):
        per = layer(x, y)
        assert list(per.shape) == [32, 1]  # reference per-sample shape
        loss = per.mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.5 * losses[0], losses


def test_class_center_sample():
    label = t(np.array([2, 7, 7, 1], np.int64))
    remapped, sampled = F.class_center_sample(label, num_classes=20,
                                              num_samples=6)
    s = sampled.numpy()
    assert len(s) == 6 and len(set(s.tolist())) == 6
    for cls in (1, 2, 7):
        assert cls in s
    r = remapped.numpy()
    for orig, rm in zip([2, 7, 7, 1], r):
        assert s[rm] == orig


def test_gather_tree():
    # example from the reference docstring
    ids = t(np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                     np.int64))
    parents = t(np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                          [[0, 0], [0, 1]]], np.int64))
    out = F.gather_tree(ids, parents).numpy()
    want = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])
    np.testing.assert_array_equal(out, want)


def test_pairwise_distance_and_softmax2d():
    x = rng.randn(4, 6).astype(np.float32)
    y = rng.randn(4, 6).astype(np.float32)
    d = paddle.nn.PairwiseDistance(p=2.0)(t(x), t(y)).numpy()
    want = torch.pairwise_distance(torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(d, want, rtol=1e-4)

    img = rng.randn(2, 3, 4, 4).astype(np.float32)
    sm = paddle.nn.Softmax2D()(t(img)).numpy()
    np.testing.assert_allclose(sm.sum(axis=1), np.ones((2, 4, 4)),
                               rtol=1e-5)


def test_lu_unpack_reconstructs():
    a = rng.randn(5, 5).astype(np.float32)
    lu, piv = paddle.linalg.lu(t(a))
    P, L, U = paddle.linalg.lu_unpack(lu, piv)
    rec = P.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)


def test_beam_search_decoder_dynamic_decode():
    """Deterministic toy LM: from any state, token (state+1) % V has the
    highest logit — greedy path is 1,2,3,... until end_token."""
    V, B, beams = 6, 2, 3

    class ToyCell:
        def __call__(self, inputs, states):
            ids = inputs._value.astype(np.int64)
            nxt = (ids + 1) % V
            import jax.numpy as jnp
            import jax
            logits = jax.nn.one_hot(nxt, V) * 5.0
            return Tensor(logits), {"h": Tensor(states["h"]._value + 1.0)}

    dec = paddle.nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=4,
                                      beam_size=beams)
    inits = {"h": t(np.zeros((B, 1), np.float32))}
    out, final = paddle.nn.dynamic_decode(dec, inits=inits, max_step_num=10)
    ids = out.numpy()  # [batch, time, beam]
    assert ids.shape[0] == B and ids.shape[2] == beams
    # best beam follows 1,2,3,4 then pads with the end token while the
    # other beams drain
    np.testing.assert_array_equal(ids[0, :4, 0], [1, 2, 3, 4])
    assert (ids[0, 4:, 0] == 4).all()


def test_distributed_shims():
    import paddle_tpu.distributed as dist

    assert dist.ParallelMode.DATA_PARALLEL == 0
    dist.gloo_barrier()
    dist.gloo_release()
    with pytest.raises(RuntimeError, match="descoped"):
        dist.InMemoryDataset()
    with pytest.raises(RuntimeError, match="descoped"):
        dist.QueueDataset()
    assert hasattr(dist.launch, "launch")


def test_distributed_split_linear():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["mp_degree"] = 2
    fleet.init(is_collective=True, strategy=strategy)
    with unique_name.guard():
        paddle.seed(0)
        x = t(rng.randn(4, 8).astype(np.float32))
        out = paddle.distributed.split(x, (8, 6), "linear", axis=1,
                                       gather_out=True)
    assert list(out.shape) == [4, 6]
    assert np.isfinite(out.numpy()).all()
