"""RNN family: SimpleRNN/LSTM/GRU cells + stacks (reference
python/paddle/nn/layer/rnn.py). Recurrences cross-checked against torch
(same equations for RNN/LSTM; GRU uses paddle's reset-after-matmul form,
checked against a numpy reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def _np(t):
    return np.asarray(t._value)


def _set_cell_from_torch(cell, t_mod, suffix="l0"):
    cell.weight_ih._value = np.asarray(getattr(t_mod, f"weight_ih_{suffix}").detach())
    cell.weight_hh._value = np.asarray(getattr(t_mod, f"weight_hh_{suffix}").detach())
    cell.bias_ih._value = np.asarray(getattr(t_mod, f"bias_ih_{suffix}").detach())
    cell.bias_hh._value = np.asarray(getattr(t_mod, f"bias_hh_{suffix}").detach())


def test_lstm_matches_torch_single_layer():
    import torch

    torch.manual_seed(0)
    B, T, I, H = 3, 7, 5, 6
    t_lstm = torch.nn.LSTM(I, H, batch_first=True)
    x = np.random.RandomState(0).randn(B, T, I).astype(np.float32)
    with torch.no_grad():
        t_out, (t_h, t_c) = t_lstm(torch.tensor(x))

    paddle.seed(0)
    lstm = paddle.nn.LSTM(I, H)
    _set_cell_from_torch(lstm.cells[0], t_lstm)
    out, (h, c) = lstm(Tensor(x))
    np.testing.assert_allclose(_np(out), t_out.numpy(), atol=1e-5)
    np.testing.assert_allclose(_np(h)[0], t_h.numpy()[0], atol=1e-5)
    np.testing.assert_allclose(_np(c)[0], t_c.numpy()[0], atol=1e-5)


def test_simple_rnn_matches_torch_bidirectional():
    import torch

    torch.manual_seed(1)
    B, T, I, H = 2, 5, 4, 3
    t_rnn = torch.nn.RNN(I, H, batch_first=True, bidirectional=True)
    x = np.random.RandomState(1).randn(B, T, I).astype(np.float32)
    with torch.no_grad():
        t_out, t_h = t_rnn(torch.tensor(x))

    rnn = paddle.nn.SimpleRNN(I, H, direction="bidirect")
    _set_cell_from_torch(rnn.cells[0], t_rnn, "l0")
    _set_cell_from_torch(rnn.cells[1], t_rnn, "l0_reverse")
    out, h = rnn(Tensor(x))
    np.testing.assert_allclose(_np(out), t_out.numpy(), atol=1e-5)
    np.testing.assert_allclose(_np(h), t_h.numpy(), atol=1e-5)


def test_gru_against_numpy_reference():
    """Paddle GRU: r,z,c split; c = tanh(x_c + r*h_c); h = (h-c)*z + c."""
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.RandomState(2)
    gru = paddle.nn.GRU(I, H)
    cell = gru.cells[0]
    x = rng.randn(B, T, I).astype(np.float32)

    w_ih, w_hh = _np(cell.weight_ih), _np(cell.weight_hh)
    b_ih, b_hh = _np(cell.bias_ih), _np(cell.bias_hh)

    def sigmoid(a):
        return 1 / (1 + np.exp(-a))

    h = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        xg = x[:, t] @ w_ih.T + b_ih
        hg = h @ w_hh.T + b_hh
        x_r, x_z, x_c = np.split(xg, 3, axis=-1)
        h_r, h_z, h_c = np.split(hg, 3, axis=-1)
        r = sigmoid(x_r + h_r)
        z = sigmoid(x_z + h_z)
        c = np.tanh(x_c + r * h_c)
        h = (h - c) * z + c
        outs.append(h.copy())
    ref = np.stack(outs, axis=1)

    out, h_n = gru(Tensor(x))
    np.testing.assert_allclose(_np(out), ref, atol=1e-5)
    np.testing.assert_allclose(_np(h_n)[0], ref[:, -1], atol=1e-5)


def test_multilayer_and_cells_consistent():
    """2-layer LSTM == manually chaining the cells' python loop."""
    B, T, I, H = 2, 4, 3, 4
    paddle.seed(3)
    lstm = paddle.nn.LSTM(I, H, num_layers=2)
    x = np.random.RandomState(3).randn(B, T, I).astype(np.float32)
    out, (h_n, c_n) = lstm(Tensor(x))

    # manual: layer0 then layer1 via RNN wrapper over the cells
    r0 = paddle.nn.RNN(lstm.cells[0])
    r1 = paddle.nn.RNN(lstm.cells[1])
    o0, _ = r0(Tensor(x))
    o1, st1 = r1(o0)
    np.testing.assert_allclose(_np(out), _np(o1), atol=1e-5)
    np.testing.assert_allclose(_np(h_n)[1], _np(st1[0]), atol=1e-5)


def test_sequence_length_masking():
    B, T, I, H = 2, 6, 3, 4
    paddle.seed(4)
    rnn = paddle.nn.SimpleRNN(I, H)
    x = np.random.RandomState(4).randn(B, T, I).astype(np.float32)
    lens = np.array([4, 6], np.int32)
    out, h_n = rnn(Tensor(x), sequence_length=Tensor(lens))
    out_np = _np(out)
    # padded steps emit zeros
    np.testing.assert_allclose(out_np[0, 4:], 0.0, atol=1e-7)
    # final state for row 0 equals the step-4 output
    np.testing.assert_allclose(_np(h_n)[0][0], out_np[0, 3], atol=1e-6)
    # row 1 (full length) matches the unmasked run
    out_full, _ = rnn(Tensor(x))
    np.testing.assert_allclose(out_np[1], _np(out_full)[1], atol=1e-6)


def test_gradients_flow_and_train():
    B, T, I, H = 4, 8, 6, 8
    paddle.seed(5)
    lstm = paddle.nn.LSTM(I, H, num_layers=2, direction="bidirect")
    head = paddle.nn.Linear(2 * H, 1)
    params = lstm.parameters() + head.parameters()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=params)
    rng = np.random.RandomState(5)
    x = rng.randn(B, T, I).astype(np.float32)
    y = rng.randn(B, 1).astype(np.float32)

    losses = []
    for _ in range(8):
        out, _ = lstm(Tensor(x))
        pred = head(out[:, -1])
        loss = ((pred - Tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    assert losses[-1] < losses[0] * 0.5, losses


def test_cells_single_step():
    B, I, H = 2, 3, 4
    paddle.seed(6)
    for cell_cls, st in ((paddle.nn.SimpleRNNCell, 1),
                         (paddle.nn.LSTMCell, 2),
                         (paddle.nn.GRUCell, 1)):
        cell = cell_cls(I, H)
        x = Tensor(np.random.RandomState(6).randn(B, I).astype(np.float32))
        out, states = cell(x)
        assert list(out.shape) == [B, H]
        if st == 2:
            assert len(states) == 2
    with pytest.raises(ValueError):
        paddle.nn.SimpleRNNCell(3, -1)
    with pytest.raises(ValueError):
        paddle.nn.SimpleRNN(3, 4, direction="sideways")


def test_time_major_layout():
    B, T, I, H = 2, 5, 3, 4
    paddle.seed(7)
    rnn = paddle.nn.GRU(I, H, time_major=True)
    x = np.random.RandomState(7).randn(T, B, I).astype(np.float32)
    out, h_n = rnn(Tensor(x))
    assert list(out.shape) == [T, B, H]

    rnn2 = paddle.nn.GRU(I, H)
    rnn2.set_state_dict(rnn.state_dict())
    out2, _ = rnn2(Tensor(np.swapaxes(x, 0, 1)))
    np.testing.assert_allclose(_np(out), np.swapaxes(_np(out2), 0, 1), atol=1e-6)


def test_custom_cell_python_loop_masks_sequence_length():
    """The custom-cell fallback must honor sequence_length like the fused
    scan path does."""

    class MyCell(paddle.nn.RNNCellBase):
        def __init__(self, cell):
            super().__init__()
            self.inner = cell

        @property
        def state_shape(self):
            return self.inner.state_shape

        def forward(self, x, states=None):
            return self.inner(x, states)

    B, T, I, H = 2, 6, 3, 4
    paddle.seed(8)
    builtin = paddle.nn.SimpleRNNCell(I, H)
    custom = MyCell(builtin)
    x = np.random.RandomState(8).randn(B, T, I).astype(np.float32)
    lens = np.array([3, 6], np.int32)

    out_b, h_b = paddle.nn.RNN(builtin)(Tensor(x), sequence_length=Tensor(lens))
    out_c, h_c = paddle.nn.RNN(custom)(Tensor(x), sequence_length=Tensor(lens))
    np.testing.assert_allclose(_np(out_c), _np(out_b), atol=1e-6)
    np.testing.assert_allclose(_np(h_c), _np(h_b), atol=1e-6)
