"""ERNIE model family: embeddings with task ids, pretraining loss (fused,
biased LM head), task heads, knowledge-masking collator.

Reference: the ERNIE encoder shape the reference's fleet stack trains
(SURVEY §7 M5); fused-CE bias parity is checked against an explicit
logits+CE computation.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.models import (
    ErnieConfig,
    ErnieDataCollator,
    ErnieForPretraining,
    ErnieForQuestionAnswering,
    ErnieForSequenceClassification,
    ErnieForTokenClassification,
    ErnieModel,
)


def tiny_cfg(**kw):
    d = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
             intermediate_size=64, max_position_embeddings=32,
             hidden_dropout=0.0, attention_dropout=0.0)
    d.update(kw)
    return ErnieConfig(**d)


def ids(b=2, s=16, v=97, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(0, v, (b, s)).astype(np.int64))


def test_model_shapes_and_task_embedding_effect():
    paddle.seed(0)
    cfg = tiny_cfg()
    model = ErnieModel(cfg)
    x = ids()
    seq, pooled = model(x)
    assert list(seq.shape) == [2, 16, 32] and list(pooled.shape) == [2, 32]
    # a different task id must change the representation (task embedding
    # actually participates in the input sum)
    task1 = paddle.to_tensor(np.ones((2, 16), np.int64))
    seq2, _ = model(x, task_type_ids=task1)
    assert not np.allclose(seq.numpy(), seq2.numpy())
    # use_task_id=False drops the table entirely
    paddle.seed(0)
    m2 = ErnieModel(tiny_cfg(use_task_id=False))
    names = [n for n, _ in m2.named_parameters()]
    assert not any("task_type" in n for n in names)


def test_pretraining_loss_matches_unfused_reference():
    paddle.seed(1)
    cfg = tiny_cfg()
    model = ErnieForPretraining(cfg)
    x = ids(seed=1)
    labels_np = np.full((2, 16), -100, np.int64)
    labels_np[:, ::3] = np.random.RandomState(2).randint(0, 97, labels_np[:, ::3].shape)
    labels = paddle.to_tensor(labels_np)

    loss = model.loss(x, labels)
    # unfused reference: explicit biased logits + masked CE
    logits, _ = model(x)
    lp = logits.numpy().astype(np.float64)
    lse = np.log(np.exp(lp - lp.max(-1, keepdims=True)).sum(-1)) + lp.max(-1)
    mask = labels_np != -100
    picked = np.take_along_axis(
        lp, np.where(mask, labels_np, 0)[..., None], axis=-1)[..., 0]
    ref = ((lse - picked) * mask).sum() / mask.sum()
    np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-4)


def test_pretraining_trains_and_bias_gets_gradient():
    paddle.seed(3)
    cfg = tiny_cfg()
    model = ErnieForPretraining(cfg)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    x = ids(seed=3)
    labels = paddle.to_tensor(
        np.random.RandomState(4).randint(0, 97, (2, 16)).astype(np.int64))
    nsp = paddle.to_tensor(np.array([0, 1], np.int64))
    losses = []
    for _ in range(8):
        loss = model.loss(x, labels, nsp_labels=nsp)
        loss.backward()
        assert model.lm_head.decoder_bias.grad is not None
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses


def test_task_heads_shapes():
    cfg = tiny_cfg()
    x = ids()
    cls = ErnieForSequenceClassification(cfg, num_classes=5)
    assert list(cls(x).shape) == [2, 5]
    tok = ErnieForTokenClassification(cfg, num_classes=7)
    assert list(tok(x).shape) == [2, 16, 7]
    qa = ErnieForQuestionAnswering(cfg)
    start, end = qa(x)
    assert list(start.shape) == [2, 16] and list(end.shape) == [2, 16]


def test_attention_mask_blocks_padding():
    paddle.seed(5)
    cfg = tiny_cfg()
    model = ErnieModel(cfg)
    x = ids(seed=5)
    mask = np.ones((2, 16), np.float32)
    mask[:, 8:] = 0.0
    seq_m, _ = model(x, attention_mask=paddle.to_tensor(mask))
    # changing PADDED tokens must not change unpadded outputs
    x2_np = x.numpy().copy()
    x2_np[:, 8:] = (x2_np[:, 8:] + 1) % 97
    seq_m2, _ = model(paddle.to_tensor(x2_np), attention_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(seq_m.numpy()[:, :8], seq_m2.numpy()[:, :8],
                               rtol=1e-4, atol=1e-5)


def test_collator_spans_and_labels():
    coll = ErnieDataCollator(vocab_size=97, mask_token_id=3, mlm_prob=0.2,
                            max_span=3, seed=0)
    batch = np.random.RandomState(6).randint(4, 97, (4, 32)).astype(np.int64)
    ids_out, labels = coll(batch)
    masked = labels != -100
    assert masked.any()
    # labels hold the ORIGINAL ids at masked positions
    np.testing.assert_array_equal(labels[masked], batch[masked])
    # most masked positions show the mask token (80/10/10 rule)
    frac_masktok = (ids_out[masked] == 3).mean()
    assert frac_masktok > 0.5
    # unmasked positions untouched
    np.testing.assert_array_equal(ids_out[~masked], batch[~masked])


def test_fused_ce_bias_gradcheck():
    """Direct check of the new bias path in fused_linear_cross_entropy."""
    rng = np.random.RandomState(7)
    h = paddle.to_tensor(rng.randn(6, 8).astype(np.float32), stop_gradient=False)
    w = paddle.to_tensor(rng.randn(13, 8).astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(rng.randn(13).astype(np.float32), stop_gradient=False)
    y = paddle.to_tensor(rng.randint(0, 13, (6,)).astype(np.int64))
    loss = F.fused_linear_cross_entropy(h, w, y, bias=b)
    # reference via explicit logits
    logits = paddle.to_tensor(h.numpy() @ w.numpy().T + b.numpy(),
                              stop_gradient=False)
    ref = F.cross_entropy(logits, y.reshape([-1, 1])).mean()
    np.testing.assert_allclose(float(loss.numpy()), float(ref.numpy()), rtol=1e-5)
    loss.backward()
    ref.backward()
    dlogits = logits.grad.numpy()
    np.testing.assert_allclose(b.grad.numpy(), dlogits.sum(0), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), dlogits.T @ h.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h.grad.numpy(), dlogits @ w.numpy(),
                               rtol=1e-4, atol=1e-5)
