"""Runtime telemetry layer (profiler/telemetry.py): phase timeline,
pipeline counters, recompile detection, exporters, and the
zero-overhead-when-disabled contract across DeviceLoader / CompiledStep /
AsyncMetricBuffer / Model.fit."""
import glob
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.callbacks import TelemetryLogger
from paddle_tpu.io import Dataset
from paddle_tpu.io.device_loader import DeviceLoader
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.metric import AsyncMetricBuffer
from paddle_tpu.nn import CrossEntropyLoss
from paddle_tpu.profiler import telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _compiled_linear_step(in_dim=3):
    paddle.seed(0)
    lin = paddle.nn.Linear(in_dim, 3)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

    def train_step(x):
        loss = lin(x).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return CompiledStep(train_step, stateful=[lin, opt])


# ---------------------------------------------------------------------------
# registry basics
# ---------------------------------------------------------------------------

def test_disabled_by_default_and_null_span_singleton():
    assert not telemetry.enabled()
    # the disabled-path span is a shared no-op object: no allocation, no
    # timing, no locking — the zero-overhead contract
    s1 = telemetry.phase_span("data_wait")
    s2 = telemetry.phase_span("dispatch")
    assert s1 is s2
    with s1:
        pass
    tm = telemetry.get_telemetry()
    assert tm.counters() == {}
    assert tm.steps() == []
    assert telemetry.summary()["phases"] == {}


def test_counters_gauges_histograms_and_reset():
    telemetry.enable()
    tm = telemetry.get_telemetry()
    tm.inc("a")
    tm.inc("a", 2)
    tm.set_gauge("g", 7.5)
    tm.observe("lat", 0.25)
    tm.observe("lat", 0.75)
    assert tm.counters()["a"] == 3
    assert tm.gauges()["g"] == 7.5
    stat = tm.get("lat")
    assert stat["count"] == 2 and stat["sum"] == 1.0
    telemetry.reset()
    assert tm.counters() == {} and tm.gauges() == {} and tm.get("lat") == {}


def test_phase_span_and_step_records():
    telemetry.enable()
    telemetry.step_begin()
    with telemetry.phase_span("data_wait"):
        time.sleep(0.002)
    with telemetry.phase_span("dispatch"):
        pass
    telemetry.step_end()
    recs = telemetry.get_telemetry().steps()
    assert len(recs) == 1
    assert recs[0].phases["data_wait"] >= 0.002
    assert "dispatch" in recs[0].phases
    assert recs[0].wall_s >= recs[0].phases["data_wait"]
    # empty records are dropped, not ring-polluting
    telemetry.step_begin()
    telemetry.step_end()
    assert len(telemetry.get_telemetry().steps()) == 1


def test_ring_buffer_bounded():
    telemetry.enable(ring_size=8)
    try:
        for _ in range(50):
            telemetry.step_begin()
            with telemetry.phase_span("dispatch"):
                pass
        telemetry.step_end()
        tm = telemetry.get_telemetry()
        assert len(tm.steps()) == 8
        assert len(tm.chrome_spans()) <= 8 * 8
        # histograms still saw every span
        assert tm.get("phase.dispatch")["count"] == 50
    finally:
        telemetry.enable(ring_size=1024)  # restore default bound


# ---------------------------------------------------------------------------
# DeviceLoader stall accounting
# ---------------------------------------------------------------------------

def test_device_loader_stall_accounting():
    telemetry.enable()

    def slow_source():
        for i in range(4):
            time.sleep(0.01)  # slower than the consumer: forced misses
            yield (np.full((2, 4), i, np.float32),)

    for _ in DeviceLoader(slow_source()):
        pass
    c = telemetry.get_telemetry().counters()
    assert c["device_loader.prefetch_miss"] >= 3
    assert c["device_loader.stall_s"] >= 0.02
    assert c["device_loader.batches_staged"] == 4
    # 4 batches x 2x4 float32
    assert c["device_loader.bytes_staged"] == 4 * 2 * 4 * 4
    # a finished loader retires its point-in-time gauges (queue depth)
    # so the next report() doesn't show stale device stats; cumulative
    # counters (asserted above) survive
    assert "device_loader.queue_depth" not in \
        telemetry.get_telemetry().gauges()
    # the waits landed in the data_wait phase histogram
    assert telemetry.summary()["phases"]["data_wait"]["count"] >= 4


def test_device_loader_prefetch_hits_with_slow_consumer():
    telemetry.enable()
    batches = [(np.zeros((2, 2), np.float32),) for _ in range(5)]
    for _ in DeviceLoader(batches, buffer_size=4):
        time.sleep(0.005)  # let the stager run ahead
    c = telemetry.get_telemetry().counters()
    assert c.get("device_loader.prefetch_hit", 0) >= 2


def test_device_loader_untouched_when_disabled():
    assert not telemetry.enabled()
    for _ in DeviceLoader([(np.zeros((2, 2), np.float32),) for _ in range(3)]):
        pass
    assert telemetry.get_telemetry().counters() == {}
    assert telemetry.get_telemetry().steps() == []


# ---------------------------------------------------------------------------
# CompiledStep compile/dispatch attribution + recompile detection
# ---------------------------------------------------------------------------

def test_compiled_step_compile_then_dispatch():
    telemetry.enable()
    step = _compiled_linear_step()
    x = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32))
    step(x)
    tm = telemetry.get_telemetry()
    first_compiles = tm.counters()["compile.count"]
    assert first_compiles >= 1
    step(x)
    step(x)
    c = tm.counters()
    assert c["compile.count"] == first_compiles  # cached: no retrace
    assert telemetry.summary()["phases"]["dispatch"]["count"] >= 2


def test_recompile_warning_on_shape_churn():
    telemetry.enable(recompile_warn_threshold=2)
    try:
        step = _compiled_linear_step()
        with pytest.warns(RuntimeWarning, match="recompilation churn"):
            for n in range(3, 7):  # every batch a new shape -> retrace each
                step(paddle.to_tensor(
                    np.random.randn(n, 3).astype(np.float32)))
        assert telemetry.get_telemetry().compile_counts()["train_step"] >= 3
        assert telemetry.summary()["recompile_count"] >= 2
    finally:
        telemetry.enable(recompile_warn_threshold=3)


def test_recompile_warning_fires_once():
    telemetry.enable(recompile_warn_threshold=1)
    try:
        step = _compiled_linear_step()
        import warnings as w

        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            for n in range(3, 8):
                step(paddle.to_tensor(
                    np.random.randn(n, 3).astype(np.float32)))
        churn = [x for x in caught if "recompilation churn" in str(x.message)]
        assert len(churn) == 1
    finally:
        telemetry.enable(recompile_warn_threshold=3)


# ---------------------------------------------------------------------------
# AsyncMetricBuffer readback accounting
# ---------------------------------------------------------------------------

def test_async_buffer_readback_counters():
    telemetry.enable()
    buf = AsyncMetricBuffer()
    for v in (1.0, 2.0, 3.0):
        buf.append(paddle.to_tensor(np.float32(v)))
    assert buf.drain() == [1.0, 2.0, 3.0]
    c = telemetry.get_telemetry().counters()
    assert c["metric.fences"] == 1
    assert c["metric.scalars_read"] == 3
    assert telemetry.summary()["phases"]["readback"]["count"] == 1
    # empty drain is not a fence
    buf.drain()
    assert telemetry.get_telemetry().counters()["metric.fences"] == 1


# ---------------------------------------------------------------------------
# Model.fit end-to-end (acceptance criteria)
# ---------------------------------------------------------------------------

class _ToyDS(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _prepared_model():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=net.parameters())
    model.prepare(opt, CrossEntropyLoss())
    return model


def test_model_fit_with_telemetry_logger(tmp_path, capsys):
    logdir = str(tmp_path / "telemetry")
    model = _prepared_model()
    cb = TelemetryLogger(log_dir=logdir, log_freq=2, print_report=True)
    model.fit(_ToyDS(), batch_size=16, epochs=2, verbose=0, callbacks=[cb])

    # JSONL scalars landed
    files = glob.glob(logdir + "/*.jsonl")
    assert files, "TelemetryLogger wrote no JSONL"
    tags = {json.loads(l)["tag"] for l in open(files[-1]) if l.strip()}
    assert any(t.startswith("telemetry/phase/data_wait") for t in tags)
    assert any(t.startswith("telemetry/phase/dispatch") for t in tags)
    assert "telemetry/counter/compile.count" in tags
    assert "telemetry/gauge/device_loader.queue_depth" in tags

    # report table: nonzero data_wait/dispatch, recompile counter, queue
    # stats (printed at train end by the callback)
    table = capsys.readouterr().out
    assert "data_wait" in table and "dispatch" in table
    assert "compile.count" in table
    assert "device_loader.prefetch_hit" in table or \
        "device_loader.prefetch_miss" in table
    s = telemetry.summary()
    assert s["phases"]["data_wait"]["sum"] > 0
    assert s["phases"]["dispatch"]["sum"] > 0
    assert s["counters"]["compile.count"] >= 1
    assert s["steps_recorded"] >= 8  # 2 epochs x 4 batches
    # the callback turned telemetry back off after the run
    assert not telemetry.enabled()


def test_model_fit_disabled_is_zero_overhead():
    """With telemetry disabled, the instrumented fit loop must do NO
    telemetry work: nothing recorded, no step records, no counters."""
    model = _prepared_model()
    model.fit(_ToyDS(), batch_size=16, epochs=1, verbose=0)
    tm = telemetry.get_telemetry()
    assert not telemetry.enabled()
    assert tm.counters() == {}
    assert tm.gauges() == {}
    assert tm.steps() == []
    assert tm.chrome_spans() == []
    assert telemetry.summary()["phases"] == {}
    # and the disabled-path guard itself is trivially cheap (no-op span +
    # flag check, generous bound to stay robust on loaded CI hosts)
    t0 = time.perf_counter()
    for _ in range(100_000):
        telemetry.enabled()
        telemetry.step_begin()
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"disabled-path guard too slow: {dt:.3f}s / 100k calls"


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_export_scalars_and_report_tool_roundtrip(tmp_path):
    import os
    import subprocess
    import sys

    telemetry.enable()
    tm = telemetry.get_telemetry()
    for _ in range(3):
        telemetry.step_begin()
        for phase in telemetry.PHASES:
            with telemetry.phase_span(phase):
                pass
    telemetry.step_end()
    tm.inc("device_loader.prefetch_hit", 5)
    tm.set_gauge("device_loader.queue_depth", 2)
    from paddle_tpu.utils.log_writer import LogWriter

    with LogWriter(str(tmp_path), file_name="t.jsonl") as w:
        tm.export_scalars(w, step=3)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "telemetry_report.py"),
         str(tmp_path / "t.jsonl")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    for phase in telemetry.PHASES:
        assert phase in out.stdout
    assert "prefetch_hit" in out.stdout
    assert "queue_depth" in out.stdout


def test_profiler_merges_telemetry_spans():
    from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent

    telemetry.enable()
    prof = Profiler(targets=[ProfilerTarget.CPU],
                    on_trace_ready=lambda p: None)
    with prof:
        with RecordEvent("host_span"):
            with telemetry.phase_span("dispatch"):
                time.sleep(0.001)
    names = [e.name for e in prof.profiler_result.events]
    assert "host_span" in names
    assert "telemetry::dispatch" in names
    tel = [e for e in prof.profiler_result.events
           if e.name == "telemetry::dispatch"]
    assert tel[0].event_type == "Telemetry"
    assert tel[0].end_ns - tel[0].start_ns >= 1_000_000  # the 1ms sleep


def test_bench_telemetry_block():
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    from bench_common import measure_steps, telemetry_block

    step = _compiled_linear_step(in_dim=4)
    batches = [(np.random.randn(4, 4).astype(np.float32),)
               for _ in range(8)]
    total, vals = measure_steps(step, batches, iters=5, warmup=3)
    assert len(vals) == 5
    blk = telemetry_block(total, 5)
    assert blk["steps_per_sec"] > 0
    assert 0.0 <= blk["data_wait_frac"] <= 1.0
    assert blk["compile_count"] >= 1
    assert "dispatch" in blk["phase_s"] or "compile" in blk["phase_s"]
    assert blk["prefetch"]["bytes_staged"] > 0
    # measure_steps turned telemetry back off but kept the data readable
    assert not telemetry.enabled()
    assert telemetry.summary()["steps_recorded"] >= 5
