"""BERT model family + new vision models (DenseNet/AlexNet/SqueezeNet).
References: BASELINE.md BERT metric; python/paddle/vision/models/."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.models import (BertConfig, BertForPretraining,
                               BertForSequenceClassification, BertModel,
                               bert_base, bert_large)
from paddle_tpu.utils import unique_name


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_cache():
    """Dodge the conftest KNOWN HAZARD: a same-host persistent-cache
    round-trip of this module's executables SIGABRTs mid-suite
    (cpu_aot_loader), and whether the broken deserialization path is hit
    depends on which in-memory executables the preceding modules left
    behind. Compile fresh for this module instead of loading from the
    cache. Flipping the flag alone is not enough — jax memoizes the
    use-the-cache decision at the first compile of the process
    (compilation_cache._cache_checked), so reset it on the way in AND on
    the way out to restore warm-cache behavior for later modules."""
    from jax._src import compilation_cache

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    compilation_cache.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", old)
    compilation_cache.reset_cache()


def _tiny_cfg():
    return BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=2, intermediate_size=64,
                      max_position_embeddings=64, type_vocab_size=2,
                      hidden_dropout=0.0, attention_dropout=0.0)


def test_bert_configs():
    assert bert_base().num_layers == 12
    lg = bert_large()
    assert lg.hidden_size == 1024 and lg.num_layers == 24 and lg.num_heads == 16


def test_bert_forward_shapes_and_padding_mask():
    paddle.seed(0)
    m = BertModel(_tiny_cfg())
    m.eval()
    ids = Tensor(np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64))
    seq, pooled = m(ids)
    assert list(seq.shape) == [2, 16, 32] and list(pooled.shape) == [2, 32]

    # padding mask: padded positions must not affect unpadded outputs
    mask = np.ones((2, 16), np.float32)
    mask[:, 12:] = 0.0
    seq_m, _ = m(ids, attention_mask=Tensor(mask))
    ids2 = np.asarray(ids._value).copy()
    ids2[:, 12:] = 7  # change padded content
    seq_m2, _ = m(Tensor(ids2), attention_mask=Tensor(mask))
    np.testing.assert_allclose(np.asarray(seq_m._value)[:, :12],
                               np.asarray(seq_m2._value)[:, :12], atol=1e-5)


def test_bert_pretraining_trains_with_fused_mlm():
    paddle.seed(1)
    model = BertForPretraining(_tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    rng = np.random.RandomState(1)
    ids = Tensor(rng.randint(0, 128, (4, 16)).astype(np.int64))
    labels = rng.randint(0, 128, (4, 16)).astype(np.int64)
    labels[:, ::3] = -100  # unmasked positions ignored
    nsp = Tensor(rng.randint(0, 2, (4,)).astype(np.int64))

    from paddle_tpu.jit.functionalize import CompiledStep

    def step(ids, mlm, nsp):
        loss = model.loss(ids, mlm, nsp_labels=nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[model, opt])
    l0 = float(np.asarray(cs(ids, Tensor(labels), nsp)._value))
    for _ in range(6):
        l1 = float(np.asarray(cs(ids, Tensor(labels), nsp)._value))
    assert np.isfinite(l1) and l1 < l0

    # fused loss == unfused full-logits loss
    model.eval()
    logits, _ = model(ids)
    import paddle_tpu.nn.functional as F

    fused = float(np.asarray(model.loss(ids, Tensor(labels))._value))
    ref2 = float(np.asarray(F.cross_entropy(
        logits.reshape([-1, 128]), Tensor(labels.reshape(-1, 1)),
        ignore_index=-100)._value))
    np.testing.assert_allclose(fused, ref2, rtol=1e-5)


def test_bert_classifier():
    paddle.seed(2)
    m = BertForSequenceClassification(_tiny_cfg(), num_classes=3)
    m.eval()
    ids = Tensor(np.random.RandomState(2).randint(0, 128, (2, 8)).astype(np.int64))
    out = m(ids)
    assert list(out.shape) == [2, 3]


@pytest.mark.parametrize("factory,expect_params", [
    ("densenet121", None), ("alexnet", None), ("squeezenet1_1", None),
])
def test_vision_models_forward(factory, expect_params):
    from paddle_tpu.vision import models as M

    paddle.seed(3)
    net = getattr(M, factory)(num_classes=10)
    net.eval()
    x = Tensor(np.random.RandomState(3).randn(1, 3, 64, 64).astype(np.float32))
    out = net(x)
    assert list(out.shape) == [1, 10]
    assert len(net.parameters()) > 5
    with pytest.raises(ValueError):
        getattr(M, factory)(pretrained=True)


def test_densenet_channel_math():
    from paddle_tpu.vision.models import DenseNet

    with pytest.raises(ValueError):
        DenseNet(layers=123)
    net = DenseNet(layers=121, num_classes=4)
    net.eval()
    x = Tensor(np.random.RandomState(4).randn(1, 3, 32, 32).astype(np.float32))
    assert list(net(x).shape) == [1, 4]
