"""Async device pipeline: ``io.DeviceLoader`` staging (ordering,
back-pressure, shutdown), ``CompiledStep(donate_inputs=True)`` aliasing,
deferred loss readback equivalence (``metric.AsyncMetricBuffer``) in
``hapi.Model.fit`` and auto-parallel ``Engine.fit`` on the 8-device CPU
mesh, and the planner's eval-mode/BN trace regression."""
import threading
import time
import warnings

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io import DataLoader, DeviceLoader, TensorDataset
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.metric import AsyncMetricBuffer


# ---------------------------------------------------------------------------
# DeviceLoader mechanics
# ---------------------------------------------------------------------------
def _batches(n, shape=(4, 3)):
    rng = np.random.RandomState(0)
    return [(Tensor(rng.randn(*shape).astype(np.float32)),
             Tensor(np.full(shape, i, np.float32))) for i in range(n)]


def test_device_loader_preserves_order_and_values():
    data = _batches(12)
    staged = list(DeviceLoader(data, buffer_size=3))
    assert len(staged) == 12
    for i, (x, y) in enumerate(staged):
        assert isinstance(x, Tensor) and isinstance(y, Tensor)
        assert isinstance(x._value, jax.Array)
        np.testing.assert_array_equal(np.asarray(y._value), i)
        np.testing.assert_array_equal(np.asarray(x._value),
                                      np.asarray(data[i][0]._value))


def test_device_loader_is_reiterable_per_epoch():
    data = _batches(4)
    dl = DeviceLoader(data, buffer_size=2)
    for _ in range(3):  # one staging pass per epoch over a re-iterable source
        got = [float(np.asarray(y._value[0, 0])) for _, y in dl]
        assert got == [0.0, 1.0, 2.0, 3.0]


def test_device_loader_back_pressure_bounds_prefetch():
    pulled = []
    produced = threading.Event()

    def source():
        for i in range(50):
            pulled.append(i)
            produced.set()
            yield (np.full((2, 2), i, np.float32),)

    dl = DeviceLoader(source(), buffer_size=2)
    it = iter(dl)
    next(it)
    # consumer idles: the stager may run at most buffer_size ahead of the
    # single consumed batch, plus the one batch in its hands
    deadline = time.time() + 2.0
    while time.time() < deadline and len(pulled) < 4:
        time.sleep(0.02)
    time.sleep(0.2)  # would overrun well past the bound if unbounded
    assert 1 <= len(pulled) <= 1 + dl.buffer_size + 1, pulled
    it.close()


def test_device_loader_shutdown_on_early_break():
    dl = DeviceLoader(_batches(100), buffer_size=2)
    it = iter(dl)
    for _ in range(3):
        next(it)
    it.close()  # early abandon: the stager thread must terminate
    deadline = time.time() + 5.0
    while time.time() < deadline and dl._live_threads:
        time.sleep(0.02)
    assert not dl._live_threads
    dl.shutdown()  # idempotent


def test_device_loader_propagates_source_errors():
    def source():
        yield (np.ones((2, 2), np.float32),)
        raise RuntimeError("boom in the loader")

    with pytest.raises(RuntimeError, match="boom in the loader"):
        list(DeviceLoader(source(), buffer_size=2))


def test_device_loader_place_fn_shards_onto_mesh():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    def place(arr):
        spec = [None] * np.ndim(arr)
        if np.ndim(arr) and np.shape(arr)[0] % 8 == 0:
            spec[0] = "dp"
        return jax.device_put(arr, NamedSharding(mesh, P(*spec)))

    data = [(Tensor(np.arange(32, dtype=np.float32).reshape(8, 4)),)]
    ((x,),) = tuple(DeviceLoader(data, place_fn=place))
    assert x._value.sharding.spec == P("dp", None)
    np.testing.assert_array_equal(np.asarray(x._value),
                                  np.arange(32, dtype=np.float32).reshape(8, 4))


def test_device_loader_passes_non_array_leaves():
    data = [([Tensor(np.ones((2, 2), np.float32)), "tag", 7],)]
    ((batch,),) = tuple(DeviceLoader(data))
    assert batch[1] == "tag" and batch[2] == 7


# ---------------------------------------------------------------------------
# donated-input aliasing with CompiledStep
# ---------------------------------------------------------------------------
def test_compiled_step_donate_inputs_consumes_staged_batch():
    # shape-preserving output so XLA can alias the donated input buffer
    step = CompiledStep(lambda x: x * 2.0, donate_inputs=True)
    (staged,) = list(DeviceLoader([Tensor(np.ones((64, 64), np.float32))]))
    out = step(staged)
    np.testing.assert_array_equal(np.asarray(out._value), 2.0)
    # the staged batch was CONSUMED: its buffer is gone
    assert staged._value.is_deleted()
    with pytest.raises(RuntimeError):
        np.asarray(staged._value)


def test_compiled_step_donate_inputs_off_by_default():
    step = CompiledStep(lambda x: x * 2.0)
    x = Tensor(np.ones((8, 8), np.float32))
    step(x)
    assert not x._value.is_deleted()
    np.testing.assert_array_equal(np.asarray(x._value), 1.0)  # still usable


def test_donated_training_chain_matches_undonated():
    """A full train loop over donated staged batches must produce the same
    losses as the plain per-step path (donation never changes numerics)."""

    def build():
        paddle.seed(7)
        net = nn.Linear(6, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())

        def train(x, y):
            loss = nn.MSELoss()(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return net, opt, train

    rng = np.random.RandomState(3)
    data = [(rng.randn(8, 6).astype(np.float32),
             rng.randn(8, 1).astype(np.float32)) for _ in range(6)]

    net, opt, fn = build()
    step = CompiledStep(fn, stateful=[net, opt], donate_state=True)
    ref = [float(np.asarray(step(Tensor(x), Tensor(y))._value))
           for x, y in data]

    net, opt, fn = build()
    step = CompiledStep(fn, stateful=[net, opt], donate_state=True,
                        donate_inputs=True)
    buf = AsyncMetricBuffer()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # CPU may decline some donations
        for batch in DeviceLoader(data, buffer_size=2):
            buf.append(step(*batch))
    assert buf.num_pending == len(data)  # nothing fenced inside the loop
    assert buf.result() == ref


# ---------------------------------------------------------------------------
# AsyncMetricBuffer
# ---------------------------------------------------------------------------
def test_async_metric_buffer_defers_and_orders():
    buf = AsyncMetricBuffer()
    vals = [Tensor(np.asarray(float(i))) for i in range(5)]
    for v in vals[:3]:
        buf.append(v)
    assert buf.num_pending == 3 and buf.values == []
    assert buf.last() is None
    assert buf.drain() == [0.0, 1.0, 2.0]
    for v in vals[3:]:
        buf.append(v)
    assert buf.result() == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert buf.last() == 4.0
    assert buf.drain() == []  # idempotent when nothing is pending


# ---------------------------------------------------------------------------
# hapi.Model.fit: deferred readback, fences only at log_freq boundaries
# ---------------------------------------------------------------------------
class _ToyRegression:
    def __init__(self, n=48, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = rng.randn(n, 1).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _toy_model(lr=0.05):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=lr, parameters=net.parameters())
    model.prepare(opt, nn.MSELoss())
    return model


def test_fit_fences_only_at_log_freq_boundaries(monkeypatch):
    """12 steps at log_freq=5: drains happen at step 0 (seed the logs),
    steps 5 and 10 (boundaries), and epoch end — never in between."""
    drain_at = []
    orig_drain = AsyncMetricBuffer.drain

    def counting_drain(self):
        drain_at.append(len(self.values) + self.num_pending)
        return orig_drain(self)

    monkeypatch.setattr(AsyncMetricBuffer, "drain", counting_drain)
    model = _toy_model()
    model.fit(_ToyRegression(48), batch_size=4, epochs=1, log_freq=5,
              verbose=0)
    # drains observed with 1 (step 0), 5, 10 (freq boundaries) and 12
    # (epoch end) losses issued — i.e. 8 of the 12 steps never synchronized
    assert drain_at == [1, 5, 10, 12], drain_at


def test_fit_deferred_history_matches_eager_train_batch():
    """Pipelined fit (DeviceLoader + deferred fences) must reproduce the
    eager per-step float(loss) history bit-exactly."""
    losses = []

    class Track(paddle.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            losses.append(logs["loss"])

    model = _toy_model()
    model.fit(_ToyRegression(48), batch_size=4, epochs=1, shuffle=False,
              verbose=0, callbacks=[Track()])

    ref_model = _toy_model()  # same seed -> identical init
    loader = DataLoader(_ToyRegression(48), batch_size=4, shuffle=False)
    ref = [ref_model.train_batch([x], [y])[0] for x, y in loader]
    assert losses[-1] == ref[-1]


def test_evaluate_still_reports_loss_and_metrics():
    model = _toy_model()
    model.fit(_ToyRegression(48), batch_size=8, epochs=2, verbose=0)
    ev = model.evaluate(_ToyRegression(24, seed=1), batch_size=8, verbose=0)
    assert np.isfinite(ev["loss"])
    assert ev["eval_samples"] == 24


# ---------------------------------------------------------------------------
# Engine on the 8-device mesh: pipelined history parity + planner regression
# ---------------------------------------------------------------------------
needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def _engine_fixture(with_bn=False, seed=0):
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    paddle.seed(seed)
    layers = [nn.Linear(8, 16)]
    if with_bn:
        layers.append(nn.BatchNorm1D(16))
    layers += [nn.ReLU(), nn.Linear(16, 4)]
    model = nn.Sequential(*layers)
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = rng.randn(32, 4).astype(np.float32)
    ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
    return Engine(model=model, loss=nn.MSELoss(), optimizer=opt), ds


@needs_mesh
def test_engine_pipelined_history_matches_synchronous():
    """Engine.fit with the async pipeline (prefetch+deferred fences) must
    produce bit-identical per-step losses to the synchronous path."""
    eng, ds = _engine_fixture()
    loader = DataLoader(ds, batch_size=8, shuffle=False, drop_last=True)
    hist = eng.fit(loader, epochs=1, prefetch=2, log_freq=100)["loss"]

    eng2, ds2 = _engine_fixture()
    loader2 = DataLoader(ds2, batch_size=8, shuffle=False, drop_last=True)
    ref = eng2.fit(loader2, epochs=1, prefetch=0)["loss"]
    assert hist == ref
    assert len(hist) == 4 and all(np.isfinite(v) for v in hist)


@needs_mesh
def test_engine_fit_strategy_none_with_batchnorm_does_not_crash():
    """Planner regression (ADVICE high): the cost-model trace must run in
    eval() mode with buffers snapshot/restored — BN running-stat updates
    under jit left tracers in model state and crashed fit."""
    eng, ds = _engine_fixture(with_bn=True)
    assert eng._auto_plan_pending  # strategy=None, no mesh, 8 devices
    hist = eng.fit(ds, batch_size=8, epochs=1)["loss"]
    assert len(hist) == 4 and all(np.isfinite(v) for v in hist)
    # the trace ran under eval(): fit must resume in train mode with clean
    # (concrete, non-tracer) buffers
    assert eng.model.training
    for b in eng.model.buffers():
        assert isinstance(b._value, jax.Array)
        assert not isinstance(b._value, jax.core.Tracer)


@needs_mesh
def test_engine_evaluate_defers_readback():
    eng, ds = _engine_fixture()
    eng.fit(ds, batch_size=8, epochs=1)
    logs = eng.evaluate(ds, batch_size=8)
    assert np.isfinite(logs["loss"])
