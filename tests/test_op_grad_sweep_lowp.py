"""bf16/fp16 gradient sweep over the differentiable op surface (round-5
VERDICT item 4). Every entry of paddle_tpu/ops/op_table.py additionally
runs in bfloat16 AND float16 — the framework's actual training dtypes —
with the analytic low-precision gradient compared against the fp32
analytic gradient at representable input points (reference discipline:
``unittests/op_test.py:1851`` per-dtype check_grad). Skips/deviations are
declared in the table's LOWP map, with reasons."""
import numpy as np
import pytest

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.op_table import LOWP, LOWP_DEFAULT, OPS

from tests.op_test import check_grad_lowp
from tests.test_op_grad_sweep import _ADAPTERS, _draw, _ids, _resolve  # noqa: F401


def _cases():
    ids = _ids()
    out = []
    for e, eid in zip(OPS, ids):
        for dtype in ("bfloat16", "float16"):
            out.append(pytest.param(e, dtype, id=f"{eid}-{dtype}"))
    return out


def test_lowp_axis_covers_table():
    """>=150 entries x 2 dtypes actually checked (VERDICT done-criterion)."""
    active = [e for e in OPS if LOWP.get(e["api"]) is not False]
    assert len(active) >= 150, len(active)


@pytest.mark.parametrize("entry,dtype", _cases())
def test_op_gradient_lowp(entry, dtype):
    spec = LOWP.get(entry["api"])
    if spec is False:
        pytest.skip(f"{entry['api']}: low-precision skipped (see LOWP map)")
    if isinstance(spec, dict) and spec.get(dtype) is False:
        pytest.skip(f"{entry['api']}: {dtype} skipped (see LOWP map)")
    tol = dict(LOWP_DEFAULT[dtype])
    if isinstance(spec, dict):
        tol.update(spec.get(dtype, {}))

    fn = _resolve(entry["api"])
    assert fn is not None, entry["api"]
    import zlib

    rng = np.random.RandomState(zlib.crc32(entry["api"].encode()) % (2**31))
    arrays = [_draw(s, d, rng) for s, d in entry["inputs"]]
    diffable = [
        i for i, (s, d) in enumerate(entry["inputs"])
        if not (d == "bool" or d == "sign" or d.startswith("int:"))
    ]
    if entry["only"] is not None:
        diffable = [i for i in diffable if i in entry["only"]]

    kwargs = entry["kwargs"]
    fixed = {i: Tensor(a) for i, a in enumerate(arrays) if i not in diffable}

    def wrapped(*diff_tensors):
        args = []
        it = iter(diff_tensors)
        for i in range(len(arrays)):
            args.append(fixed[i] if i in fixed else next(it))
        out = fn(*args, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    if not diffable:
        pytest.skip("no differentiable inputs")

    check_grad_lowp(wrapped, [arrays[i] for i in diffable], dtype=dtype,
                    **tol)
