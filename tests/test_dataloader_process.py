"""Multiprocess DataLoader (io/worker.py): parity with in-process loading,
shared-memory transport, persistent workers, error/crash propagation.
Reference: ``fluid/dataloader/dataloader_iter.py:342``
(_DataLoaderIterMultiProcess) + ``memory/allocation/mmap_allocator.cc``."""
import os
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset, IterableDataset, get_worker_info
from paddle_tpu.io.worker import WorkerFailure


@pytest.fixture(autouse=True)
def _fast_fork(monkeypatch, request):
    """fork-start for speed (forkserver costs ~10s/pool on this box); the
    default forkserver path is exercised by test_forkserver_default_start."""
    if "forkserver" not in request.node.name:
        monkeypatch.setenv("PADDLE_TPU_WORKER_START", "fork")


def test_forkserver_default_start():
    ds = ArrayDataset()
    assert os.environ.get("PADDLE_TPU_WORKER_START") is None
    got = _collect(DataLoader(ds, batch_size=16, num_workers=2,
                              use_process=True))
    assert got == list(range(64))


class ArrayDataset(Dataset):
    def __init__(self, n=64, dim=8):
        self.x = np.arange(n * dim, dtype=np.float32).reshape(n, dim)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], np.int64(i)


class PyHeavyDataset(ArrayDataset):
    """Pure-Python per-sample transform: the GIL-bound case processes exist
    for."""

    def __getitem__(self, i):
        acc = 0.0
        for j in range(20000):
            acc += (i * j) % 7
        x, y = super().__getitem__(i)
        return x + (acc % 3), y


class BoomDataset(ArrayDataset):
    def __getitem__(self, i):
        if i == 13:
            raise ValueError("boom at 13")
        return super().__getitem__(i)


class KillSelfDataset(ArrayDataset):
    def __getitem__(self, i):
        if i == 7:
            os._exit(42)  # simulates a segfaulted/killed worker
        return super().__getitem__(i)


class ShardedIterable(IterableDataset):
    def __init__(self, n=64):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        wid = info.id if info else 0
        nw = info.num_workers if info else 1
        for i in range(wid, self.n, nw):
            yield np.float32(i)


def _collect(loader):
    out = []
    for xb, yb in loader:
        out.extend(np.asarray(yb).tolist())
        assert np.asarray(xb).dtype == np.float32
    return out


def test_process_mode_parity_with_single_thread():
    ds = ArrayDataset()
    base = _collect(DataLoader(ds, batch_size=8, num_workers=0))
    got = _collect(DataLoader(ds, batch_size=8, num_workers=4,
                              use_process=True))
    assert got == base
    # batches themselves identical
    b0 = next(iter(DataLoader(ds, batch_size=8, num_workers=0)))
    b1 = next(iter(DataLoader(ds, batch_size=8, num_workers=4,
                              use_process=True)))
    np.testing.assert_array_equal(np.asarray(b0[0]), np.asarray(b1[0]))


def test_process_mode_without_shared_memory():
    ds = ArrayDataset()
    base = _collect(DataLoader(ds, batch_size=8, num_workers=0))
    got = _collect(DataLoader(ds, batch_size=8, num_workers=2,
                              use_process=True, use_shared_memory=False))
    assert got == base


def test_worker_exception_propagates():
    loader = DataLoader(BoomDataset(), batch_size=4, num_workers=2,
                        use_process=True)
    with pytest.raises(WorkerFailure, match="boom at 13"):
        list(loader)


def test_killed_worker_detected():
    loader = DataLoader(KillSelfDataset(), batch_size=4, num_workers=2,
                        use_process=True)
    with pytest.raises(WorkerFailure, match="exited unexpectedly"):
        list(loader)


def test_persistent_workers_reuse_pool_across_epochs():
    ds = ArrayDataset()
    loader = DataLoader(ds, batch_size=8, num_workers=2, use_process=True,
                        persistent_workers=True)
    e1 = _collect(loader)
    pool = loader._pool
    assert pool is not None
    pids = [p.pid for p in pool._procs]
    e2 = _collect(loader)
    assert e1 == e2
    assert loader._pool is pool
    assert [p.pid for p in pool._procs] == pids
    assert all(p.is_alive() for p in pool._procs)
    loader.__del__()
    assert all(not p.is_alive() for p in pool._procs)


def test_early_break_then_reiterate():
    ds = ArrayDataset()
    loader = DataLoader(ds, batch_size=8, num_workers=2, use_process=True,
                        persistent_workers=True)
    it = iter(loader)
    next(it), next(it)  # abandon mid-epoch
    del it
    assert _collect(loader) == list(range(64))  # stale epoch fully discarded


def test_iterable_dataset_process_sharding():
    loader = DataLoader(ShardedIterable(48), batch_size=4, num_workers=3,
                        use_process=True)
    got = []
    for batch in loader:
        got.extend(np.asarray(batch).astype(int).tolist())
    assert sorted(got) == list(range(48))


def _ok_init(wid):
    pass  # runs in the child


def _bad_init(wid):
    raise RuntimeError("init failed")


def test_worker_init_fn_runs_and_failure_propagates():
    ds = ArrayDataset()
    assert _collect(DataLoader(ds, batch_size=8, num_workers=2,
                               use_process=True, worker_init_fn=_ok_init)) \
        == list(range(64))

    loader = DataLoader(ds, batch_size=8, num_workers=2, use_process=True,
                        worker_init_fn=_bad_init)
    with pytest.raises(WorkerFailure, match="worker_init_fn"):
        list(loader)


@pytest.mark.skipif((os.cpu_count() or 1) < 2,
                    reason="parallel speedup needs >1 core")
def test_python_heavy_transform_speedup():
    """The reason process workers exist: a pure-Python transform is GIL-bound
    under threads but parallel under processes."""
    ds = PyHeavyDataset(n=32)

    t0 = time.perf_counter()
    _collect(DataLoader(ds, batch_size=4, num_workers=4))
    threaded = time.perf_counter() - t0

    t0 = time.perf_counter()
    _collect(DataLoader(ds, batch_size=4, num_workers=4, use_process=True))
    proc_time = time.perf_counter() - t0

    assert proc_time < threaded, (proc_time, threaded)


def test_concurrent_iterators_on_persistent_loader():
    """Review regression: a second live iterator must not cross epoch tags
    with the persistent pool (it gets its own temporary pool)."""
    ds = ArrayDataset()
    loader = DataLoader(ds, batch_size=8, num_workers=2, use_process=True,
                        persistent_workers=True)
    it1, it2 = iter(loader), iter(loader)
    a1 = [np.asarray(next(it1)[1]).tolist() for _ in range(4)]
    a2 = [np.asarray(next(it2)[1]).tolist() for _ in range(4)]
    assert a1 == a2
    rest1 = [np.asarray(b[1]).tolist() for b in it1]
    rest2 = [np.asarray(b[1]).tolist() for b in it2]
    assert rest1 == rest2 and len(a1 + rest1) == 8


def test_timeout_raises_on_hung_worker():
    loader = DataLoader(HangDataset(), batch_size=4, num_workers=1,
                        use_process=True, timeout=3)
    with pytest.raises(WorkerFailure, match="timed out"):
        list(loader)


class HangDataset(ArrayDataset):
    def __getitem__(self, i):
        if i == 5:
            time.sleep(600)
        return super().__getitem__(i)
