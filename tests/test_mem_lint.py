"""Memory lint (ISSUE 12): per-eqn liveness over the step jaxpr, the
hbm-* registry rules, the predicted-vs-measured peak crosscheck on the
MULTICHIP zoo + serve decode, donation-aliasing / scan-residual liveness,
the bytes-based admission policy, the auto-parallel peak pruning, and the
CLI exports.

Acceptance (ISSUE 12):
  * on the dp×mp zoo config and the gpt2 serve decode the predicted peak
    agrees with ``compiled.memory_analysis()`` within the MEM_RTOL band
    (0.15 at ISSUE 12; 0.10 + 64 KiB atol since the fusion-aware
    timeline of ISSUE 18) on XLA:CPU and never UNDER-predicts beyond it;
  * ``tools/mem_lint.py --fixture undonated-longctx`` exits 1;
  * the bytes-based ``CostAwareAdmission`` sheds a request at submit that
    the token-count policy would have admitted straight into an
    injected-OOM degraded-decode tick.
"""
import importlib.util
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import mem_lint
from paddle_tpu.fault import inject
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler import devprof, telemetry
from paddle_tpu.serving import (
    CostAwareAdmission,
    GenerationEngine,
    Request,
    Scheduler,
)
from paddle_tpu.utils import unique_name

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _load_cli():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "mem_lint.py")
    spec = importlib.util.spec_from_file_location("mem_lint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cli():
    return _load_cli()


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()
    inject.disarm_all()
    yield
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()
    inject.disarm_all()


def _mlp(donate=True, batch=16, din=32, dh=64):
    """Tiny single-device MLP train step for the liveness unit tests."""
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(din, dh)
        l2 = paddle.nn.Linear(dh, din)
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(l1.parameters()) + list(l2.parameters()))

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        out = l2(h)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "mlp_train_step"
    step = CompiledStep(train_step, stateful=[l1, l2, opt],
                        donate_state=donate)
    rng = np.random.RandomState(0)
    x = Tensor(rng.randn(batch, din).astype(np.float32))
    y = Tensor(rng.randn(batch, din).astype(np.float32))
    return step, (x, y)


@pytest.fixture(scope="module")
def serve_eng():
    """One warmed 2-slot engine shared by the serving-side tests (same
    sharing rationale as test_serving_resilience: prefill fully resets a
    slot on admit, so state cannot leak between tests)."""
    with unique_name.guard():
        paddle.seed(3)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=64, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    e = GenerationEngine(model, max_batch=2, max_len=64,
                         prefill_buckets=(8, 16))
    e.prefill(0, [1] * 7)
    e.decode_once(np.zeros(2, np.int32))
    return e


def _sched(eng, **kw):
    kw.setdefault("retry_sleep", lambda s: None)
    return Scheduler(eng, **kw)


def _reqs(n, seed=5, max_new=6, vocab=97):
    rng = np.random.RandomState(seed)
    return [Request(prompt=rng.randint(0, vocab,
                                       int(rng.randint(3, 14))).tolist(),
                    max_new_tokens=max_new) for _ in range(n)]


# ---------------------------------------------------------------------------
# acceptance: predicted vs measured peak on the zoo configs
# ---------------------------------------------------------------------------

def _cli_measure(model):
    """Drive the measured crosscheck in a SUBPROCESS: the rtol gate needs
    a real alias term, and an executable deserialized from the persistent
    compile cache (tests/conftest.py enables it for this process) reports
    alias=0 — tripping satellite 1's alias_unavailable skip, which would
    pass the gate vacuously on every warm run. The CLI process never
    enables the persistent cache, so its compile is always fresh —
    without toggling global jax config inside this process."""
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "mem_lint.py")
    return subprocess.run(
        [sys.executable, path, "--models", model, "--measure"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


@needs_8_devices
def test_crosscheck_dp_mp_zoo(cli):
    """dp×mp Megatron-TP MLP with donated state: the timeline's peak
    (donation aliasing + per-shard local shapes) agrees with XLA's
    ``memory_analysis()`` within rtol and never under-predicts."""
    buf = io.StringIO()
    (name, report, tl, rows), = cli.lint_zoo(["dp-mp"], out=buf)
    assert tl is not None and tl.peak_bytes > 0
    assert tl.alias_bytes > 0, "donated state must alias into the outputs"
    out = _cli_measure("dp-mp")
    assert out.returncode == 0, out.stdout + out.stderr
    checks = [l for l in out.stdout.splitlines()
              if l.startswith("crosscheck:")]
    assert checks, out.stdout
    for line in checks:
        assert "agrees=True" in line and "under_predicted=False" in line, \
            line
    assert "0 crosscheck disagreement(s)" in out.stdout


@needs_8_devices
def test_zero_sharded_update_cuts_predicted_peak(cli):
    """dp-plain vs dp-zero zoo pair (ISSUE 14): the ZeRO sharded weight
    update must drop the PREDICTED per-device peak by at least the
    sharded optimizer-state bytes — 12 B/param (fp32 master + moment1 +
    moment2 under bf16 multi_precision AdamW) scaled by (dp-1)/dp — and
    the ``spmd-replicated-optimizer-state`` rule flips from firing on the
    plain step to quiet on the sharded one."""
    buf = io.StringIO()
    res = {name: (report, tl)
           for name, report, tl, _ in cli.lint_zoo(["dp-plain", "dp-zero"],
                                                   out=buf)}
    rep_plain, tl_plain = res["dp-plain"]
    rep_zero, tl_zero = res["dp-zero"]
    assert rep_plain.by_rule("spmd-replicated-optimizer-state")
    assert not rep_zero.by_rule("spmd-replicated-optimizer-state")
    assert not rep_zero.by_rule("hbm-const-folded")  # state stays threaded

    dp = 8
    n_params = 256 * 1024 + 1024 + 1024 * 256 + 256  # the zoo MLP
    acc_drop = 12 * n_params * (dp - 1) // dp
    drop = tl_plain.peak_bytes - tl_zero.peak_bytes
    # essentially the accumulator shards leave the peak. The floor admits
    # the fusion-aware timeline (ISSUE 18) eliding a few hundred KB of
    # update temps from the PLAIN peak that the fusion-blind model priced
    # on top of the accumulators (0.95x observed); the ceiling admits the
    # sharded gradients/update temps that ride along on the legacy path
    # (~1.43x observed with fusion off)
    assert drop >= 0.9 * acc_drop, (drop, acc_drop)
    assert drop <= 1.6 * acc_drop, (drop, acc_drop)


def test_crosscheck_serve_decode_zoo(cli):
    """gpt2-style serve decode: the static-shape KV-cache step's predicted
    peak agrees with the measured one, and the padded example lengths
    trip hbm-kv-bucket-waste."""
    buf = io.StringIO()
    (name, report, tl, rows), = cli.lint_zoo(["serve-decode"], out=buf)
    assert tl is not None and tl.peak_bytes > 0
    # lengths [3, 5] against the default bucket ladder waste >25%
    assert report.by_rule("hbm-kv-bucket-waste")
    out = _cli_measure("serve-decode")
    assert out.returncode == 0, out.stdout + out.stderr
    checks = [l for l in out.stdout.splitlines()
              if l.startswith("crosscheck:")]
    assert checks, out.stdout
    for line in checks:
        assert "agrees=True" in line and "under_predicted=False" in line, \
            line


# ---------------------------------------------------------------------------
# rules: positive + clean per rule
# ---------------------------------------------------------------------------

def test_rule_peak_over_capacity():
    step, (x, y) = _mlp()
    rep = analysis.lint_step(step, x, y,
                             config={"hbm_capacity_bytes": 256.0})
    hits = rep.by_rule("hbm-peak-over-capacity")
    assert hits and hits[0].severity == "error"
    assert "exceeds" in hits[0].message
    clean = analysis.lint_step(step, x, y,
                               config={"hbm_capacity_bytes": float(1 << 40)})
    assert not clean.by_rule("hbm-peak-over-capacity")


def test_rule_remat_candidate():
    step, (x, y) = _mlp()
    rep = analysis.lint_step(step, x, y,
                             config={"remat_min_bytes": 1.0,
                                     "remat_min_span": 0.0})
    hits = rep.by_rule("hbm-remat-candidate")
    assert hits and hits[0].severity == "warning"
    assert "jax.checkpoint" in hits[0].hint
    clean = analysis.lint_step(step, x, y)  # default 8 MiB floor
    assert not clean.by_rule("hbm-remat-candidate")


def test_rule_liveness_spike():
    step, (x, y) = _mlp()
    rep = analysis.lint_step(step, x, y,
                             config={"spike_min_bytes": 1.0,
                                     "spike_fraction": 0.01})
    hits = rep.by_rule("hbm-liveness-spike")
    assert hits and hits[0].severity == "warning"
    clean = analysis.lint_step(step, x, y,
                               config={"spike_min_bytes": float(1 << 40)})
    assert not clean.by_rule("hbm-liveness-spike")


def test_rule_kv_bucket_waste(serve_eng):
    args = serve_eng.example_decode_args([1])
    rep = analysis.lint_step(serve_eng.decode_step, *args)
    hits = rep.by_rule("hbm-kv-bucket-waste")
    assert hits and hits[0].severity == "warning"
    assert "wastes" in hits[0].message
    # near-full occupancy: 60/64 rounds to the top bucket with ~6% waste
    args = serve_eng.example_decode_args([60, 60])
    clean = analysis.lint_step(serve_eng.decode_step, *args)
    assert not clean.by_rule("hbm-kv-bucket-waste")


def test_undonated_input_reports_peak_delta():
    """Satellite: hbm-undonated-input now quotes the timeline's predicted
    peak reduction for donating the flagged inputs."""
    step, (x, y) = _mlp(donate=False)
    rep = analysis.lint_step(step, x, y,
                             config={"donate_min_bytes": 1.0})
    hits = rep.by_rule("hbm-undonated-input")
    assert hits
    assert any("peak" in f.message for f in hits)


# ---------------------------------------------------------------------------
# liveness mechanics: donation aliasing + scan residual attribution
# ---------------------------------------------------------------------------

def test_donation_aliasing_liveness():
    stepd, (xd, yd) = _mlp(donate=True)
    stepu, (xu, yu) = _mlp(donate=False)
    tld = analysis.analyze_memory(stepd, xd, yd)
    tlu = analysis.analyze_memory(stepu, xu, yu)
    # donated run: updated state aliases the donated buffers — the alias
    # term is positive and the aliased outputs stop double-counting
    assert tld.alias_bytes > 0
    assert any(b.is_output and b.aliases is not None and b.eff_bytes == 0
               for b in tld.buffers)
    assert any(b.donated for b in tld.buffers)
    # undonated run: no aliasing, and the peak can only be higher
    assert tlu.alias_bytes == 0
    assert tlu.peak_bytes >= tld.peak_bytes
    # what-if: donating the undonated state shrinks the predicted peak
    paths = [b.path for b in tlu.buffers
             if b.kind == "input" and not b.donated and b.path]
    assert tlu.delta_if_donated(paths) > 0


def test_scan_residual_attribution():
    """grad-of-scan: the forward scan's stacked ys consumed by the
    backward scan are tagged as residuals and qualify as remat
    candidates regardless of span."""
    W = jnp.eye(16, dtype=jnp.float32)
    xs = jnp.ones((8, 16), jnp.float32)

    def loss(W, xs):
        def body(c, x):
            c = jnp.tanh(c @ W) + x
            return c, c

        _, ys = jax.lax.scan(body, jnp.zeros(16, jnp.float32), xs)
        return ys.sum()

    closed = jax.make_jaxpr(jax.grad(loss))(W, xs)
    tl = mem_lint.timeline_from_jaxpr(closed, name="scan-grad")
    tags = {b.tag for b in tl.buffers if b.tag}
    assert tags & {"residual", "scan-ys"}, tags
    # residual tags qualify for remat independently of the span filter
    remat = tl.long_lived(1.0, 1.1)
    assert any(b.tag in ("residual", "scan-ys") for b in remat)


def test_timeline_table_and_dict():
    step, (x, y) = _mlp()
    tl = analysis.analyze_memory(step, x, y)
    d = tl.as_dict(top_k=3)
    assert d["peak_bytes"] == tl.peak_bytes
    assert len(d["contributors"]) <= 3
    assert "peak" in tl.table()


# ---------------------------------------------------------------------------
# crosscheck_mem unit semantics
# ---------------------------------------------------------------------------

def test_crosscheck_mem_verdicts():
    m = float(100 << 20)  # well above MEM_ATOL so rtol dominates
    ok = analysis.crosscheck_mem(m, {"peak_bytes": m})[0]
    assert ok["agrees"] is True and ok["under_predicted"] is False
    under = analysis.crosscheck_mem(0.5 * m, {"peak_bytes": m})[0]
    assert under["agrees"] is False and under["under_predicted"] is True
    over = analysis.crosscheck_mem(2.0 * m, {"peak_bytes": m})[0]
    assert over["agrees"] is False and over["under_predicted"] is False


def test_crosscheck_mem_atol_floor():
    """ISSUE 18: tiny programs carry a fixed runtime-scratch overhead no
    live-set model predicts — the MEM_ATOL absolute band absorbs it, so a
    KB-scale gap never flips the verdict, while MB-scale gaps still do."""
    assert analysis.MEM_ATOL == 64 << 10
    small = analysis.crosscheck_mem(
        100.0, {"peak_bytes": float(analysis.MEM_ATOL)})[0]
    assert small["agrees"] is True and small["under_predicted"] is False
    # zero atol restores the strict relative verdict
    strict = analysis.crosscheck_mem(
        100.0, {"peak_bytes": float(analysis.MEM_ATOL)}, atol=0.0)[0]
    assert strict["agrees"] is False and strict["under_predicted"] is True


def test_crosscheck_mem_skips_alias_unavailable():
    """Satellite: a persistent-cache executable's MemoryBreakdown
    (alias term unavailable) must be skipped, not mis-gated."""
    mb = devprof.MemoryBreakdown(argument_bytes=100, output_bytes=50,
                                 alias_bytes=0, alias_unavailable=True)
    assert mb.as_dict()["alias_unavailable"] is True
    row = analysis.crosscheck_mem(100.0, mb)[0]
    assert row["skipped"]
    assert row["agrees"] is None
    # the dict form (e.g. a registered report round-tripped via JSON)
    # skips identically
    row2 = analysis.crosscheck_mem(
        100.0, {"peak_bytes": 150.0, "alias_unavailable": True})[0]
    assert row2["skipped"] and row2["agrees"] is None


# ---------------------------------------------------------------------------
# serving: predicted footprints + bytes-based admission
# ---------------------------------------------------------------------------

def test_predicted_footprints(serve_eng):
    fp = serve_eng.predicted_footprints()
    for key in ("decode_peak_bytes", "cache_bytes", "base_bytes",
                "per_token_bytes", "prefill_bucket_bytes", "timeline"):
        assert key in fp, key
    assert fp["cache_bytes"] > 0
    assert fp["per_token_bytes"] >= 1
    assert fp["base_bytes"] >= 0
    assert fp["decode_peak_bytes"] > 0
    assert set(fp["prefill_bucket_bytes"]) == set(serve_eng.prefill_buckets)
    for b, nbytes in fp["prefill_bucket_bytes"].items():
        assert nbytes == fp["per_token_bytes"] * min(serve_eng.max_len, b)
    # cached until refresh=True
    assert serve_eng.predicted_footprints()["decode_peak_bytes"] == \
        fp["decode_peak_bytes"]
    fresh = serve_eng.predicted_footprints(refresh=True)
    assert fresh["cache_bytes"] == fp["cache_bytes"]


def test_admission_policy_validation():
    with pytest.raises(ValueError):
        CostAwareAdmission(policy="flops")


def test_bytes_admission_sheds_before_injected_oom(serve_eng):
    """Acceptance: capacity the token policy can't see. The token-count
    policy admits both requests and an injected OOM mid-decode forces a
    degraded-decode eviction; the bytes policy, fed the predicted
    per-bucket footprints against the same capacity, sheds the second
    request at submit — degraded decode becomes the last resort."""
    eng = serve_eng
    fp = eng.predicted_footprints()
    prompts = [r.prompt for r in _reqs(2, seed=11)]

    # token policy: backlog bound is generous, both admitted
    tok = _sched(eng, admission=CostAwareAdmission(
        max_backlog_tokens=10 ** 9))
    tok_reqs = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    for r in tok_reqs:
        tok.submit(r)
    assert all(r.finish_reason != "shed" for r in tok_reqs)
    inject.arm("oom", "serve.decode", at=2)
    tok.run()
    assert sum(r.finish_reason == "oom_evicted" for r in tok_reqs) == 1

    # bytes policy against a capacity that fits exactly one request:
    # the same second request is shed at submit instead of being
    # admitted into the OOM
    probe = CostAwareAdmission(policy="bytes")
    costs = [probe.estimate_bytes(
        Request(prompt=list(p), max_new_tokens=6), eng) for p in prompts]
    cap = fp["base_bytes"] + costs[0] + 0.5 * costs[1]
    by = _sched(eng, admission=CostAwareAdmission(
        policy="bytes", capacity_bytes=cap))
    by_reqs = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    by.submit(by_reqs[0])
    assert by_reqs[0].finish_reason is None, "first request must fit"
    by.submit(by_reqs[1])
    assert by_reqs[1].finish_reason == "shed"
    by.run()
    assert by_reqs[0].finish_reason in ("eos", "length")


# ---------------------------------------------------------------------------
# auto-parallel: peak-aware plan pruning
# ---------------------------------------------------------------------------

def _tie_setup():
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.planner import Plan, Planner

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(32, 32)
    eng = Engine.__new__(Engine)  # wiring-only: no mesh/fit needed
    eng.model = net

    def fwd_loss(xa, ya):
        out = net(Tensor(xa))
        return (((out - Tensor(ya)) ** 2).mean())._value

    x = Tensor(np.random.RandomState(0).randn(16, 32).astype(np.float32))
    y = Tensor(np.random.RandomState(1).randn(16, 32).astype(np.float32))
    stats = {"step_flops": 1e6, "param_bytes": 32 * 32 * 4,
             "act_bytes": 16 * 32 * 4, "layers": 1, "batch": 16,
             "param_shapes": [(32 * 32 * 4, (32, 32))]}

    def planner_for(tied):
        class _TiedPlanner(Planner):
            def enumerate_plans(self):
                return list(tied)

        return _TiedPlanner(8, stats)

    def plans():
        return [Plan(dp=8, mp=1, est_step_time=1.0, feasible=True),
                Plan(dp=4, mp=2, est_step_time=1.0, feasible=True)]

    return eng, fwd_loss, x, y, planner_for, plans


@needs_8_devices
def test_plan_tie_break_scores_predicted_peak():
    """Every tied candidate gets a mem-lint predicted peak; with the
    default 16 GB chip nothing is pruned and the comm winner stands."""
    eng, fwd_loss, x, y, planner_for, plans = _tie_setup()
    tied = plans()
    chosen = eng._break_plan_tie(planner_for(tied), tied[0], fwd_loss, x, y)
    assert all(p.predicted_peak_bytes > 0 for p in tied)
    assert chosen is min(tied, key=lambda p: p.predicted_comm_bytes)


@needs_8_devices
def test_plan_prune_over_capacity():
    """A tied candidate whose predicted peak exceeds the chip's HBM is
    pruned before the comm tie-break — and when EVERY candidate is over,
    pruning backs off instead of discarding them all."""
    eng, fwd_loss, x, y, planner_for, plans = _tie_setup()
    # pass 1: score both peaks under the default (huge) capacity
    scored = plans()
    eng._break_plan_tie(planner_for(scored), scored[0], fwd_loss, x, y)
    peaks = sorted(p.predicted_peak_bytes for p in scored)
    assert peaks[0] > 0 and peaks[0] < peaks[1], peaks

    # capacity between the two peaks: the bigger plan is pruned, the
    # smaller one wins even if it lost the comm tie-break
    tied = plans()
    planner = planner_for(tied)
    planner.chip.hbm_bytes = 0.5 * (peaks[0] + peaks[1])
    chosen = eng._break_plan_tie(planner, tied[0], fwd_loss, x, y)
    assert chosen.predicted_peak_bytes == pytest.approx(peaks[0])

    # capacity below both: all pruned -> keep all, comm winner stands
    tied2 = plans()
    planner2 = planner_for(tied2)
    planner2.chip.hbm_bytes = 1.0
    chosen2 = eng._break_plan_tie(planner2, tied2[0], fwd_loss, x, y)
    assert chosen2 is min(tied2, key=lambda p: p.predicted_comm_bytes)


# ---------------------------------------------------------------------------
# CLI: fixture gate, SARIF/JSONL exports, bench-sentinel satellite
# ---------------------------------------------------------------------------

def test_cli_fixture_exits_nonzero(cli, capsys, tmp_path):
    """Acceptance: the undonated long-context fixture must exit 1 —
    peak over the injected budget + the undonated-input delta."""
    out_jsonl = tmp_path / "findings.jsonl"
    rc = cli.run(["--fixture", "undonated-longctx",
                  "--jsonl", str(out_jsonl)])
    assert rc == 1
    text = capsys.readouterr().out
    assert "hbm-peak-over-capacity" in text
    assert "hbm-undonated-input" in text
    rules = {json.loads(line)["rule"]
             for line in out_jsonl.read_text().splitlines()}
    assert "hbm-peak-over-capacity" in rules


def test_cli_sarif(cli, capsys):
    rc = cli.run(["--fixture", "undonated-longctx", "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "paddle-tpu-mem-lint"
    assert doc["runs"][0]["results"]


def test_bench_sentinel_tracks_hbm_peak():
    """Satellite: BENCH/SERVE history rounds carrying hbm_peak_bytes are
    tracked as lower-better metrics."""
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "bench_sentinel.py")
    spec = importlib.util.spec_from_file_location("bench_sentinel_cli", path)
    sentinel = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sentinel)
    bench = sentinel.extract_bench(
        {"parsed": {"value": 10.0}, "telemetry": {"hbm_peak_bytes": 4096}})
    assert bench["hbm_peak_bytes"] == (4096.0, "lower")
    serve = sentinel.extract_serve(
        {"value": 5.0, "telemetry": {"hbm_peak_bytes": 2048}})
    assert serve["hbm_peak_bytes"] == (2048.0, "lower")
