"""Inference serving tier (ISSUE 6): static-shape KV-cache decode +
continuous batching.

Contracts under test:
  * cached-vs-uncached greedy parity — 32 tokens of greedy decode through
    the static KV cache produce the SAME token ids as the uncached full
    forward, for bucket-boundary and mid-bucket prompt lengths;
  * O(1) decode — telemetry compile counters over a 64+-token generation:
    decode compiles EXACTLY once, prefill once per length bucket;
  * static lint — the decode step at two consecutive positions carries
    zero shape-churn/kv-cache findings, while the legacy grow-by-concat
    gpt cache path is flagged by the `kv-cache-concat` rule;
  * continuous batching — admit/evict determinism under a seeded arrival
    stream, per-request output parity with single-request generate, and
    dense-batch occupancy accounting.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    GPTConfig,
    GPTDecoderLayer,
    GPTForCausalLM,
)
from paddle_tpu.profiler import telemetry
from paddle_tpu.serving import (
    GenerationEngine,
    KVCache,
    Request,
    Scheduler,
    default_buckets,
    pick_bucket,
)
from paddle_tpu.utils import unique_name


@pytest.fixture
def _no_persistent_compile_cache():
    """Parity tests compare a cached-decode executable against a fresh
    eager path: executables round-tripped through the persistent XLA:CPU
    compile cache are not bit-identical to in-process compiles on this
    stack (see tests/test_fault_tolerance.py and the conftest warm-cache
    hazard note — the eager BERT path comes back corrupted on a warm
    cache), so these tests compile everything in-process."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _gpt_cfg(max_pos=128):
    return GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                     num_heads=2, max_position_embeddings=max_pos,
                     hidden_dropout=0.0, attention_dropout=0.0)


def _gpt(seed=0, max_pos=128):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(_gpt_cfg(max_pos))
    model.eval()
    return model


def _greedy_eager(model, prompt, n):
    """Uncached reference: full forward over the growing sequence."""
    ids = list(prompt)
    out = []
    for _ in range(n):
        logits = model(Tensor(np.asarray(ids, np.int64)[None, :]))
        nxt = int(np.asarray(logits._value)[0, -1].argmax())
        out.append(nxt)
        ids.append(nxt)
    return out


# ---------------------------------------------------------------------------
# bucketing + cache plumbing
# ---------------------------------------------------------------------------
def test_bucket_helpers():
    assert default_buckets(64) == (16, 32, 64)
    assert default_buckets(100) == (16, 32, 64, 100)
    assert pick_bucket(1, (8, 16)) == 8
    assert pick_bucket(8, (8, 16)) == 8   # boundary stays in its bucket
    assert pick_bucket(9, (8, 16)) == 16
    with pytest.raises(ValueError, match="largest prefill bucket"):
        pick_bucket(17, (8, 16))


def test_kv_cache_alloc_layout():
    c = KVCache.alloc(num_layers=3, batch=2, max_len=16, num_heads=4,
                      head_dim=8)
    assert c.num_layers == 3 and c.batch == 2 and c.max_len == 16
    assert c.num_heads == 4 and c.head_dim == 8
    assert c.ks[0].shape == (2, 16, 4, 8)
    assert c.lengths.dtype.name == "int32"
    # 3 layers x (K+V) x 2*16*4*8 floats
    assert c.nbytes() == 3 * 2 * 2 * 16 * 4 * 8 * 4
    # a registered pytree: flattens/unflattens through jax
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(c)
    assert len(leaves) == 3 * 2 + 1
    assert isinstance(jax.tree_util.tree_unflatten(treedef, leaves), KVCache)


# ---------------------------------------------------------------------------
# cached-vs-uncached greedy parity (the correctness tentpole)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("prompt_len", [5, 8])  # mid-bucket / boundary
def test_cached_greedy_parity_32_tokens(prompt_len,
                                        _no_persistent_compile_cache):
    model = _gpt()
    prompt = np.random.RandomState(7).randint(0, 97, prompt_len).tolist()
    eng = GenerationEngine(model, max_batch=2, max_len=64,
                           prefill_buckets=(8, 16))
    got = eng.generate(prompt, max_new_tokens=32)
    want = _greedy_eager(model, prompt, 32)
    assert got == want


def test_generate_convenience_on_model_caches_engine(
        _no_persistent_compile_cache):
    model = _gpt()
    prompt = [3, 1, 4, 1, 5]
    got = model.generate(prompt, max_new_tokens=8, max_len=64,
                         prefill_buckets=(8,))
    assert got == _greedy_eager(model, prompt, 8)
    eng = model._serve_engine
    # second call reuses the cached engine (and its compiled executables)
    model.generate(prompt, max_new_tokens=4, max_len=64,
                   prefill_buckets=(8,))
    assert model._serve_engine is eng


def test_generate_stops_at_eos():
    model = _gpt()
    eng = GenerationEngine(model, max_batch=1, max_len=64,
                           prefill_buckets=(8,))
    free_run = eng.generate([1, 2, 3], max_new_tokens=8)
    eos = free_run[1]
    out = eng.generate([1, 2, 3], max_new_tokens=8, eos_id=eos)
    # greedy is deterministic: stops right after the FIRST eos emission
    assert out == free_run[:free_run.index(eos) + 1]
    assert out[-1] == eos and len(out) < 8


# ---------------------------------------------------------------------------
# O(1) decode: compile counters + static lint
# ---------------------------------------------------------------------------
def test_decode_compiles_once_over_64_tokens():
    model = _gpt()
    telemetry.reset()
    telemetry.enable()
    try:
        eng = GenerationEngine(model, max_batch=2, max_len=128,
                               prefill_buckets=(8, 16))
        out = eng.generate([5, 6, 7], max_new_tokens=65)
        counts = telemetry.get_telemetry().compile_counts()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert len(out) == 65
    assert counts.get("serve_decode") == 1, counts  # 64 steps, ONE compile
    assert counts.get("serve_prefill") == 1, counts  # one bucket touched


def test_prefill_compiles_once_per_bucket():
    model = _gpt()
    telemetry.reset()
    telemetry.enable()
    try:
        eng = GenerationEngine(model, max_batch=2, max_len=64,
                               prefill_buckets=(8, 16))
        eng.generate([1] * 5, max_new_tokens=3)    # bucket 8
        eng.generate([1] * 12, max_new_tokens=3)   # bucket 16
        eng.generate([1] * 7, max_new_tokens=3)    # bucket 8 again: cached
        counts = telemetry.get_telemetry().compile_counts()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert counts.get("serve_prefill") == 2, counts
    assert counts.get("serve_decode") == 1, counts


def test_decode_lint_clean_at_consecutive_positions():
    model = _gpt()
    eng = GenerationEngine(model, max_batch=2, max_len=32,
                           prefill_buckets=(8,))
    a1 = eng.example_decode_args([5, 3])
    a2 = eng.example_decode_args([6, 4])
    report = analysis.lint_step(eng.decode_step, *a1, extra_args=[a2])
    churn = [f for f in report
             if f.rule in ("retrace-shape-churn", "kv-cache-concat")]
    assert not churn, report.table()
    assert not report.errors, report.table()


def test_kv_cache_concat_rule_flags_legacy_gpt_cache():
    """Regression fixture: the pre-fix grow-by-concat tuple cache — the
    cache operands change shape between consecutive positions and come
    back one step larger, which is exactly the `kv-cache-concat`
    signature. The rule must name the cache paths and point at
    serving.KVCache."""
    cfg = _gpt_cfg(max_pos=32)
    with unique_name.guard():
        paddle.seed(0)
        layer = GPTDecoderLayer(cfg)
    layer.eval()

    def legacy_decode(x, k, v):
        out, cache = layer(x, cache=(k, v))
        return out, cache[0], cache[1]

    x = np.random.RandomState(0).randn(1, 1, cfg.hidden_size)
    x = x.astype(np.float32)

    def kv(t):
        shape = (1, t, cfg.num_heads, cfg.hidden_size // cfg.num_heads)
        return (np.zeros(shape, np.float32), np.zeros(shape, np.float32))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        report = analysis.lint_step(legacy_decode, x, *kv(5),
                                    extra_args=[(x,) + kv(6)])
    findings = [f for f in report if f.rule == "kv-cache-concat"]
    assert {f.path for f in findings} == {"args[1]", "args[2]"}
    assert all(f.severity == "error" for f in findings)
    assert "serving.KVCache" in findings[0].hint
    # a shape-stable signature stays silent (no variants disagree)
    clean = analysis.lint_step(legacy_decode, x, *kv(5),
                               extra_args=[(x,) + kv(5)])
    assert not [f for f in clean if f.rule == "kv-cache-concat"]


def test_tuple_cache_shim_still_works_and_warns_once():
    from paddle_tpu.utils import _WARNED_ONCE

    cfg = _gpt_cfg(max_pos=32)
    with unique_name.guard():
        paddle.seed(0)
        layer = GPTDecoderLayer(cfg)
    layer.eval()
    _WARNED_ONCE.discard("gpt-kv-cache-concat")
    hd = cfg.hidden_size // cfg.num_heads
    k0 = Tensor(np.zeros((1, 3, cfg.num_heads, hd), np.float32))
    v0 = Tensor(np.zeros((1, 3, cfg.num_heads, hd), np.float32))
    x = Tensor(np.random.RandomState(0).randn(1, 1, cfg.hidden_size)
               .astype(np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out, cache = layer(x, cache=(k0, v0))
        out2, cache2 = layer(x, cache=cache)
    msgs = [str(x.message) for x in w]
    assert sum("deprecated" in m for m in msgs) == 1  # warns ONCE
    assert tuple(cache[0].shape) == (1, 4, cfg.num_heads, hd)   # grew...
    assert tuple(cache2[0].shape) == (1, 5, cfg.num_heads, hd)  # ...again
    assert tuple(out2.shape) == (1, 1, cfg.hidden_size)


# ---------------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------------
def _request_stream(seed, n, vocab=97):
    rng = np.random.RandomState(seed)
    return [Request(prompt=rng.randint(0, vocab,
                                       int(rng.randint(3, 14))).tolist(),
                    max_new_tokens=int(rng.randint(4, 12)), rid=i)
            for i in range(n)]


def _run_stream(seed):
    model = _gpt(seed=3, max_pos=64)
    eng = GenerationEngine(model, max_batch=4, max_len=64,
                           prefill_buckets=(8, 16))
    sched = Scheduler(eng)
    for req in _request_stream(seed, 9):
        sched.submit(req)
    finished = sched.run()
    return sched, {r.rid: list(r.tokens) for r in finished}


def test_scheduler_admit_evict_deterministic():
    s1, out1 = _run_stream(11)
    s2, out2 = _run_stream(11)
    assert s1.events == s2.events  # identical admit/evict log
    assert out1 == out2
    assert len(out1) == 9
    # slots were actually recycled: more admits than batch slots
    admits = [e for e in s1.events if e[1] == "admit"]
    assert len(admits) == 9 > s1.engine.max_batch
    assert 0.0 < s1.occupancy() <= 1.0


def test_scheduler_matches_single_request_generate(
        _no_persistent_compile_cache):
    """Continuous batching with slot churn produces the SAME tokens per
    request as serving each request alone — cross-slot isolation."""
    model = _gpt(seed=3, max_pos=64)
    eng = GenerationEngine(model, max_batch=3, max_len=64,
                           prefill_buckets=(8, 16))
    sched = Scheduler(eng)
    reqs = _request_stream(5, 7)
    for r in reqs:
        sched.submit(r)
    sched.run()
    solo = GenerationEngine(model, max_batch=1, max_len=64,
                            prefill_buckets=(8, 16))
    for r in reqs:
        want = solo.generate(r.prompt, max_new_tokens=r.max_new_tokens)
        assert r.tokens == want, f"request {r.rid} diverged"
        assert r.finish_reason == "length"
        assert r.ttft_s is not None and r.latency_s is not None


def test_scheduler_rejects_oversized_requests():
    model = _gpt(max_pos=64)
    eng = GenerationEngine(model, max_batch=2, max_len=32,
                           prefill_buckets=(8, 16))
    sched = Scheduler(eng)
    with pytest.raises(ValueError, match="prefill bucket"):
        sched.submit(Request(prompt=[1] * 20, max_new_tokens=4))
    with pytest.raises(ValueError, match="cache capacity"):
        sched.submit(Request(prompt=[1] * 10, max_new_tokens=30))
    with pytest.raises(ValueError, match="empty prompt"):
        sched.submit(Request(prompt=[], max_new_tokens=4))


def test_scheduler_publishes_telemetry():
    model = _gpt(max_pos=64)
    telemetry.reset()
    telemetry.enable()
    try:
        eng = GenerationEngine(model, max_batch=2, max_len=64,
                               prefill_buckets=(8, 16))
        sched = Scheduler(eng)
        for r in _request_stream(2, 4):
            r.max_new_tokens = 4
            sched.submit(r)
        sched.run()
        tm = telemetry.get_telemetry()
        counters, gauges = tm.counters(), tm.gauges()
        ttft = tm.get("serve.ttft_s")
        latency = tm.get("serve.latency_s")
    finally:
        telemetry.disable()
        telemetry.reset()
    assert counters["serve.admitted"] == 4
    assert counters["serve.evicted"] == 4
    assert counters["serve.tokens_generated"] == 16
    assert counters["serve.decode_steps"] == sched.decode_steps
    # a fully-drained run() RETIRES the lifecycle gauges (stale-gauge
    # fix) — counters/histograms survive
    assert "serve.requests_in_flight" not in gauges
    assert "serve.queue_depth" not in gauges
    assert ttft.get("count") == 4
    assert latency.get("count") == 4


def test_scheduler_gauges_retired_on_drain_and_shutdown():
    """Regression (ISSUE 8 satellite, mirrors the PR 5 DeviceLoader fix):
    a drained or shut-down scheduler must not leave stale
    serve.requests_in_flight / serve.queue_depth gauges behind."""
    model = _gpt(max_pos=64)
    telemetry.reset()
    telemetry.enable()
    try:
        eng = GenerationEngine(model, max_batch=2, max_len=64,
                               prefill_buckets=(8, 16))
        sched = Scheduler(eng)
        for r in _request_stream(3, 3):
            r.max_new_tokens = 3
            sched.submit(r)
        tm = telemetry.get_telemetry()
        assert tm.gauges()["serve.queue_depth"] == 3.0
        # mid-serve (NOT drained): gauges live
        sched.step()
        g = tm.gauges()
        assert g["serve.requests_in_flight"] == 2.0
        assert g["serve.queue_depth"] == 1.0
        # partial run that stops before the drain keeps them live too
        sched.run(max_steps=1)
        assert "serve.requests_in_flight" in tm.gauges()
        # full drain retires them
        sched.run()
        g = tm.gauges()
        assert "serve.requests_in_flight" not in g
        assert "serve.queue_depth" not in g
        # and republishing works: new traffic brings them back...
        for r in _request_stream(5, 1):
            r.max_new_tokens = 2
            sched.submit(r)
        sched.step()
        assert "serve.requests_in_flight" in tm.gauges()
        # ...until an explicit shutdown retires them again, mid-flight
        sched.shutdown()
        g = tm.gauges()
        assert "serve.requests_in_flight" not in g
        assert "serve.queue_depth" not in g
        # shutdown is idempotent and only touches the lifecycle gauges
        tm.set_gauge("serve.tokens_per_s", 42.0)
        sched.shutdown()
        assert tm.gauges()["serve.tokens_per_s"] == 42.0
    finally:
        telemetry.disable()
        telemetry.reset()


# ---------------------------------------------------------------------------
# encoder scoring (BERT serving path)
# ---------------------------------------------------------------------------
def test_encoder_scorer_parity_and_bucket_compiles(
        _no_persistent_compile_cache):
    with unique_name.guard():
        paddle.seed(0)
        model = BertForSequenceClassification(
            BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=2, intermediate_size=64,
                       max_position_embeddings=64, hidden_dropout=0.0,
                       attention_dropout=0.0),
            num_classes=3)
    model.eval()
    telemetry.reset()
    telemetry.enable()
    try:
        scorer = model.scorer(max_batch=4, seq_buckets=(8, 16))
        rng = np.random.RandomState(0)
        seqs = [rng.randint(0, 128, n).tolist()
                for n in (5, 8, 11, 16, 3, 7)]
        got = scorer.score(seqs)
        counts = telemetry.get_telemetry().compile_counts()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert got.shape == (6, 3)
    assert counts.get("serve_score") == 2, counts  # one per bucket
    for s, row in zip(seqs, got):
        want = np.asarray(model(Tensor(np.asarray(s, np.int64)[None]))
                          ._value)[0]
        np.testing.assert_allclose(row, want, rtol=1e-4, atol=1e-5)
