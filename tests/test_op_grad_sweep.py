"""Table-driven gradient sweep over the differentiable op surface
(VERDICT item 9). Every entry in paddle_tpu/ops/op_table.py is checked:
analytic tape gradients vs central finite differences, the reference's
per-op OpTest.check_grad discipline (unittests/op_test.py:1851) at scale."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.op_table import OPS

from tests.op_test import check_grad


def _draw(shape, domain, rng):
    if domain in ("f", "f2", "f3"):
        return rng.uniform(-0.9, 0.9, shape).astype(np.float32)
    if domain == "fp":
        return rng.uniform(0.2, 1.2, shape).astype(np.float32)
    if domain == "fnz":  # away from 0 (kinks in relu-family)
        return (rng.uniform(0.15, 0.9, shape)
                * rng.choice([-1.0, 1.0], shape)).astype(np.float32)
    if domain == "funique":  # distinct values (max/median ties)
        base = rng.uniform(-1, 1, shape)
        ramp = np.arange(base.size).reshape(shape) * 1e-2
        return (base + ramp).astype(np.float32)
    if domain == "unit":
        return rng.uniform(0.1, 0.9, shape).astype(np.float32)
    if domain == "logunit":
        return np.log(rng.uniform(0.1, 0.9, shape)).astype(np.float32)
    if domain == "gt1":
        return rng.uniform(1.2, 2.0, shape).astype(np.float32)
    if domain == "sign":
        return rng.choice([-1.0, 1.0], shape).astype(np.float32)
    if domain == "spd":
        n = shape[-1]
        a = rng.uniform(-1, 1, shape)
        return (a @ a.T + n * np.eye(n)).astype(np.float32)
    if domain == "trilpd":
        n = shape[-1]
        a = np.tril(rng.uniform(0.2, 1.0, shape)) + n * np.eye(n)
        return a.astype(np.float32)
    if domain == "bool":
        return rng.uniform(0, 1, shape) > 0.5
    if domain.startswith("int:"):
        hi = int(domain.split(":")[1])
        return rng.randint(0, hi, shape).astype(np.int64)
    raise ValueError(domain)


# pseudo-API adapters: entries whose name does not directly resolve
_ADAPTERS = {
    "ops.concat2": lambda a, b, axis=0: paddle.concat([a, b], axis=axis),
    "ops.stack2": lambda a, b, axis=0: paddle.stack([a, b], axis=axis),
    "ops.split_first": lambda x, num_or_sections=2: paddle.split(x, num_or_sections)[0],
    "ops.where3": lambda c, a, b: paddle.where(c, a, b),
    "ops.einsum_ij_jk": lambda a, b: paddle.einsum("ij,jk->ik", a, b),
    "ops.multi_dot": lambda a, b: paddle.multi_dot([a, b]),
    "ops.pad2d": lambda x, pad=None: F.pad(x, pad),
    "ops.getitem_slice": lambda x: x[0:2, 1:3],
    "ops.multiplex2": lambda a, b: paddle.multiplex(
        [a, b], paddle.to_tensor(np.zeros((a.shape[0], 1), np.int32))),
    "F.cross_entropy_labels": lambda x, y: F.cross_entropy(x, y),
    "F.layer_norm_w": lambda x, w, b: F.layer_norm(x, [int(x.shape[-1])], w, b),
    "F.dropout_eval": lambda x: F.dropout(x, 0.5, training=False),
    "F.interpolate_nearest": lambda x: F.interpolate(
        x, scale_factor=2, mode="nearest"),
}


def _resolve(api):
    if api in _ADAPTERS:
        return _ADAPTERS[api]
    ns, name = api.split(".", 1)
    mod = paddle if ns == "ops" else F
    fn = getattr(mod, name, None)
    if fn is None and ns == "ops":
        import paddle_tpu.ops as _o

        fn = getattr(_o, name, None)
    return fn


def _ids():
    counts = {}
    out = []
    for e in OPS:
        n = e["api"]
        counts[n] = counts.get(n, 0) + 1
        out.append(n if counts[n] == 1 else f"{n}#{counts[n]}")
    return out


def test_table_is_large_enough():
    assert len(OPS) >= 150, len(OPS)


@pytest.mark.parametrize("entry", OPS, ids=_ids())
def test_op_gradient(entry):
    fn = _resolve(entry["api"])
    assert fn is not None, f"API {entry['api']} not found on the public surface"
    # stable per-op seed: python's str hash is randomized per process
    # (PYTHONHASHSEED), which made boundary-sensitive ops (grid_sample)
    # flake run-to-run — crc32 is deterministic
    import zlib

    rng = np.random.RandomState(zlib.crc32(entry["api"].encode()) % (2**31))

    arrays = [_draw(s, d, rng) for s, d in entry["inputs"]]
    diffable = [
        i for i, (s, d) in enumerate(entry["inputs"])
        if not (d == "bool" or d == "sign" or d.startswith("int:"))
    ]
    if entry["only"] is not None:
        diffable = [i for i in diffable if i in entry["only"]]

    kwargs = entry["kwargs"]
    fixed = {
        i: (Tensor(a) if a.dtype != np.bool_ else Tensor(a))
        for i, a in enumerate(arrays) if i not in diffable
    }

    def wrapped(*diff_tensors):
        args = []
        it = iter(diff_tensors)
        for i in range(len(arrays)):
            args.append(fixed[i] if i in fixed else next(it))
        out = fn(*args, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    if not diffable:
        # value-only check: runs and is finite
        out = wrapped()
        assert np.isfinite(np.asarray(out._value)).all()
        return

    check_grad(
        wrapped,
        [arrays[i] for i in diffable],
        rtol=entry["rtol"], atol=entry["atol"], delta=entry["delta"],
    )
