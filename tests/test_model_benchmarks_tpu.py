"""Hardware-gated throughput regression tests for the three benchmark
models (BASELINE.md configs; VERDICT round-5 item 1).

Run: PADDLE_TPU_HW_TESTS=1 PYTHONPATH=/root/.axon_site:/root/repo \
       python -m pytest tests/test_model_benchmarks_tpu.py -q

Thresholds sit ~12% under the committed round-5 artifacts (RESNET_r05.json,
BERT_r05.json, LONGCTX_r05.json) to absorb the tunnel's run-to-run noise
while still catching real regressions (the reference gates op perf the
same relative way — tools/ci_op_benchmark.sh)."""
import os
import sys

import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("PADDLE_TPU_HW_TESTS"),
    reason="hardware benchmark tests need PADDLE_TPU_HW_TESTS=1 + a TPU")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _require_tpu():
    import jax

    if jax.default_backend() == "cpu":
        pytest.skip("no TPU backend")
    # conftest pins matmul precision to HIGHEST for CPU finite-difference
    # parity; on TPU that forces multi-pass fp32-emulated matmuls (and
    # Mosaic rejects the pass-split dots inside the pallas kernels) —
    # throughput must be measured at the hardware's native bf16 precision,
    # exactly like the standalone bench tools
    prev = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "default")
    yield
    jax.config.update("jax_default_matmul_precision",
                      prev if prev is not None else "highest")
    jax.clear_caches()


def test_resnet50_throughput_floor():
    from bench_resnet import _run

    # ResNet steps are short (~53 ms): the relay's ~150 ms fence round-trip
    # needs >=12 steps to amortize below the floor's noise margin (4 iters
    # measured 20% low on a healthy chip)
    ips = _run(batch=128, iters=12, artifact=False)
    assert ips >= 1900, f"ResNet-50 {ips:.0f} img/s below floor (r05: 2166)"


def test_bert_large_seq128_throughput_floor():
    from bench_bert import _run_one

    res = _run_one(128, iters=4)
    tps = res["value"]
    assert tps >= 49000, f"BERT-large {tps:.0f} tok/s below floor (r05: 55993)"


def test_gpt_long_context_throughput_floor():
    """s=8192 flagship long-context: guards the flash-attention long-seq
    path (block routing + multi-tile online softmax)."""
    import time

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    batch, seq = 4, 8192
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position_embeddings=seq,
                    hidden_dropout=0.0, attention_dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    for _, sub in model.named_sublayers():
        if type(sub).__name__ == "LayerNorm":
            sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)

    def train_step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True)
    rng = np.random.RandomState(0)
    data = [Tensor(rng.randint(0, cfg.vocab_size, (batch, seq))
                   .astype(np.int64)) for _ in range(6)]
    for i in range(2):
        np.asarray(step(data[i], data[i])._value)
    t0 = time.perf_counter()
    outs = [step(b, b) for b in data[2:]]
    np.asarray(outs[-1]._value)
    toks = batch * seq * 4 / (time.perf_counter() - t0)
    assert toks >= 53000, f"GPT s=8192 {toks:.0f} tok/s below floor (r05: ~60k)"
