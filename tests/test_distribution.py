"""paddle.distribution: sampling statistics, log_prob parity vs scipy,
kl_divergence rules, transforms, reparameterized gradients.
Reference: python/paddle/distribution/ + its unittests/distribution suite."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (
    AffineTransform, Beta, Categorical, ChainTransform, Dirichlet, ExpTransform,
    Independent, Multinomial, Normal, SigmoidTransform, TanhTransform,
    TransformedDistribution, Uniform, kl_divergence, register_kl,
)
from paddle_tpu.framework.tensor import Tensor


def _np(t):
    return np.asarray(t._value)


def test_normal_logprob_entropy_vs_scipy():
    loc, scale = 0.7, 1.3
    d = Normal(loc, scale)
    v = np.linspace(-3, 3, 11).astype(np.float32)
    np.testing.assert_allclose(_np(d.log_prob(Tensor(v))),
                               st.norm.logpdf(v, loc, scale), atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.norm.entropy(loc, scale), atol=1e-5)
    assert float(_np(d.mean)) == pytest.approx(loc)
    assert float(_np(d.variance)) == pytest.approx(scale ** 2)


def test_normal_sampling_moments_and_rsample_grad():
    paddle.seed(0)
    d = Normal(Tensor(np.float32(2.0)), Tensor(np.float32(0.5)))
    s = d.sample([20000])
    assert abs(_np(s).mean() - 2.0) < 0.02
    assert abs(_np(s).std() - 0.5) < 0.02

    # reparameterized: gradient flows to loc/scale
    loc = Tensor(np.float32(0.0), stop_gradient=False)
    scale = Tensor(np.float32(1.0), stop_gradient=False)
    d2 = Normal(loc, scale)
    out = d2.rsample([1000])
    (out * out).mean().backward()
    assert loc.grad is not None and scale.grad is not None
    # d E[(loc + scale*eps)^2] / dscale = 2*scale ~ 2
    assert abs(float(_np(scale.grad)) - 2.0) < 0.2


def test_uniform_basic():
    d = Uniform(1.0, 3.0)
    v = np.array([0.5, 1.5, 2.9, 3.5], np.float32)
    lp = _np(d.log_prob(Tensor(v)))
    np.testing.assert_allclose(lp[1:3], np.log(0.5), atol=1e-6)
    assert np.isneginf(lp[0]) and np.isneginf(lp[3])
    assert float(_np(d.entropy())) == pytest.approx(np.log(2.0))
    paddle.seed(1)
    s = _np(d.sample([5000]))
    assert s.min() >= 1.0 and s.max() < 3.0
    assert abs(s.mean() - 2.0) < 0.05


def test_categorical_logprob_entropy_sampling():
    logits = np.log(np.array([[0.2, 0.3, 0.5]], np.float32))
    d = Categorical(Tensor(logits))
    lp = _np(d.log_prob(Tensor(np.array([2], np.int64))))
    np.testing.assert_allclose(lp, np.log(0.5), atol=1e-6)
    np.testing.assert_allclose(float(_np(d.entropy())[0]),
                               st.entropy([0.2, 0.3, 0.5]), atol=1e-5)
    paddle.seed(2)
    s = _np(d.sample([8000]))
    freq = np.bincount(s.ravel(), minlength=3) / s.size
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)


def test_beta_vs_scipy():
    a, b = 2.0, 5.0
    d = Beta(a, b)
    v = np.array([0.1, 0.4, 0.8], np.float32)
    np.testing.assert_allclose(_np(d.log_prob(Tensor(v))),
                               st.beta.logpdf(v, a, b), atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.beta.entropy(a, b), atol=1e-5)
    assert float(_np(d.mean)) == pytest.approx(a / (a + b))


def test_dirichlet_vs_scipy():
    conc = np.array([2.0, 3.0, 4.0], np.float32)
    d = Dirichlet(Tensor(conc))
    v = np.array([0.2, 0.3, 0.5], np.float32)
    np.testing.assert_allclose(float(_np(d.log_prob(Tensor(v)))),
                               st.dirichlet.logpdf(v, conc), atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.dirichlet.entropy(conc), atol=1e-5)
    paddle.seed(3)
    s = _np(d.sample([4000]))
    np.testing.assert_allclose(s.mean(0), conc / conc.sum(), atol=0.02)


def test_multinomial():
    probs = np.array([0.25, 0.25, 0.5], np.float32)
    d = Multinomial(10, Tensor(probs))
    v = np.array([2.0, 3.0, 5.0], np.float32)
    np.testing.assert_allclose(float(_np(d.log_prob(Tensor(v)))),
                               st.multinomial.logpmf(v, 10, probs), atol=1e-5)
    paddle.seed(4)
    s = _np(d.sample([2000]))
    assert s.shape == (2000, 3)
    np.testing.assert_allclose(s.sum(-1), 10.0)
    np.testing.assert_allclose(s.mean(0), 10 * probs, atol=0.2)
    with pytest.raises(ValueError):
        Multinomial(0, Tensor(probs))


def test_kl_rules():
    p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
    expect = np.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5
    np.testing.assert_allclose(float(_np(kl_divergence(p, q))), expect, atol=1e-6)
    np.testing.assert_allclose(float(_np(p.kl_divergence(q))), expect, atol=1e-6)

    c1 = Categorical(Tensor(np.log(np.array([0.3, 0.7], np.float32))))
    c2 = Categorical(Tensor(np.log(np.array([0.5, 0.5], np.float32))))
    expect = 0.3 * np.log(0.3 / 0.5) + 0.7 * np.log(0.7 / 0.5)
    np.testing.assert_allclose(float(_np(kl_divergence(c1, c2))), expect, atol=1e-6)

    b1, b2 = Beta(2.0, 3.0), Beta(4.0, 2.0)
    # numeric reference via scipy integration of p*log(p/q)
    from scipy.integrate import quad

    f = lambda x: st.beta.pdf(x, 2, 3) * (st.beta.logpdf(x, 2, 3) - st.beta.logpdf(x, 4, 2))
    expect, _ = quad(f, 1e-9, 1 - 1e-9)
    np.testing.assert_allclose(float(_np(kl_divergence(b1, b2))), expect, atol=1e-4)

    d1 = Dirichlet(Tensor(np.array([1.0, 2.0], np.float32)))
    d2 = Dirichlet(Tensor(np.array([2.0, 2.0], np.float32)))
    assert float(_np(kl_divergence(d1, d2))) > 0

    with pytest.raises(NotImplementedError):
        kl_divergence(p, c1)


def test_register_kl_custom():
    class MyN(Normal):
        pass

    @register_kl(MyN, Normal)
    def _rule(p, q):
        return Tensor(np.float32(42.0))

    assert float(_np(kl_divergence(MyN(0.0, 1.0), Normal(0.0, 1.0)))) == 42.0


def test_transforms_roundtrip_and_jacobian():
    x = Tensor(np.array([0.3, -0.7, 1.2], np.float32))
    for t in (ExpTransform(), AffineTransform(2.0, 3.0), SigmoidTransform(),
              TanhTransform()):
        y = t.forward(x)
        back = t.inverse(y)
        np.testing.assert_allclose(_np(back), _np(x), atol=1e-5)
    # chain: exp(2x+1)
    ch = ChainTransform([AffineTransform(1.0, 2.0), ExpTransform()])
    y = ch.forward(x)
    np.testing.assert_allclose(_np(y), np.exp(2 * _np(x) + 1), rtol=1e-5)
    # |dy/dx| = 2*exp(2x+1)
    np.testing.assert_allclose(_np(ch.forward_log_det_jacobian(x)),
                               np.log(2.0) + 2 * _np(x) + 1, atol=1e-5)


def test_transformed_distribution_lognormal():
    """exp(Normal) == LogNormal: log_prob parity with scipy."""
    d = TransformedDistribution(Normal(0.0, 1.0), [ExpTransform()])
    v = np.array([0.5, 1.0, 2.5], np.float32)
    np.testing.assert_allclose(_np(d.log_prob(Tensor(v))),
                               st.lognorm.logpdf(v, 1.0), atol=1e-5)
    paddle.seed(5)
    s = _np(d.sample([8000]))
    assert abs(np.log(s).mean()) < 0.05


def test_independent_sums_event_dims():
    base = Normal(Tensor(np.zeros((3, 4), np.float32)),
                  Tensor(np.ones((3, 4), np.float32)))
    ind = Independent(base, 1)
    assert ind.batch_shape == [3] and ind.event_shape == [4]
    v = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(_np(ind.log_prob(Tensor(v))),
                               _np(base.log_prob(Tensor(v))).sum(-1), atol=1e-6)
    with pytest.raises(ValueError):
        Independent(base, 3)
