"""Autograd engine tests (semantics mirror reference eager autograd tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_shared_input_fanout():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x + x * 3  # dy/dx = 2x + 3 = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    z = (d * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y._grad_node is None
    assert y.stop_gradient


def test_no_grad_decorator():
    @paddle.no_grad()
    def f(t):
        return t * 2

    x = paddle.to_tensor([1.0], stop_gradient=False)
    assert f(x).stop_gradient


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_double_backward_without_retain_raises():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_multi_output_op():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3), stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() + (2 * b).sum()).backward()  # c unused -> zero grad path
    np.testing.assert_allclose(x.grad.numpy(), [[1, 2, 0], [1, 2, 0]])


def test_register_hook_leaf():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    seen = []
    h = x.register_hook(lambda g: seen.append(g.numpy()))
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0, 3.0])
    h.remove()


def test_register_hook_modifies_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    x.register_hook(lambda g: g * 2)
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_hook_on_intermediate():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.register_hook(lambda g: g * 10)
    (y * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [60.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_paddle_grad_intermediate_input():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * 3
    z = y * y
    (gy,) = paddle.grad(z, y)
    np.testing.assert_allclose(gy.numpy(), [12.0])


def test_paddle_grad_unused_raises():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(ValueError):
        paddle.grad(y, z, retain_graph=True)
    (g,) = paddle.grad(y, [z], allow_unused=True)
    assert g is None


def test_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 5.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 10.0])


def test_setitem_autograd():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    v = paddle.to_tensor([10.0], stop_gradient=False)
    y = x * 1
    y[1] = v
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])
    np.testing.assert_allclose(v.grad.numpy(), [1.0])


def test_getitem_autograd():
    x = paddle.to_tensor(np.arange(9, dtype="float32").reshape(3, 3), stop_gradient=False)
    x[1:, paddle.to_tensor([0, 2])].sum().backward()
    expect = np.zeros((3, 3))
    expect[1:, [0, 2]] = 1
    np.testing.assert_allclose(x.grad.numpy(), expect)
