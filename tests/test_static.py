"""paddle.static tests: Program recording, Executor replay, append_backward,
optimizer minimize, cond/while_loop, save_inference_model.

Mirrors the reference static-mode tests (``unittests/test_layers.py`` static
branches, ``book/test_recognize_digits.py``) at smoke scale.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import static
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name


@pytest.fixture(autouse=True)
def _dynamic_after():
    yield
    paddle.disable_static()


def test_program_records_and_executor_runs():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 3)
        y = lin(x)
        z = F.relu(y) * 2.0
    assert len(main.ops) >= 2
    assert z.shape[-1] == 3

    exe = static.Executor()
    x_np = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    (out,) = exe.run(main, feed={"x": x_np}, fetch_list=[z])

    ref = np.maximum(
        x_np @ np.asarray(lin.weight._value) + np.asarray(lin.bias._value), 0
    ) * 2.0
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_append_backward_grads_match_dygraph():
    with unique_name.guard():
        paddle.seed(0)
        lin_s = nn.Linear(8, 4)
    with unique_name.guard():
        paddle.seed(0)
        lin_d = nn.Linear(8, 4)
    x_np = np.random.RandomState(1).randn(4, 8).astype(np.float32)

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        loss = lin_s(x).pow(2).mean()
        pairs = static.append_backward(loss)
    exe = static.Executor()
    fetches = [loss] + [g for _, g in pairs]
    outs = exe.run(main, feed={"x": x_np}, fetch_list=fetches)

    out_d = lin_d(Tensor(x_np)).pow(2).mean()
    out_d.backward()
    np.testing.assert_allclose(outs[0], np.asarray(out_d._value), rtol=1e-5)
    grads_d = {p.name.split("_")[-1]: np.asarray(p.grad) for p in lin_d.parameters()}
    for (p, _), g in zip(pairs, outs[1:]):
        ref = grads_d[p.name.split("_")[-1]]
        np.testing.assert_allclose(g, ref, rtol=1e-4, atol=1e-6)


def test_static_mnist_training_mirrors_dygraph():
    """config-1 style MNIST MLP trained via Executor.run — the static twin
    of the dygraph e2e test; loss must decrease and match the dygraph twin
    step-for-step."""
    rng = np.random.RandomState(0)
    x_np = rng.randn(32, 784).astype(np.float32)
    y_np = rng.randint(0, 10, (32, 1)).astype(np.int64)

    def make_net():
        return nn.Sequential(nn.Linear(784, 64), nn.ReLU(), nn.Linear(64, 10))

    # dygraph twin
    with unique_name.guard():
        paddle.seed(0)
        net_d = make_net()
    opt_d = paddle.optimizer.SGD(learning_rate=0.1, parameters=net_d.parameters())
    dyn_losses = []
    for _ in range(5):
        loss = F.cross_entropy(net_d(Tensor(x_np)), Tensor(y_np)).mean()
        loss.backward()
        opt_d.step()
        opt_d.clear_grad()
        dyn_losses.append(float(np.asarray(loss._value)))

    # static twin
    paddle.enable_static()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        with unique_name.guard():
            paddle.seed(0)
            net_s = make_net()
        x = static.data("x", [32, 784], "float32")
        y = static.data("y", [32, 1], "int64")
        loss = F.cross_entropy(net_s(x), y).mean()
        opt_s = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_s.parameters())
        opt_s.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    st_losses = []
    for _ in range(5):
        (lv,) = exe.run(main, feed={"x": x_np, "y": y_np}, fetch_list=[loss])
        st_losses.append(float(lv))
    paddle.disable_static()

    assert st_losses[-1] < st_losses[0]
    np.testing.assert_allclose(st_losses, dyn_losses, rtol=1e-4)


def test_cond_eager_and_grad():
    x = Tensor(np.asarray([3.0], np.float32))
    x.stop_gradient = False
    pred = Tensor(np.asarray(True))
    out = static.nn.cond(pred, lambda: x * 2.0, lambda: x * 10.0)
    assert float(np.asarray(out._value)[0]) == 6.0
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad), [2.0])

    pred_f = Tensor(np.asarray(False))
    out2 = static.nn.cond(pred_f, lambda: x * 2.0, lambda: x * 10.0)
    assert float(np.asarray(out2._value)[0]) == 30.0


def test_while_loop_eager():
    i = Tensor(np.asarray(0, np.int32))
    s = Tensor(np.asarray(0.0, np.float32))

    def cond_fn(i, s):
        return i < 5

    def body_fn(i, s):
        return i + 1, s + 2.0

    iv, sv = static.nn.while_loop(cond_fn, body_fn, [i, s])
    assert int(np.asarray(iv._value)) == 5
    assert float(np.asarray(sv._value)) == 10.0


def test_cond_recorded_in_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        flag = static.data("flag", [], "bool")
        out = static.nn.cond(flag, lambda: x + 1.0, lambda: x - 1.0)
    exe = static.Executor()
    x_np = np.asarray([1.0, 2.0, 3.0], np.float32)
    (o1,) = exe.run(main, feed={"x": x_np, "flag": np.asarray(True)},
                    fetch_list=[out])
    (o2,) = exe.run(main, feed={"x": x_np, "flag": np.asarray(False)},
                    fetch_list=[out])
    np.testing.assert_allclose(o1, x_np + 1)
    np.testing.assert_allclose(o2, x_np - 1)


def test_save_load_inference_model(tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 8], "float32")
        lin = nn.Linear(8, 4)
        out = F.relu(lin(x))
    path = str(tmp_path / "infer_model")
    static.save_inference_model(path, [x], [out], program=main)

    loaded, feeds, fetches = static.load_inference_model(path)
    x_np = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    got = loaded(Tensor(x_np))
    exe = static.Executor()
    (want,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got._value), want, rtol=1e-5)


def test_dynamic_batch_dim_retraces_correctly():
    """VERDICT weak #8: None/-1 dims are dynamic — different batch sizes
    run correctly (each size is its own compiled bucket), and mismatched
    STATIC dims raise instead of silently mis-shaping."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [-1, 8], "float32")
            lin = nn.Linear(8, 3)
            y = (lin(x) * 2.0).sum(axis=1)
        exe = paddle.static.Executor()
        exe.run(startup)
        for bs in (4, 7, 4):
            (out,) = exe.run(main, feed={"x": np.ones((bs, 8), np.float32)},
                             fetch_list=[y.name])
            assert out.shape == (bs,), out.shape
        # static dim mismatch raises
        import pytest

        with pytest.raises(ValueError, match="declared"):
            exe.run(main, feed={"x": np.ones((4, 9), np.float32)},
                    fetch_list=[y.name])
        with pytest.raises(ValueError, match="declared"):
            exe.run(main, feed={"x": np.ones((4,), np.float32)},
                    fetch_list=[y.name])
    finally:
        paddle.disable_static()


def test_inplace_op_in_static_program_and_feed_shape():
    """Inplace ops rebind the static handle without corrupting earlier
    reads or the placeholder's feed validation (record-time name snapshots
    + declaration-pinned feed shape)."""
    import numpy as np

    import paddle_tpu as paddle

    paddle.enable_static()
    try:
        main, startup = paddle.static.Program(), paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [3, 3], "float32")
            y = x * 2.0
            paddle.fill_diagonal_(y, 9.0)
            z = y + 1.0  # must read the POST-write binding
            exe = paddle.static.Executor()
            exe.run(startup)
            (zo,) = exe.run(main, feed={"x": np.ones((3, 3), np.float32)},
                            fetch_list=[z])
        expect = np.full((3, 3), 3.0)
        np.fill_diagonal(expect, 10.0)
        np.testing.assert_allclose(zo, expect)

        # inplace op applied directly to the PLACEHOLDER: the feed for its
        # name still validates against the data()-time declaration
        main2, startup2 = paddle.static.Program(), paddle.static.Program()
        with paddle.static.program_guard(main2, startup2):
            a = paddle.static.data("a", [2, 3], "float32")
            paddle.fill_diagonal_(a, 5.0)
            out = a + 0.0
            exe = paddle.static.Executor()
            exe.run(startup2)
            (ao,) = exe.run(main2, feed={"a": np.zeros((2, 3), np.float32)},
                            fetch_list=[out])
        expect2 = np.zeros((2, 3), np.float32)
        np.fill_diagonal(expect2, 5.0)
        np.testing.assert_allclose(ao, expect2)
    finally:
        paddle.disable_static()


def test_static_program_records_amp_autocast():
    """Recording under amp.auto_cast captures the O1 dtype policy in the
    program (reference static AMP: fluid/contrib/mixed_precision rewrites
    the program with casts; here the recorded fwd autocasts)."""
    import numpy as np

    import paddle_tpu as paddle

    paddle.enable_static()
    try:
        main, startup = paddle.static.Program(), paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [4, 8], "float32")
            w = paddle.static.data("w", [8, 8], "float32")
            with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
                y = paddle.matmul(x, w)  # white-list op: bf16 under O1
            exe = paddle.static.Executor()
            exe.run(startup)
            (out,) = exe.run(
                main,
                feed={"x": np.full((4, 8), 1.0 + 2**-10, np.float32),
                      "w": np.eye(8, dtype=np.float32)},
                fetch_list=[y])
        assert str(out.dtype) == "bfloat16", out.dtype
        # bf16 rounding proves the matmul really ran in low precision
        assert float(np.asarray(out, np.float32)[0, 0]) in (1.0, 1.0078125)
    finally:
        paddle.disable_static()
