"""Per-op finite-difference gradient checks (reference OpTest.check_grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_grad

rng = np.random.RandomState(42)


@pytest.mark.parametrize(
    "fn,shapes",
    [
        (lambda x, y: paddle.add(x, y), [(3, 4), (3, 4)]),
        (lambda x, y: paddle.subtract(x, y), [(3, 4), (4,)]),
        (lambda x, y: paddle.multiply(x, y), [(3, 4), (3, 4)]),
        (lambda x, y: paddle.divide(x, y + 2.0), [(3, 4), (3, 4)]),
        (lambda x, y: paddle.matmul(x, y), [(3, 4), (4, 5)]),
        (lambda x, y: paddle.matmul(x, y, transpose_y=True), [(3, 4), (5, 4)]),
        (lambda x: paddle.exp(x), [(3, 3)]),
        (lambda x: paddle.tanh(x), [(3, 3)]),
        (lambda x: paddle.sum(x, axis=1), [(3, 4)]),
        (lambda x: paddle.mean(x), [(3, 4)]),
        (lambda x: paddle.reshape(x, [2, 6]), [(3, 4)]),
        (lambda x: paddle.transpose(x, [1, 0]), [(3, 4)]),
        (lambda x: paddle.concat([x, x], axis=0), [(2, 3)]),
        (lambda x: F.relu(x), [(4, 4)]),
        (lambda x: F.sigmoid(x), [(3, 3)]),
        (lambda x: F.softmax(x, axis=-1), [(3, 5)]),
        (lambda x: F.gelu(x), [(3, 3)]),
        (lambda x: paddle.squeeze(paddle.unsqueeze(x, 1), 1), [(3, 4)]),
    ],
)
def test_grad_matches_numeric(fn, shapes):
    arrays = [rng.randn(*s).astype(np.float32) for s in shapes]
    check_grad(fn, arrays)


def test_log_softmax_grad():
    arrays = [rng.randn(3, 5).astype(np.float32)]
    check_grad(lambda x: F.log_softmax(x, axis=-1), arrays, rtol=6e-2, atol=3e-3)


def test_layer_norm_grad():
    arrays = [rng.randn(4, 8).astype(np.float32)]
    check_grad(lambda x: F.layer_norm(x, 8), arrays, rtol=2e-2, atol=2e-3)


def test_conv2d_grad():
    x = rng.randn(2, 3, 5, 5).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    check_grad(lambda x_, w_: F.conv2d(x_, w_, padding=1), [x, w], rtol=2e-2, atol=2e-3)


def test_softmax_ce_grad():
    logits = rng.randn(4, 7).astype(np.float32)
    labels = np.array([0, 3, 6, 2])

    def fn(lg):
        return F.cross_entropy(lg, paddle.to_tensor(labels), reduction="mean")

    check_grad(fn, [logits])


def test_embedding_grad():
    w = rng.randn(10, 4).astype(np.float32)
    ids = paddle.to_tensor(np.array([1, 3, 3, 7]))

    def fn(w_):
        return F.embedding(ids, w_)

    check_grad(fn, [w])


def test_pool_grads():
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    check_grad(lambda t: F.avg_pool2d(t, 2), [x])
    check_grad(lambda t: F.max_pool2d(t, 2), [x])


def test_bmm_and_einsum():
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 4, 5).astype(np.float32)
    check_grad(lambda x, y: paddle.bmm(x, y), [a, b])
    check_grad(lambda x, y: paddle.einsum("bij,bjk->bik", x, y), [a, b])


def test_forward_values_against_numpy():
    x = rng.randn(3, 4).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.exp(t).numpy(), np.exp(x), rtol=1e-5)
    np.testing.assert_allclose(paddle.sum(t, axis=0).numpy(), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.clip(t, -0.5, 0.5).numpy(), np.clip(x, -0.5, 0.5), rtol=1e-6
    )
    np.testing.assert_allclose(paddle.t(t).numpy(), x.T)
    v, i = paddle.topk(t, 2, axis=1)
    np.testing.assert_allclose(v.numpy(), np.sort(x, axis=1)[:, ::-1][:, :2], rtol=1e-5)


def test_as_complex_gradient_both_channels():
    """|as_complex(x)|^2 is real and depends on BOTH channels, so this
    checks the full complex vjp (the FD sweep's real-cast scalarization
    would silently ignore the imaginary part)."""
    import numpy as np

    import paddle_tpu as paddle

    xv = np.array([[1.0, 2.0], [3.0, -4.0]], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    z = paddle.as_complex(x)
    mag2 = (z.real() ** 2 + z.imag() ** 2).sum()
    mag2.backward()
    # d/dx sum(re^2 + im^2) = 2x for both channels
    np.testing.assert_allclose(x.grad.numpy(), 2 * xv, rtol=1e-5)
