"""Device-side observability (profiler.devprof): memory/cost harvest,
per-mesh-axis collective attribution on the dryrun-shaped configs,
pipeline-bubble metrics, straggler detection, and OOM forensics.

Reference contract (ISSUE 5): bench telemetry carries hbm_peak_bytes /
comm_fraction, the MULTICHIP dryrun configs log per-axis collective byte
counters, and an injected dispatch OOM produces a forensics dump instead
of a bare XLA error.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.profiler import devprof, telemetry
from paddle_tpu.utils import unique_name

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def _np(t):
    return np.asarray(t._value)


def _mlp_step(name="train_step", donate_inputs=False, seed=0):
    """The bench-shaped MLP train step (model + SGD, one fused program)."""
    with unique_name.guard():
        paddle.seed(seed)
        net = paddle.nn.Sequential(paddle.nn.Linear(16, 32),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())

    def train_step(x, y):
        loss = F.cross_entropy(net(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = name
    step = CompiledStep(train_step, stateful=[net, opt],
                        donate_inputs=donate_inputs)
    rng = np.random.RandomState(seed)
    x = Tensor(rng.rand(8, 16).astype(np.float32))
    y = Tensor(rng.randint(0, 4, (8, 1)).astype(np.int64))
    return step, x, y


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()
    yield
    telemetry.disable()
    telemetry.reset()
    devprof.clear_reports()


# ---------------------------------------------------------------------------
# normalize_cost_analysis (shared shim: cost_model / bench_common / devprof)
# ---------------------------------------------------------------------------

def test_normalize_cost_analysis_shapes():
    assert devprof.normalize_cost_analysis(None) == {}
    assert devprof.normalize_cost_analysis("garbage") == {}
    assert devprof.normalize_cost_analysis({"flops": 2}) == {"flops": 2.0}
    # newer jax: list of per-computation dicts -> numeric values summed
    out = devprof.normalize_cost_analysis(
        [{"flops": 2, "bytes accessed": 8.0, "label": "x"},
         {"flops": 3, "other": True}])
    assert out == {"flops": 5.0, "bytes accessed": 8.0}
    assert devprof.normalize_cost_analysis([]) == {}
    assert devprof.normalize_cost_analysis([None, {"a": 1}]) == {"a": 1.0}


def test_cost_model_uses_shared_normalizer():
    from paddle_tpu.cost_model import CostModel

    data = CostModel().static_cost_data(
        lambda a, b: jnp.matmul(a, b).sum(),
        (jnp.ones((16, 16)), jnp.ones((16, 16))))
    assert data["flops"] > 0
    assert isinstance(data["raw"], dict)


# ---------------------------------------------------------------------------
# memory/cost report on the bench MLP step
# ---------------------------------------------------------------------------

def test_device_report_memory_breakdown_sums_to_peak():
    step, x, y = _mlp_step()
    rep = step.device_report(x, y)
    assert rep is devprof.get_report("train_step")
    assert rep.flops > 0
    assert rep.bytes_accessed > 0
    md = rep.memory.as_dict()
    assert md["peak_bytes"] > 0
    assert (md["argument_bytes"] + md["output_bytes"] + md["temp_bytes"]
            + md["generated_code_bytes"] - md["alias_bytes"]
            == md["peak_bytes"])
    # single device: no interconnect traffic
    assert not rep.collectives
    assert rep.comm_bytes == 0
    assert rep.comm_fraction == 0.0
    assert "train_step" in rep.table()


@pytest.fixture
def _no_persistent_compile_cache():
    """Executables deserialized from the persistent XLA:CPU compile cache
    report ``alias_size_in_bytes=0`` in ``memory_analysis()`` (fresh
    in-process compiles report the real donated-alias size) — so the alias
    assertion below must compile fresh. The breakdown identity
    (arg+out+temp+code−alias == peak) holds either way."""
    import jax

    from jax._src import compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    # flipping the config alone is NOT enough: the cache object was
    # initialized at conftest import and keeps serving the old dir —
    # reset it, and drop in-process executables an earlier test may have
    # deserialized (alias-less) from disk
    compilation_cache.reset_cache()
    jax.clear_caches()
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    compilation_cache.reset_cache()  # re-attach the restored dir lazily


def test_device_report_safe_on_donated_inputs(_no_persistent_compile_cache):
    """Harvest lowers from shapes only — works after the real batch was
    donated/consumed by the step."""
    step, x, y = _mlp_step(donate_inputs=True)
    step(x, y)  # consumes x/y device buffers
    rep = step.device_report(x, y)
    assert rep.memory.peak_bytes > 0
    # state donation aliases params/accumulators into outputs -> nonzero
    # alias segment (x/y themselves can't alias: no same-shape output)
    assert rep.memory.alias_bytes > 0


def test_auto_harvest_on_first_compile_registers_telemetry():
    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)  # first call compiles -> auto-harvest
    rep = devprof.get_report("train_step")
    assert rep is not None and rep.flops > 0
    g = telemetry.get_telemetry().gauges()
    assert g["hbm.peak_bytes"] == rep.memory.peak_bytes
    assert g["cost.flops"] == rep.flops
    assert g["comm.fraction"] == 0.0
    # once per step object: a second call must not re-harvest
    devprof.clear_reports()
    step(x, y)
    assert devprof.get_report("train_step") is None


def test_auto_harvest_does_not_perturb_compile_counts():
    """The harvest lowers through its own jit identity: the step's
    trace cache must not gain entries, or recompile telemetry would
    under-count (the lazy-accumulator contract from PR 2/3)."""
    telemetry.enable()
    step, x, y = _mlp_step()
    for _ in range(3):
        step(x, y)
    assert telemetry.get_telemetry().compile_counts() == {"train_step": 1}
    assert telemetry.summary()["recompile_count"] == 0


def test_disabled_auto_harvest():
    telemetry.enable()
    devprof.enable_auto_harvest(False)
    try:
        step, x, y = _mlp_step()
        step(x, y)
        assert devprof.get_report("train_step") is None
    finally:
        devprof.enable_auto_harvest(True)


# ---------------------------------------------------------------------------
# collective attribution — dryrun-shaped configs
# ---------------------------------------------------------------------------

def test_collectives_gspmd_dp_mp():
    """dp×mp GSPMD program (sharded batch, TP-sharded weight): the
    compiled HLO carries the partitioner-inserted collectives, attributed
    to the dp / mp mesh axes."""
    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x, w):
        y = x._value @ w._value
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P("dp", None)))
        return (y * y).sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((8, 16)),
                              NamedSharding(mesh, P("dp", None))))
    w = Tensor(jax.device_put(jnp.ones((16, 32)),
                              NamedSharding(mesh, P(None, "mp"))))
    rep = step.device_report(x, w)
    assert rep.comm_source == "hlo"
    axes = rep.collectives.axes()
    assert any("dp" in a for a in axes), rep.collectives.as_dict()
    assert any("mp" in a for a in axes), rep.collectives.as_dict()
    assert rep.comm_bytes > 0
    assert 0.0 < rep.comm_fraction < 1.0


def test_collectives_jaxpr_explicit_shard_map():
    """Explicit shard_map collectives: exact per-axis counts and the ring
    bytes-moved model (psum = 2(S−1)/S × local bytes)."""
    from jax.experimental.shard_map import shard_map

    mesh = build_mesh({"dp": 2, "mp": 2})

    def fn(x):
        def inner(v):
            s = jax.lax.psum(v, "dp")
            w = jax.lax.ppermute(v, "mp", [(0, 1), (1, 0)])
            return s + w

        v = shard_map(inner, mesh=mesh, in_specs=P("dp", "mp"),
                      out_specs=P("dp", "mp"), check_rep=False)(x._value)
        return v.sum()

    step = CompiledStep(fn, stateful=(), donate_state=False)
    x = Tensor(jax.device_put(jnp.ones((8, 16), jnp.float32),
                              NamedSharding(mesh, P("dp", "mp"))))
    rep = step.device_report(x)
    tr = rep.collectives_traced.as_dict()
    # local shard (4, 8) f32 = 128 B; S=2 for both axes
    assert tr["dp"]["prims"] == {"psum": 1}
    assert tr["dp"]["bytes"] == 2 * (2 - 1) / 2 * 128
    assert tr["mp"]["prims"] == {"ppermute": 1}
    assert tr["mp"]["bytes"] == 1.0 * 128
    # the HLO (authoritative) view sees the same traffic classes
    assert rep.comm_bytes > 0


def test_collectives_moe_all_to_all_expert_parallel():
    """The MULTICHIP MoE dryrun config: stacked expert params sharded over
    the 8-way mesh, dispatch/combine lowering to expert all_to_all —
    nonzero collective bytes attributed to the expert-parallel axis."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.data_parallel import shard_batch
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    n = 8
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = n
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    d, n_exp, tokens = 8, n, 4 * n
    with unique_name.guard():
        paddle.seed(3)
        experts = [paddle.nn.Sequential(paddle.nn.Linear(d, d),
                                        paddle.nn.ReLU(),
                                        paddle.nn.Linear(d, d))
                   for _ in range(n_exp)]
        moe = MoELayer(d_model=d, experts=experts, gate={"type": "gshard"},
                       moe_group=hcg.get_data_parallel_group(),
                       capacity_factor=float(n_exp))
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=moe.parameters())

    def train_step(xb):
        out = moe(xb)
        loss = (out - 1.0).square().mean() + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[moe, opt], donate_state=True)
    xs = np.random.RandomState(5).randn(tokens, d).astype(np.float32)
    x = shard_batch(Tensor(xs), hcg.get_data_parallel_group())
    rep = step.device_report(x)
    assert rep.comm_source == "hlo"
    dp_axes = {a: st for a, st in rep.collectives.as_dict().items()
               if "dp" in a}
    assert dp_axes, rep.collectives.as_dict()
    assert sum(st["bytes"] for st in dp_axes.values()) > 0
    assert rep.comm_fraction > 0


def test_collectives_zero_on_single_device():
    step, x, y = _mlp_step()
    rep = step.device_report(x, y)
    assert rep.collectives.total_count == 0
    assert rep.collectives_traced.total_count == 0


def test_hlo_group_decoding():
    assert devprof._decode_groups("{{0,1},{2,3}}") == [[0, 1], [2, 3]]
    assert devprof._decode_groups("{}") is None
    # iota form: [groups, size]<=[dims]T(perm)
    assert devprof._decode_groups("[2,2]<=[4]") == [[0, 1], [2, 3]]
    assert devprof._decode_groups("[2,2]<=[2,2]T(1,0)") == [[0, 2], [1, 3]]


def test_hlo_explicit_brace_groups_attributed_per_axis():
    """Regression (found by the ISSUE 7 shard-lint crosscheck): the line
    regex used to truncate `{{0,1},{2,3}}` at the FIRST closing brace, so
    explicit-brace groups decoded to None = "all devices" — mislabeling a
    2-wide mp all-reduce as dp+mp and mispricing it with S=4."""
    mesh = build_mesh({"dp": 2, "mp": 2})
    line = ("%all-reduce = f32[8,32]{1,0} all-reduce(f32[8,32]{1,0} "
            "%dot.1), channel_id=1, replica_groups={{0,1},{2,3}}, "
            "use_global_device_ids=true, to_apply=%add.clone")
    st = devprof.collectives_from_hlo(line, mesh=mesh)
    # groups {0,1}/{2,3} vary the mp coordinate only; S=2 ⇒ factor 1
    assert st.as_dict() == {"mp": {"count": 1, "bytes": 8 * 32 * 4.0,
                                   "prims": {"all-reduce": 1}}}
    line_dp = line.replace("{{0,1},{2,3}}", "{{0,2},{1,3}}")
    st2 = devprof.collectives_from_hlo(line_dp, mesh=mesh)
    assert list(st2.as_dict()) == ["dp"]


def test_hlo_reduce_scatter_sync_prices_result_shard():
    """Ring model: each device ships (s-1) result-shard-sized chunks. The
    sync op's shape IS the local shard."""
    mesh = build_mesh({"dp": 4})
    line = ("%reduce-scatter = f32[4,32]{1,0} reduce-scatter(f32[16,32]{1,0} "
            "%param.1), channel_id=2, replica_groups={{0,1,2,3}}, "
            "use_global_device_ids=true, dimensions={0}, to_apply=%add")
    st = devprof.collectives_from_hlo(line, mesh=mesh).as_dict()
    assert st["dp"]["prims"] == {"reduce-scatter": 1}
    assert st["dp"]["bytes"] == 3 * (4 * 32 * 4)  # (s-1) x result shard


def test_hlo_reduce_scatter_start_rescaled_to_shard():
    """Regression: the async -start op's result tuple carries the INPUT
    buffer (s x the shard) as its largest element; pricing must rescale by
    the group size so sync and async forms agree."""
    mesh = build_mesh({"dp": 4})
    line = ("%reduce-scatter-start = ((f32[16,32]{1,0}), f32[4,32]{1,0}) "
            "reduce-scatter-start(f32[16,32]{1,0} %param.1), channel_id=2, "
            "replica_groups={{0,1,2,3}}, use_global_device_ids=true, "
            "dimensions={0}, to_apply=%add")
    st = devprof.collectives_from_hlo(line, mesh=mesh).as_dict()
    assert st["dp"]["bytes"] == 3 * (4 * 32 * 4)  # == the sync price


def test_hlo_all_gather_start_max_not_sum():
    """The -start tuple repeats input+output; summing would double-count.
    max picks the gathered result, priced (s-1)/s."""
    mesh = build_mesh({"dp": 4})
    sync = ("%all-gather = f32[16,32]{1,0} all-gather(f32[4,32]{1,0} "
            "%param.1), channel_id=3, replica_groups={{0,1,2,3}}, "
            "use_global_device_ids=true, dimensions={0}")
    start = ("%all-gather-start = (f32[4,32]{1,0}, f32[16,32]{1,0}) "
             "all-gather-start(f32[4,32]{1,0} %param.1), channel_id=3, "
             "replica_groups={{0,1,2,3}}, use_global_device_ids=true, "
             "dimensions={0}")
    want = (3 / 4) * (16 * 32 * 4)
    assert devprof.collectives_from_hlo(
        sync, mesh=mesh).as_dict()["dp"]["bytes"] == want
    assert devprof.collectives_from_hlo(
        start, mesh=mesh).as_dict()["dp"]["bytes"] == want


def test_hlo_all_reduce_start_matches_sync():
    mesh = build_mesh({"dp": 2})
    sync = ("%all-reduce = f32[8,32]{1,0} all-reduce(f32[8,32]{1,0} "
            "%dot.1), channel_id=1, replica_groups={{0,1}}, "
            "use_global_device_ids=true, to_apply=%add")
    start = ("%all-reduce-start = (f32[8,32]{1,0}, f32[8,32]{1,0}) "
             "all-reduce-start(f32[8,32]{1,0} %dot.1), channel_id=1, "
             "replica_groups={{0,1}}, use_global_device_ids=true, "
             "to_apply=%add")
    want = (2 * 1 / 2) * (8 * 32 * 4)  # 2(s-1)/s, s=2
    assert devprof.collectives_from_hlo(
        sync, mesh=mesh).as_dict()["dp"]["bytes"] == want
    assert devprof.collectives_from_hlo(
        start, mesh=mesh).as_dict()["dp"]["bytes"] == want


def test_hlo_collective_broadcast_decoded():
    """collective-broadcast (GSPMD emits it for replicating a sharded
    buffer) must be decoded, not silently dropped from the comm price."""
    mesh = build_mesh({"dp": 4})
    line = ("%collective-broadcast = f32[8,32]{1,0} collective-broadcast("
            "f32[8,32]{1,0} %param.1), channel_id=5, "
            "replica_groups={{0,1,2,3}}")
    st = devprof.collectives_from_hlo(line, mesh=mesh).as_dict()
    assert st["dp"]["prims"] == {"collective-broadcast": 1}
    assert st["dp"]["bytes"] == (3 / 4) * (8 * 32 * 4)


def test_hlo_int8_wire_priced_at_one_byte():
    """The int8 EF all-gather ships s8 on the wire — the pricer must use
    the element size from the HLO dtype, not assume fp32."""
    mesh = build_mesh({"dp": 4})
    line = ("%all-gather.9 = s8[16,256]{1,0} all-gather(s8[4,256]{1,0} "
            "%bitcast.3), channel_id=7, replica_groups=[1,4]<=[4], "
            "use_global_device_ids=true, dimensions={0}")
    st = devprof.collectives_from_hlo(line, mesh=mesh).as_dict()
    assert st["dp"]["bytes"] == (3 / 4) * (16 * 256 * 1)


# ---------------------------------------------------------------------------
# pipeline bubble + straggler metrics
# ---------------------------------------------------------------------------

def test_pipeline_bubble_fraction_analytic():
    assert devprof.pipeline_bubble_fraction(2, 2) == pytest.approx(1 / 3)
    assert devprof.pipeline_bubble_fraction(8, 4) == pytest.approx(3 / 11)
    assert devprof.pipeline_bubble_fraction(4, 1) == 0.0  # no pipeline
    assert devprof.pipeline_bubble_fraction(0, 4) == 0.0


def test_bubble_from_synthetic_microbatch_spans():
    # 2 ranks, perfect 1F1B staircase: each busy 2 of the 3-tick window
    spans = {0: [(0.0, 1.0), (1.0, 2.0)], 1: [(1.0, 2.0), (2.0, 3.0)]}
    out = devprof.bubble_from_spans(spans)
    assert out["window_s"] == pytest.approx(3.0)
    assert out["per_rank"][0] == pytest.approx(1 / 3)
    assert out["per_rank"][1] == pytest.approx(1 / 3)
    assert out["bubble_fraction"] == pytest.approx(1 / 3)
    # matches the analytic schedule bubble for M=2, pp=2
    assert out["bubble_fraction"] == pytest.approx(
        devprof.pipeline_bubble_fraction(2, 2))
    # tuple-list input form
    out2 = devprof.bubble_from_spans(
        [(0, 0.0, 1.0), (0, 1.0, 2.0), (1, 1.0, 2.0), (1, 2.0, 3.0)])
    assert out2["bubble_fraction"] == pytest.approx(1 / 3)
    assert devprof.bubble_from_spans({})["bubble_fraction"] == 0.0


def test_elastic_heartbeat_carries_step_time_and_finds_stragglers(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager

    managers = [ElasticManager(elastic_dir=str(tmp_path), rank=r,
                               world_size=3, timeout=30.0)
                for r in range(3)]
    managers[0].heartbeat(step_time_s=0.10)
    managers[1].heartbeat(step_time_s=0.11)
    managers[2].heartbeat(step_time_s=0.35)  # sick host: 3x the median
    times = managers[0].step_times()
    assert times == {0: 0.10, 1: 0.11, 2: 0.35}
    assert managers[0].stragglers(ratio=1.5) == [2]
    assert managers[0].stragglers(ratio=4.0) == []
    # healthy poll still reports nothing to restart
    assert managers[0].watch() is None


def test_elastic_heartbeat_pulls_step_gauge(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager

    telemetry.enable()
    tm = telemetry.get_telemetry()
    tm.step_begin()
    with telemetry.phase_span("dispatch"):
        pass
    tm.step_end()
    assert "step.time_s" in tm.gauges()
    m = ElasticManager(elastic_dir=str(tmp_path), rank=0, world_size=1)
    m.heartbeat()
    assert 0 in m.step_times()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_injected_dispatch_oom_dumps_forensics(capfd):
    from paddle_tpu.fault import inject

    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)  # compile + auto-harvest: forensics can cite the breakdown
    inject.disarm_all()
    inject.arm("oom", "dispatch", at=1)  # next dispatch (hits count
    # from arming, not from process start)
    try:
        with pytest.raises(Exception) as ei:
            step(x, y)
    finally:
        inject.disarm_all()
    # the original error is re-raised, not swallowed
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    fo = devprof.last_oom_report()
    assert fo is not None and fo.step_name == "train_step"
    # ranked report went to stderr instead of a bare XLA error
    err = capfd.readouterr().err
    assert "OOM forensics" in err
    assert "memory breakdown" in err
    assert "donation" in err
    d = fo.as_dict()
    assert d["memory"]["peak_bytes"] > 0
    assert d["donation"] == {"donate_state": True, "donate_inputs": False,
                             "donate_paths": []}
    assert d["batch"] and d["batch"][0]["nbytes"] > 0
    assert d["state"] and d["state"][0]["nbytes"] >= d["state"][-1]["nbytes"]
    assert telemetry.get_telemetry().counters().get("oom.count") == 1


def test_oom_forensics_json_round_trip(tmp_path, monkeypatch, capfd):
    from paddle_tpu.fault import inject

    monkeypatch.setenv(devprof.OOM_DUMP_ENV, str(tmp_path))
    step, x, y = _mlp_step()
    inject.disarm_all()
    inject.arm("oom", "dispatch", at=1)  # before any compile: no breakdown
    try:
        with pytest.raises(inject.InjectedResourceExhausted):
            step(x, y)
    finally:
        inject.disarm_all()
    capfd.readouterr()
    path = tmp_path / "oom_train_step.json"
    assert path.exists()
    loaded = devprof.OOMForensics.from_dict(json.loads(path.read_text()))
    assert loaded.step_name == "train_step"
    assert loaded.memory is None  # step never compiled -> unavailable
    assert loaded.batch[0]["shape"] == [8, 16] or \
        tuple(loaded.batch[0]["shape"]) == (8, 16)
    assert "unavailable" in loaded.report()


def test_non_oom_dispatch_errors_pass_through():
    from paddle_tpu.fault import inject

    step, x, y = _mlp_step()
    inject.disarm_all()
    inject.arm("error", "dispatch", at=1)
    try:
        with pytest.raises(inject.TransientError):
            step(x, y)
    finally:
        inject.disarm_all()
    assert devprof.last_oom_report() is None or \
        "transient" not in devprof.last_oom_report().error


# ---------------------------------------------------------------------------
# telemetry surface: percentiles, device section, loader gauges
# ---------------------------------------------------------------------------

def test_phase_stats_percentiles_and_report_columns():
    telemetry.enable()
    tm = telemetry.get_telemetry()
    for i in range(20):
        tm.add_phase("dispatch", 0, (i + 1) * 1_000_000)  # 1..20 ms
    st = telemetry.summary()["phases"]["dispatch"]
    assert st["p50"] == pytest.approx(0.010, abs=2e-3)
    assert st["p95"] == pytest.approx(0.019, abs=2e-3)
    table = tm.report(file=open(os.devnull, "w"))
    assert "P50(ms)" in table and "P95(ms)" in table


def test_report_renders_device_stats_section():
    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)
    table = telemetry.get_telemetry().report(file=open(os.devnull, "w"))
    assert "device stats:" in table
    assert "hbm.peak_bytes" in table


def test_device_loader_clears_gauges_on_shutdown():
    from paddle_tpu.io import DeviceLoader

    telemetry.enable()
    loader = DeviceLoader([(np.zeros((2, 2), np.float32),)
                           for _ in range(3)])
    for _ in loader:
        pass
    assert "device_loader.queue_depth" not in \
        telemetry.get_telemetry().gauges()
    # explicit shutdown path too
    it = iter(loader)
    next(it)
    loader.shutdown()
    assert "device_loader.queue_depth" not in \
        telemetry.get_telemetry().gauges()


def test_export_scalars_includes_percentiles_and_device_gauges(tmp_path):
    from paddle_tpu.utils.log_writer import LogWriter

    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)
    with LogWriter(str(tmp_path), file_name="t.jsonl") as w:
        telemetry.get_telemetry().export_scalars(w, step=1)
    tags = {json.loads(l)["tag"]
            for l in (tmp_path / "t.jsonl").read_text().splitlines()}
    assert "telemetry/phase/compile/p50_s" in tags
    assert "telemetry/phase/compile/p95_s" in tags
    assert "telemetry/gauge/hbm.peak_bytes" in tags
    assert "telemetry/gauge/comm.fraction" in tags


# ---------------------------------------------------------------------------
# bench + tools integration
# ---------------------------------------------------------------------------

def test_telemetry_block_reports_device_keys():
    from bench_common import measure_steps, telemetry_block

    step, _, _ = _mlp_step()
    rng = np.random.RandomState(0)
    batches = [(rng.rand(8, 16).astype(np.float32),
                rng.randint(0, 4, (8, 1)).astype(np.int64))
               for _ in range(7)]
    total, _ = measure_steps(step, batches, iters=4, warmup=2)
    blk = telemetry_block(total, 4)
    assert blk["hbm_peak_bytes"] > 0
    assert blk["comm_fraction"] == 0.0  # single device
    assert blk["comm_bytes_by_axis"] == {}
    assert blk["compile_count"] >= 1


def test_compiled_flops_prefers_harvested_report():
    from bench_common import compiled_flops

    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)
    rep = devprof.get_report("train_step")
    assert compiled_flops(step, [(x, y)]) == rep.flops


def test_mem_report_tool_renders_harvest(tmp_path, capsys):
    import mem_report
    from paddle_tpu.utils.log_writer import LogWriter

    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)
    tm = telemetry.get_telemetry()
    tm.inc("comm.bytes.dp", 4096)
    tm.inc("comm.count.dp", 2)
    with LogWriter(str(tmp_path), file_name="m.jsonl") as w:
        tm.export_scalars(w, step=1)
    assert mem_report.main([str(tmp_path / "m.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "HBM peak" in out
    assert "argument_bytes" in out
    assert "dp" in out
    # no device stats -> exit 1
    (tmp_path / "empty.jsonl").write_text(
        json.dumps({"tag": "train/loss", "value": 1.0}) + "\n")
    assert mem_report.main([str(tmp_path / "empty.jsonl")]) == 1


def test_telemetry_report_tool_device_section(tmp_path, capsys):
    import telemetry_report
    from paddle_tpu.utils.log_writer import LogWriter

    telemetry.enable()
    step, x, y = _mlp_step()
    step(x, y)
    with LogWriter(str(tmp_path), file_name="t.jsonl") as w:
        telemetry.get_telemetry().export_scalars(w, step=1)
    assert telemetry_report.main([str(tmp_path / "t.jsonl")]) == 0
    out = capsys.readouterr().out
    assert "device stats:" in out
    assert "P50(ms)" in out


# ---------------------------------------------------------------------------
# hapi / Engine surfaces
# ---------------------------------------------------------------------------

def test_hapi_device_stats_logger_callback(capsys):
    from paddle_tpu.hapi.callbacks import DeviceStatsLogger

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    data = [(rng.rand(4, 8).astype(np.float32),
             rng.randint(0, 4, (4, 1)).astype(np.int64))
            for _ in range(4)]
    cb = DeviceStatsLogger()
    model.fit(data, epochs=1, verbose=0, callbacks=[cb])
    assert cb.report is not None
    assert cb.report.memory.peak_bytes > 0
    assert model.device_report() is cb.report
    assert "device cost report" in capsys.readouterr().out
    assert not telemetry.enabled()  # callback restored the flag


def test_engine_device_report_accessor():
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.process_mesh import ProcessMesh
    from paddle_tpu.io import Dataset

    class _DS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.rand(16, 8).astype(np.float32)
            self.y = rng.randint(0, 4, (16, 1)).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
    telemetry.enable()
    engine = Engine(model=net, loss=paddle.nn.CrossEntropyLoss(),
                    optimizer=paddle.optimizer.SGD(
                        learning_rate=0.1, parameters=net.parameters()),
                    process_mesh=ProcessMesh(np.arange(8), dim_names=["dp"]))
    engine.fit(_DS(), batch_size=8, epochs=1)
    rep = engine.device_report()
    assert rep is not None
    assert rep.memory.peak_bytes > 0
    # dp=8 data-parallel training: the gradient all-reduce shows up as
    # dp-axis collective traffic in the compiled HLO
    assert any("dp" in a for a in rep.collectives.axes()), \
        rep.collectives.as_dict()
    assert rep.comm_fraction > 0
