"""hapi Model: prepare/fit/evaluate/predict/save/load + callbacks + summary.
Reference: python/paddle/hapi/model.py:915,1574, hapi/callbacks.py,
python/paddle/tests/test_model.py."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi import EarlyStopping, ModelCheckpoint
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.nn import CrossEntropyLoss


class _ToyClassify(Dataset):
    """Linearly separable 2-class problem."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp():
    return paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 2)
    )


def _prepared_model(lr=0.1):
    paddle.seed(0)
    net = _mlp()
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=lr, parameters=net.parameters())
    model.prepare(opt, CrossEntropyLoss(), Accuracy())
    return model


def test_fit_decreases_loss_and_tracks_acc():
    model = _prepared_model()
    ds = _ToyClassify(64)
    first, last = [], []

    class Track(Callback):
        def on_train_batch_end(self, step, logs=None):
            (first if not first else last).clear() if False else None
            last.append(logs["loss"])
            if len(last) == 1:
                first.append(logs["loss"])

    logs = model.fit(ds, batch_size=16, epochs=8, verbose=0, callbacks=[Track()])
    assert last[-1] < first[0], f"loss did not decrease: {first[0]} -> {last[-1]}"
    assert logs["acc"] > 0.8
    assert "loss" in logs


def test_evaluate_and_predict():
    model = _prepared_model()
    ds = _ToyClassify(64)
    model.fit(ds, batch_size=16, epochs=6, verbose=0)
    ev = model.evaluate(_ToyClassify(32, seed=1), batch_size=16, verbose=0)
    assert "loss" in ev and "acc" in ev
    assert ev["eval_samples"] == 32

    preds = model.predict(_ToyClassify(32, seed=1), batch_size=16,
                          stack_outputs=True, verbose=0)
    assert len(preds) == 1 and preds[0].shape == (32, 2)


def test_train_eval_batch_api():
    model = _prepared_model()
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.zeros((16,), np.int64)
    (l0,) = model.train_batch([x], [y])
    (l1,) = model.train_batch([x], [y])
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
    ev = model.eval_batch([x], [y])
    assert np.isfinite(ev[0])


def test_save_load_roundtrip(tmp_path):
    model = _prepared_model()
    ds = _ToyClassify(32)
    model.fit(ds, batch_size=16, epochs=2, verbose=0)
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")

    model2 = _prepared_model(lr=0.0)
    model2.load(path)
    x = np.ones((4, 8), np.float32)
    p1 = model.predict_batch([x])[0]
    p2 = model2.predict_batch([x])[0]
    np.testing.assert_allclose(p1, p2, atol=1e-6)


def test_model_checkpoint_callback(tmp_path):
    model = _prepared_model()
    save_dir = str(tmp_path / "auto")
    model.fit(_ToyClassify(32), batch_size=16, epochs=2, verbose=0,
              save_dir=save_dir, save_freq=1)
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))


def test_early_stopping_stops():
    model = _prepared_model(lr=0.0)  # frozen -> metric never improves
    es = EarlyStopping(monitor="loss", patience=1, verbose=0,
                       save_best_model=False)
    stopped = []

    class CountEpochs(Callback):
        def on_epoch_end(self, epoch, logs=None):
            stopped.append(epoch)

    model.fit(_ToyClassify(32), eval_data=_ToyClassify(16, seed=2),
              batch_size=16, epochs=10, verbose=0,
              callbacks=[es, CountEpochs()])
    assert len(stopped) < 10, "early stopping never fired"


def test_lr_scheduler_callback_steps():
    paddle.seed(0)
    net = _mlp()
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, CrossEntropyLoss())
    model.fit(_ToyClassify(32), batch_size=16, epochs=1, verbose=0)
    # 2 steps/epoch, step_size=2 -> one decay
    assert sched.last_lr < 0.1


def test_summary():
    net = _mlp()
    info = paddle.summary(net, (4, 8))
    # 8*16+16 + 16*2+2 = 178
    assert info["total_params"] == 178
    assert info["trainable_params"] == 178


def test_prepare_type_errors():
    net = _mlp()
    model = paddle.Model(net)
    with pytest.raises(TypeError):
        model.prepare(None, loss=123)
    with pytest.raises(RuntimeError):
        model.train_batch([np.zeros((2, 8), np.float32)], [np.zeros(2, np.int64)])


def test_model_fit_under_data_parallel_mesh():
    """Reference ``python/paddle/tests/dist_hapi_mnist_dynamic.py``: hapi
    Model.fit with the net wrapped for data parallelism — here on the
    8-device CPU mesh with batch sharding."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.io import Dataset

    class Ds(Dataset):
        def __init__(self, n=64):
            r = np.random.RandomState(0)
            self.x = r.randn(n, 8).astype(np.float32)
            self.y = (self.x.sum(1) > 0).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    dp_net = paddle.DataParallel(net) if hasattr(paddle, "DataParallel") \
        else paddle.distributed.DataParallel(net)
    model = paddle.Model(dp_net)
    model.prepare(paddle.optimizer.Adam(1e-2, parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy())
    model.fit(Ds(), batch_size=16, epochs=6, shuffle=False, verbose=0)
    res = model.evaluate(Ds(), batch_size=16, verbose=0)
    assert res["acc"] > 0.7, res
