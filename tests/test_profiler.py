"""paddle.profiler: scheduler state machine, RecordEvent capture, chrome
export, summary (reference python/paddle/profiler/profiler.py:271)."""
import glob
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, SortedKeys,
    export_chrome_tracing, load_profiler_result, make_scheduler,
)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=4, repeat=1, skip_first=1)
    states = [sched(i) for i in range(9)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3:6] == [ProfilerState.RECORD] * 3
    assert states[6] == ProfilerState.RECORD_AND_RETURN
    assert states[7] == ProfilerState.CLOSED          # repeat exhausted
    assert states[8] == ProfilerState.CLOSED


def test_record_event_noop_outside_profiler():
    ev = RecordEvent("nothing")
    ev.begin()
    ev.end()  # must not raise, must not record


def test_profiler_captures_train_step(tmp_path):
    traces = []

    def on_ready(prof):
        traces.append(prof.profiler_result)

    lin = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))

    prof = Profiler(targets=[ProfilerTarget.CPU],
                    scheduler=make_scheduler(closed=0, ready=1, record=2, repeat=1),
                    on_trace_ready=on_ready)
    prof.start()
    for i in range(4):
        with RecordEvent("train_step"):
            loss = lin(x).square().mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        prof.step()
    prof.stop()

    assert traces, "on_trace_ready never fired"
    names = [e.name for e in traces[0].events]
    assert "train_step" in names
    assert any(n.endswith(".step") for n in names), f"optimizer span missing: {names}"
    # summary builds a table over captured spans
    table = prof.summary()
    assert "train_step" in table


def test_chrome_export_roundtrip(tmp_path):
    out = str(tmp_path / "traces")
    prof = Profiler(targets=[ProfilerTarget.CPU],
                    on_trace_ready=export_chrome_tracing(out, worker_name="w0"))
    with prof:
        with RecordEvent("span_a"):
            time.sleep(0.001)
    files = glob.glob(os.path.join(out, "w0*.json"))
    assert files
    result = load_profiler_result(files[0])
    assert any(e.name == "span_a" for e in result.events)
    data = json.load(open(files[0]))
    assert data["traceEvents"][0]["ph"] == "X"


def test_context_manager_with_step_range_scheduler():
    with Profiler(targets=[ProfilerTarget.CPU], scheduler=(1, 3),
                  on_trace_ready=lambda p: None) as prof:
        for _ in range(4):
            with RecordEvent("w"):
                pass
            prof.step()
    assert prof.step_num == 4
    assert "step" in prof.step_info()


# ---------------------------------------------------------------------------
# make_scheduler state-machine edges
# ---------------------------------------------------------------------------

def test_make_scheduler_skip_first_only_delays_the_cycle():
    sched = make_scheduler(closed=2, ready=1, record=1, skip_first=3)
    # steps 0-2 are the skip_first window, CLOSED regardless of the cycle
    assert [sched(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    # then the cycle starts from its beginning: closed,closed,ready,record
    assert sched(3) == ProfilerState.CLOSED
    assert sched(4) == ProfilerState.CLOSED
    assert sched(5) == ProfilerState.READY
    assert sched(6) == ProfilerState.RECORD_AND_RETURN


def test_make_scheduler_single_step_record_window():
    # record=1: the sole record step of each cycle must RECORD_AND_RETURN
    sched = make_scheduler(closed=0, ready=0, record=1, repeat=2)
    assert sched(0) == ProfilerState.RECORD_AND_RETURN
    assert sched(1) == ProfilerState.RECORD_AND_RETURN
    assert sched(2) == ProfilerState.CLOSED  # repeat exhausted


def test_make_scheduler_repeat_exhaustion_stays_closed():
    sched = make_scheduler(closed=1, ready=0, record=2, repeat=2, skip_first=1)
    period = 3
    for i in range(1 + 2 * period, 1 + 2 * period + 10):
        assert sched(i) == ProfilerState.CLOSED
    # repeat=0 never exhausts
    sched0 = make_scheduler(closed=1, ready=0, record=2, repeat=0)
    assert sched0(3 * 1000 + 2) == ProfilerState.RECORD_AND_RETURN


def test_make_scheduler_negative_step_raises():
    sched = make_scheduler(closed=1, ready=1, record=1)
    import pytest

    with pytest.raises(ValueError):
        sched(-1)


# ---------------------------------------------------------------------------
# satellite fixes: summary sort, stop() in-flight step, save dirs, units
# ---------------------------------------------------------------------------

def _profiler_with_events(events):
    from paddle_tpu.profiler.profiler import _HostEvent

    prof = Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=lambda p: None)
    prof._events = [_HostEvent(name, "PythonUserDefined", 0, s, e)
                    for name, s, e in events]
    return prof


def test_summary_sorted_by_avg_uses_per_call_average(capsys):
    # A: 1 call of 10ms; B: 10 calls of 1.2ms (total 12ms)
    ms = 1_000_000
    events = [("A", 0, 10 * ms)]
    events += [("B", i * 20 * ms, i * 20 * ms + 12 * ms // 10)
               for i in range(1, 11)]
    prof = _profiler_with_events(events)
    by_total = prof.summary(sorted_by=SortedKeys.CPUTotal)
    by_avg = prof.summary(sorted_by=SortedKeys.CPUAvg)
    capsys.readouterr()

    def first_row_name(table):
        return table.splitlines()[2].split()[0]

    assert first_row_name(by_total) == "B"  # 12ms total beats 10ms
    assert first_row_name(by_avg) == "A"    # 10ms avg beats 1.2ms


def test_profiler_stop_keeps_inflight_step_duration():
    prof = Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=lambda p: None)
    prof.start()
    time.sleep(0.002)
    prof.step()
    time.sleep(0.002)
    prof.stop()  # the in-flight step must not be dropped
    assert len(prof._step_times) == 2
    assert all(t >= 0.002 for t in prof._step_times)
    assert "step" in prof.step_info()


def test_profiler_result_save_creates_nested_dirs(tmp_path):
    from paddle_tpu.profiler.profiler import ProfilerResult, _HostEvent

    res = ProfilerResult([_HostEvent("x", "t", 0, 0, 1000)])
    target = tmp_path / "deeply" / "nested" / "dir" / "trace.json"
    res.save(str(target))  # must not throw on the missing parents
    assert target.exists()
    assert load_profiler_result(str(target)).events[0].name == "x"


def test_step_info_honors_unit():
    prof = Profiler(targets=[ProfilerTarget.CPU], on_trace_ready=lambda p: None)
    prof._step_times = [0.5]
    assert "500.000 ms" in prof.step_info()
    assert "0.500 s" in prof.step_info(unit="s")
    assert "500000.000 us" in prof.step_info(unit="us")
