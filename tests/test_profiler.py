"""paddle.profiler: scheduler state machine, RecordEvent capture, chrome
export, summary (reference python/paddle/profiler/profiler.py:271)."""
import glob
import json
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, export_chrome_tracing,
    load_profiler_result, make_scheduler,
)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=4, repeat=1, skip_first=1)
    states = [sched(i) for i in range(9)]
    assert states[0] == ProfilerState.CLOSED          # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3:6] == [ProfilerState.RECORD] * 3
    assert states[6] == ProfilerState.RECORD_AND_RETURN
    assert states[7] == ProfilerState.CLOSED          # repeat exhausted
    assert states[8] == ProfilerState.CLOSED


def test_record_event_noop_outside_profiler():
    ev = RecordEvent("nothing")
    ev.begin()
    ev.end()  # must not raise, must not record


def test_profiler_captures_train_step(tmp_path):
    traces = []

    def on_ready(prof):
        traces.append(prof.profiler_result)

    lin = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))

    prof = Profiler(targets=[ProfilerTarget.CPU],
                    scheduler=make_scheduler(closed=0, ready=1, record=2, repeat=1),
                    on_trace_ready=on_ready)
    prof.start()
    for i in range(4):
        with RecordEvent("train_step"):
            loss = lin(x).square().mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        prof.step()
    prof.stop()

    assert traces, "on_trace_ready never fired"
    names = [e.name for e in traces[0].events]
    assert "train_step" in names
    assert any(n.endswith(".step") for n in names), f"optimizer span missing: {names}"
    # summary builds a table over captured spans
    table = prof.summary()
    assert "train_step" in table


def test_chrome_export_roundtrip(tmp_path):
    out = str(tmp_path / "traces")
    prof = Profiler(targets=[ProfilerTarget.CPU],
                    on_trace_ready=export_chrome_tracing(out, worker_name="w0"))
    with prof:
        with RecordEvent("span_a"):
            time.sleep(0.001)
    files = glob.glob(os.path.join(out, "w0*.json"))
    assert files
    result = load_profiler_result(files[0])
    assert any(e.name == "span_a" for e in result.events)
    data = json.load(open(files[0]))
    assert data["traceEvents"][0]["ph"] == "X"


def test_context_manager_with_step_range_scheduler():
    with Profiler(targets=[ProfilerTarget.CPU], scheduler=(1, 3),
                  on_trace_ready=lambda p: None) as prof:
        for _ in range(4):
            with RecordEvent("w"):
                pass
            prof.step()
    assert prof.step_num == 4
    assert "step" in prof.step_info()
