"""incubate.asp 2:4 sparsity, nn.quant QAT layers, distributed.elastic.
References: incubate/asp/asp.py, nn/quant/quant_layers.py,
distributed/elastic.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.incubate import asp


def _np(t):
    return np.asarray(t._value)


def test_asp_prune_and_density():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 4))
    masks = asp.prune_model(net)
    assert len(masks) == 2
    for p in (net[0].weight, net[2].weight):
        w = _np(p)
        assert asp.calculate_density(p) == pytest.approx(0.5)
        # every group of 4 along the REDUCTION dim (axis 0 for [in, out]
        # Linear weights) has exactly 2 nonzeros
        g = (w.T.reshape(w.shape[1], -1, 4) != 0).sum(-1)
        assert (g == 2).all()


def test_asp_training_stays_sparse():
    paddle.seed(1)
    net = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    asp.prune_model(net)
    opt = asp.decorate(opt)
    x = Tensor(np.random.RandomState(1).randn(4, 8).astype(np.float32))
    for _ in range(4):
        loss = net(x).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert asp.calculate_density(net.weight) == pytest.approx(0.5)
    with pytest.raises(TypeError):
        asp.decorate("nope")


def test_asp_conv_reduction_dim():
    """Conv weights group 2:4 along cin*kh*kw (the contraction), giving
    exact 0.5 density even when kh*kw is not a multiple of 4."""
    paddle.seed(5)
    conv = paddle.nn.Conv2D(4, 8, 3)  # reduction = 4*3*3 = 36, /4 = 9 groups
    asp.prune_model(conv)
    w = _np(conv.weight)
    assert asp.calculate_density(conv.weight) == pytest.approx(0.5)
    g = (w.reshape(w.shape[0], -1, 4) != 0).sum(-1)
    assert (g == 2).all()


def test_asp_excluded_layers():
    asp.reset_excluded_layers()
    paddle.seed(2)
    net = paddle.nn.Linear(8, 8)
    asp.set_excluded_layers([net.weight.name])
    try:
        masks = asp.prune_model(net)
        assert not masks
        assert asp.calculate_density(net.weight) == pytest.approx(1.0)
    finally:
        asp.reset_excluded_layers()


def test_quant_fake_abs_max_and_ste():
    from paddle_tpu.nn.quant import FakeQuantAbsMax

    q = FakeQuantAbsMax(quant_bits=8)
    x = Tensor(np.linspace(-1, 1, 9).astype(np.float32), stop_gradient=False)
    y = q(x)
    # quant-dequant error bounded by scale/qmax
    np.testing.assert_allclose(_np(y), _np(x), atol=1.0 / 127 + 1e-6)
    y.sum().backward()
    np.testing.assert_allclose(_np(x.grad), 1.0, atol=1e-6)  # STE inside range


def test_quantized_linear_trains():
    from paddle_tpu.nn.quant import quant_aware

    paddle.seed(3)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 1))
    quant_aware(net)
    from paddle_tpu.nn.quant import QuantizedLinear

    assert isinstance(net[0], QuantizedLinear)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    rng = np.random.RandomState(3)
    x = Tensor(rng.randn(16, 8).astype(np.float32))
    yt = Tensor(rng.randn(16, 1).astype(np.float32))
    losses = []
    for _ in range(8):
        loss = (net(x) - yt).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]
    # observer accumulated steps
    assert float(_np(net[0].act_quant.state)[0]) >= 8


def test_elastic_manager(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager, ElasticStatus

    d = str(tmp_path / "el")
    m0 = ElasticManager(elastic_dir=d, rank=0, world_size=2, timeout=5.0)
    m1 = ElasticManager(elastic_dir=d, rank=1, world_size=2, timeout=5.0)
    m0.register()
    assert m0.watch() == ElasticStatus.HOLD      # peer not yet arrived
    m1.register()
    assert m0.watch() is None                    # all healthy -> keep training
    assert m0.world() == [0, 1]
    m1.exit(completed=False)
    assert m0.watch() == ElasticStatus.RESTART   # peer failed
    m1.heartbeat()
    m0.exit(completed=True)
    m1.exit(completed=True)
    assert m0.watch() == ElasticStatus.COMPLETED


def test_elastic_stale_peer(tmp_path):
    import json
    import os
    import time

    from paddle_tpu.distributed.elastic import ElasticManager, ElasticStatus

    d = str(tmp_path / "el2")
    m0 = ElasticManager(elastic_dir=d, rank=0, world_size=2, timeout=0.2)
    m0.register()
    # a peer whose payload never changes again goes stale after `timeout`
    # of WATCHER-observed silence — the producer ts is an opaque change
    # marker, so cross-node clock skew cannot trigger false restarts
    with open(os.path.join(d, "rank1.json"), "w") as f:
        json.dump({"rank": 1, "ts": 123.0, "status": "running"}, f)
    assert m0.watch() is None          # first sighting just records it
    time.sleep(0.3)
    m0.heartbeat()                     # self stays fresh
    assert m0.watch() == ElasticStatus.RESTART


def test_elastic_skewed_but_alive_peer(tmp_path):
    """A peer with a wildly skewed clock that keeps heartbeating must NOT
    be flagged: staleness is watcher-observed payload-change age."""
    import json
    import os
    import time

    from paddle_tpu.distributed.elastic import ElasticManager, ElasticStatus

    d = str(tmp_path / "el3")
    m0 = ElasticManager(elastic_dir=d, rank=0, world_size=2, timeout=0.2)
    m0.register()
    for tick in range(4):
        # producer clock is an hour behind and drifting — payload changes
        with open(os.path.join(d, "rank1.json"), "w") as f:
            json.dump({"rank": 1, "ts": time.time() - 3600.0 + tick,
                       "status": "running"}, f)
        m0.heartbeat()
        assert m0.watch() is None
        time.sleep(0.1)
