"""Layer system tests (reference test_imperative_* suites)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_layer_registration_and_traversal():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    assert len(net.sublayers()) == 3
    out = net(paddle.randn([2, 4]))
    assert out.shape == [2, 2]


def test_train_eval_mode_propagates():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    x = paddle.ones([10, 4])
    y1, y2 = net(x), net(x)
    np.testing.assert_allclose(y1.numpy(), y2.numpy())
    net.train()
    assert net[1].training


def test_state_dict_roundtrip():
    net = nn.Sequential(nn.Linear(3, 5), nn.BatchNorm1D(5))
    sd = net.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "1.weight", "1.bias", "1._mean", "1._variance"}
    net2 = nn.Sequential(nn.Linear(3, 5), nn.BatchNorm1D(5))
    net2.set_state_dict(sd)
    np.testing.assert_allclose(net2[0].weight.numpy(), net[0].weight.numpy())


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm1D(4, momentum=0.5)
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("float32") * 3 + 1)
    bn.train()
    bn(x)
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    m0 = bn._mean.numpy().copy()
    bn(x)
    np.testing.assert_allclose(bn._mean.numpy(), m0)  # eval must not update


def test_forward_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h1 = lin.register_forward_pre_hook(lambda layer, inp: calls.append("pre"))
    h2 = lin.register_forward_post_hook(lambda layer, inp, out: calls.append("post"))
    lin(paddle.ones([1, 2]))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()
    calls.clear()
    lin(paddle.ones([1, 2]))
    assert calls == []


def test_containers():
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8
    pl = nn.ParameterList([paddle.create_parameter([2, 2], "float32")])
    assert len(list(pl.parameters())) == 1
    sd = nn.LayerDict({"a": nn.Linear(2, 2)})
    assert "a" in sd


def test_layer_to_dtype():
    net = nn.Linear(2, 2)
    net.to(dtype="bfloat16")
    assert str(net.weight.dtype) == "bfloat16"


def test_parameter_trainable_flag():
    lin = nn.Linear(2, 2)
    lin.weight.trainable = False
    out = lin(paddle.ones([1, 2])).sum()
    out.backward()
    assert lin.weight.grad is None
    assert lin.bias.grad is not None


def test_clear_gradients():
    lin = nn.Linear(2, 2)
    lin(paddle.ones([1, 2])).sum().backward()
    lin.clear_gradients()
    assert lin.weight.grad is None
