"""Ring attention / sequence parallelism over the sep mesh axis.
Green-field design (SURVEY §5: reference has zero SP/CP code). Parity vs
single-device attention at sep=2/4, gradients included, plus the GPT
flagship under dp×sep."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name

from capability import requires_spmd_partition_id


def _init_fleet(dp=1, mp=1, pp=1, sep=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = dp
    strategy.hybrid_configs["mp_degree"] = mp
    strategy.hybrid_configs["pp_degree"] = pp
    strategy.hybrid_configs["sep_degree"] = sep
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _ref_sdpa(q, k, v, causal):
    return F.scaled_dot_product_attention(q, k, v, is_causal=causal,
                                          training=False)


@pytest.mark.parametrize("sep,causal", [(2, True), (2, False), (4, True)])
def test_ring_attention_matches_single_device(sep, causal):
    from paddle_tpu.distributed.meta_parallel import ring_attention

    _init_fleet(sep=sep)
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 64, 4, 16
    qv = rng.randn(b, s, h, d).astype(np.float32)
    kv = rng.randn(b, s, h, d).astype(np.float32)
    vv = rng.randn(b, s, h, d).astype(np.float32)

    out = ring_attention(Tensor(qv), Tensor(kv), Tensor(vv), is_causal=causal)
    ref = _ref_sdpa(Tensor(qv), Tensor(kv), Tensor(vv), causal)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value),
                               atol=2e-5)


def test_ring_attention_gradients_match():
    from paddle_tpu.distributed.meta_parallel import ring_attention

    _init_fleet(sep=2)
    rng = np.random.RandomState(1)
    b, s, h, d = 2, 32, 2, 8
    qv = rng.randn(b, s, h, d).astype(np.float32)
    kv = rng.randn(b, s, h, d).astype(np.float32)
    vv = rng.randn(b, s, h, d).astype(np.float32)
    gv = rng.randn(b, s, h, d).astype(np.float32)

    q1, k1, v1 = (Tensor(x, stop_gradient=False) for x in (qv, kv, vv))
    out1 = ring_attention(q1, k1, v1, is_causal=True)
    (out1 * Tensor(gv)).sum().backward()

    q2, k2, v2 = (Tensor(x, stop_gradient=False) for x in (qv, kv, vv))
    out2 = _ref_sdpa(q2, k2, v2, True)
    (out2 * Tensor(gv)).sum().backward()

    for a, b_ in ((q1, q2), (k1, k2), (v1, v2)):
        np.testing.assert_allclose(np.asarray(a.grad._value),
                                   np.asarray(b_.grad._value), atol=3e-5)


def test_ring_attention_rectangular_heads_and_seq():
    """seq not equal across b/h dims and sep=2 with s/2 chunks of 48."""
    from paddle_tpu.distributed.meta_parallel import ring_attention

    _init_fleet(sep=2)
    rng = np.random.RandomState(2)
    b, s, h, d = 1, 96, 3, 8
    qv = rng.randn(b, s, h, d).astype(np.float32)
    out = ring_attention(Tensor(qv), Tensor(qv), Tensor(qv), is_causal=True)
    ref = _ref_sdpa(Tensor(qv), Tensor(qv), Tensor(qv), True)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value),
                               atol=2e-5)


@requires_spmd_partition_id()
def test_gpt_with_sep_matches_plain():
    """GPT flagship under dp2×sep2: same loss as the plain single-mesh model,
    gradients flow."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    rng = np.random.RandomState(3)
    ids_np = rng.randint(0, 64, (4, 32)).astype(np.int64)

    def build(use_sep):
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        use_sep=use_sep)
        with unique_name.guard():
            paddle.seed(0)
            return GPTForCausalLM(cfg)

    _init_fleet(dp=1)  # plain
    ref = build(False)
    l_ref = ref.loss(Tensor(ids_np), Tensor(ids_np))
    l_ref.backward()
    g_ref = np.asarray(ref.gpt.embeddings.word_embeddings.weight.grad._value)

    _init_fleet(dp=2, sep=2)
    model = build(True)
    assert model.gpt.layers[0]._use_sep
    l_sep = model.loss(Tensor(ids_np), Tensor(ids_np))
    l_sep.backward()
    g_sep = np.asarray(model.gpt.embeddings.word_embeddings.weight.grad._value)

    np.testing.assert_allclose(float(np.asarray(l_sep._value)),
                               float(np.asarray(l_ref._value)), rtol=2e-5)
    np.testing.assert_allclose(g_sep, g_ref, atol=3e-5)


@requires_spmd_partition_id()
def test_gpt_sep_jitted_train_step():
    """The sep model trains inside one jitted step (CompiledStep)."""
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    _init_fleet(dp=2, sep=2)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, hidden_dropout=0.0,
                    attention_dropout=0.0, use_sep=True)
    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[model, opt])
    ids = Tensor(np.random.RandomState(4).randint(0, 64, (4, 32)).astype(np.int64))
    l0 = float(np.asarray(cs(ids, ids)._value))
    for _ in range(4):
        l1 = float(np.asarray(cs(ids, ids)._value))
    assert np.isfinite(l1) and l1 < l0


def test_pp_with_sep_raises_clearly():
    """Ring attention cannot nest inside the pp-manual pipeline stage (sdy
    forbids re-binding the parent's manual axis) — must fail loudly."""
    from paddle_tpu.distributed.meta_parallel import build_pipelined_gpt
    from paddle_tpu.models import GPTConfig

    hcg = _init_fleet(dp=1, pp=2, sep=2)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, hidden_dropout=0.0,
                    attention_dropout=0.0, use_sep=True)
    with pytest.raises(ValueError, match="pp>1 AND sep>1"):
        build_pipelined_gpt(cfg, hcg, num_microbatches=2)


def test_ring_attention_dropout():
    """Per-chunk dropout over the sep ring: deterministic given the RNG
    state, unbiased in expectation, differentiable (round-4: lifts the
    former use_sep+dropout restriction)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
        ring_attention,
    )

    _init_fleet(sep=4)
    rng_np = np.random.RandomState(0)
    q = Tensor(rng_np.randn(2, 32, 2, 8).astype(np.float32))
    k = Tensor(rng_np.randn(2, 32, 2, 8).astype(np.float32))
    v = Tensor(rng_np.randn(2, 32, 2, 8).astype(np.float32))

    base = np.asarray(ring_attention(q, k, v, is_causal=True)._value)

    paddle.seed(123)
    d1 = np.asarray(ring_attention(q, k, v, is_causal=True,
                                   dropout_p=0.3)._value)
    paddle.seed(123)
    d2 = np.asarray(ring_attention(q, k, v, is_causal=True,
                                   dropout_p=0.3)._value)
    np.testing.assert_array_equal(d1, d2)        # deterministic given seed
    assert not np.allclose(d1, base)             # dropout perturbs

    # unbiased: mean over draws approaches the no-dropout output
    paddle.seed(0)
    acc = np.zeros_like(base)
    n = 24
    for _ in range(n):
        acc += np.asarray(ring_attention(q, k, v, is_causal=True,
                                         dropout_p=0.3)._value)
    err = np.abs(acc / n - base).mean() / (np.abs(base).mean() + 1e-9)
    assert err < 0.2, err

    # differentiable end to end
    paddle.seed(7)
    q2 = Tensor(rng_np.randn(2, 32, 2, 8).astype(np.float32))
    q2.stop_gradient = False
    out = ring_attention(q2, k, v, is_causal=True, dropout_p=0.25)
    (out * out).mean().backward()
    g = np.asarray(q2.grad._value)
    assert np.isfinite(g).all() and np.abs(g).max() > 0
