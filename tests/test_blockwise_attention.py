"""Blockwise cached attention (ISSUE 15): the length-masked KV-block scan
behind ``scaled_dot_product_attention(attn_mask=LengthMask(...))``.

Contracts under test:
  * LengthMask semantics — ``valid``/``additive`` match the numpy
    reference for every (q_pos, kv_len) combination the serving engine
    builds (prefill, chunked prefill, decode, verify window);
  * numeric parity — the blockwise online-softmax scan matches the dense
    einsum fallback on the SAME LengthMask for prefill chunks, verify
    windows, and decode at mid-bucket and bucket-boundary lengths, in
    value AND gradient (the custom_vjp backward recurrence);
  * fully-masked rows — a slot with ``kv_len == 0`` yields zeros, never
    NaN (the exp(s - m) guard);
  * greedy serving stays byte-identical with blockwise forced on, and the
    PR 13 O(1)-compile gates hold unchanged: decode compiles EXACTLY once
    over 64+ tokens with the scan path active.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import get_flags, set_flags
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.nn.functional import LengthMask
from paddle_tpu.profiler import telemetry
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.utils import unique_name

_FLAG_NAMES = ["disable_blockwise_attention", "blockwise_attention_min_kv",
               "blockwise_attention_block_q", "blockwise_attention_block_k"]


@pytest.fixture(autouse=True)
def _restore_flags():
    saved = get_flags(_FLAG_NAMES)
    yield
    set_flags(saved)


@pytest.fixture
def _no_persistent_compile_cache():
    """Same hazard as tests/test_serving.py: parity across separately
    compiled executables is only bit-exact with in-process compiles."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _qkv(b, sq, sk, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    k = rng.randn(b, sk, h, d).astype(np.float32)
    v = rng.randn(b, sk, h, d).astype(np.float32)
    return q, k, v


def _sdpa_lm(q, k, v, lm):
    out = F.scaled_dot_product_attention(
        Tensor(q), Tensor(k), Tensor(v), attn_mask=lm, training=False)
    return np.asarray(out._value)


def _both_paths(q, k, v, lm):
    """(dense einsum fallback, forced blockwise scan) on the same mask."""
    set_flags({"blockwise_attention_min_kv": 10 ** 9})
    dense = _sdpa_lm(q, k, v, lm)
    set_flags({"blockwise_attention_min_kv": 1})
    block = _sdpa_lm(q, k, v, lm)
    return dense, block


# ---------------------------------------------------------------------------
# LengthMask semantics
# ---------------------------------------------------------------------------
def test_length_mask_valid_matches_numpy_reference():
    q_pos = np.array([[3, 4, 5], [0, 1, 2]], np.int32)
    kv_len = np.array([5, 2], np.int32)
    lm = LengthMask(q_pos, kv_len)
    got = np.asarray(lm.valid(8))
    assert got.shape == (2, 1, 3, 8)
    j = np.arange(8)
    want = (j[None, None, None, :] <= q_pos[:, None, :, None]) \
        & (j[None, None, None, :] < kv_len[:, None, None, None])
    np.testing.assert_array_equal(got, want)
    # additive: 0 where valid, mask_min elsewhere, in the requested dtype
    add = np.asarray(lm.additive(8, jnp.float32))
    np.testing.assert_array_equal(add == 0.0, want)
    np.testing.assert_array_equal(add == -1e9, ~want)


def test_length_mask_without_kv_len_is_pure_causal():
    lm = LengthMask(np.arange(4, dtype=np.int32)[None, :])
    got = np.asarray(lm.valid(4))[0, 0]
    np.testing.assert_array_equal(got, np.tril(np.ones((4, 4), bool)))


# ---------------------------------------------------------------------------
# blockwise-vs-einsum numeric parity, engine-shaped masks
# ---------------------------------------------------------------------------
def test_parity_prefill_full_bucket():
    # serve_prefill: q_pos = arange(bucket)[None], kv_len = [prompt_len]
    q, k, v = _qkv(1, 16, 16)
    lm = LengthMask(np.arange(16, dtype=np.int32)[None, :],
                    np.array([9], np.int32))
    dense, block = _both_paths(q, k, v, lm)
    np.testing.assert_allclose(block, dense, rtol=1e-5, atol=1e-5)


def test_parity_prefill_chunk_at_offset():
    # serve_prefill_chunk: q_pos = offset + arange(chunk), kv = max_len
    off, chunk, max_len = 8, 8, 32
    q, k, v = _qkv(1, chunk, max_len, seed=1)
    lm = LengthMask((off + np.arange(chunk, dtype=np.int32))[None, :])
    dense, block = _both_paths(q, k, v, lm)
    np.testing.assert_allclose(block, dense, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pos", [13, 31])  # mid-bucket / bucket boundary
def test_parity_decode_single_row(pos):
    # serve_decode: q_pos = [b, 1] clamped position, kv_len = lengths
    q, k, v = _qkv(2, 1, 32, seed=2)
    lm = LengthMask(np.array([[pos], [5]], np.int32),
                    np.array([pos + 1, 6], np.int32))
    dense, block = _both_paths(q, k, v, lm)
    np.testing.assert_allclose(block, dense, rtol=1e-5, atol=1e-5)


def test_parity_verify_window():
    # serve_verify: q_pos = pos0[:, None] + arange(W), kv_len = pos0 + W
    W = 4
    pos0 = np.array([5, 11], np.int32)
    q, k, v = _qkv(2, W, 32, seed=3)
    lm = LengthMask(pos0[:, None] + np.arange(W, dtype=np.int32)[None, :],
                    pos0 + W)
    dense, block = _both_paths(q, k, v, lm)
    np.testing.assert_allclose(block, dense, rtol=1e-5, atol=1e-5)


def test_parity_odd_lengths_pick_divisor_blocks():
    # sk = 24 with preferred block 512 -> block 24; with block_k=7 -> 6
    q, k, v = _qkv(1, 5, 24, seed=4)
    lm = LengthMask(np.full((1, 5), 23, np.int32), np.array([17], np.int32))
    set_flags({"blockwise_attention_block_q": 7,
               "blockwise_attention_block_k": 7})
    dense, block = _both_paths(q, k, v, lm)
    np.testing.assert_allclose(block, dense, rtol=1e-5, atol=1e-5)


def test_blockwise_grads_match_einsum_causal_training():
    # the long-causal-training branch: attn_mask=None, is_causal=True
    q, k, v = _qkv(2, 16, 16, seed=5)
    w = np.random.RandomState(6).randn(*q.shape).astype(np.float32)

    def run():
        tq, tk, tv = (paddle.to_tensor(a, stop_gradient=False)
                      for a in (q, k, v))
        out = F.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
        (out * Tensor(w)).sum().backward()
        return (np.asarray(out._value),
                [np.asarray(t.grad._value) for t in (tq, tk, tv)])

    set_flags({"disable_blockwise_attention": True})
    ref_out, ref_g = run()
    set_flags({"disable_blockwise_attention": False,
               "blockwise_attention_min_kv": 1})
    got_out, got_g = run()
    np.testing.assert_allclose(got_out, ref_out, rtol=1e-5, atol=1e-5)
    for g, r in zip(got_g, ref_g):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-5)


def test_fully_masked_rows_are_zero_not_nan():
    q, k, v = _qkv(2, 4, 16, seed=7)
    # slot 1 has an empty cache: every key invalid for every query row
    lm = LengthMask(np.tile(np.arange(4, dtype=np.int32), (2, 1)),
                    np.array([16, 0], np.int32))
    set_flags({"blockwise_attention_min_kv": 1})
    out = _sdpa_lm(q, k, v, lm)
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))


# ---------------------------------------------------------------------------
# serving stays byte-identical + the PR 13 compile gates hold
# ---------------------------------------------------------------------------
def _serve_model(seed=0):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=2,
            max_position_embeddings=128, hidden_dropout=0.0,
            attention_dropout=0.0, initializer_range=0.6))
    model.eval()
    return model


def test_greedy_serving_byte_identical_with_blockwise_forced(
        _no_persistent_compile_cache):
    model = _serve_model()
    prompt = np.random.RandomState(11).randint(0, 512, 7).tolist()

    def gen():
        eng = GenerationEngine(model, max_batch=2, max_len=64,
                               prefill_buckets=(8, 16))
        return eng.generate(prompt, max_new_tokens=16)

    base = gen()
    set_flags({"blockwise_attention_min_kv": 1})
    forced = gen()
    assert len(set(base)) > 2, "degenerate model; parity check is vacuous"
    assert forced == base


def test_chunked_prefill_byte_identical_with_blockwise_forced(
        _no_persistent_compile_cache):
    model = _serve_model(seed=1)
    prompt = np.random.RandomState(12).randint(0, 512, 21).tolist()

    def gen():
        eng = GenerationEngine(model, max_batch=2, max_len=64,
                               prefill_buckets=(8, 16, 32),
                               prefill_chunk=8)
        return eng.generate(prompt, max_new_tokens=12)

    base = gen()
    set_flags({"blockwise_attention_min_kv": 1})
    forced = gen()
    assert forced == base


def test_decode_still_compiles_once_with_blockwise_forced():
    set_flags({"blockwise_attention_min_kv": 1})
    model = _serve_model()
    telemetry.reset()
    telemetry.enable()
    try:
        eng = GenerationEngine(model, max_batch=2, max_len=128,
                               prefill_buckets=(8, 16))
        out = eng.generate([5, 6, 7], max_new_tokens=65)
        counts = telemetry.get_telemetry().compile_counts()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert len(out) == 65
    assert counts.get("serve_decode") == 1, counts
    assert counts.get("serve_prefill") == 1, counts
