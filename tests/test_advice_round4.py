"""Round-4 advisor-finding regression tests (ADVICE.md round 3):
DGC applicability warning, SelectedRows demoted-cache accumulate, istft
NOLA raise, spawn err_q drain."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def test_dgc_non_momentum_warns():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.dgc = True
    fleet.init(is_collective=True, strategy=strategy)
    net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    with pytest.warns(UserWarning, match="DGC is NOT applied"):
        fleet.distributed_optimizer(opt)


def test_selected_rows_accumulate_after_dense_demotion():
    """A dense write (e.g. grad-clip rescale) must survive a subsequent
    SelectedRows accumulation instead of being discarded."""
    from paddle_tpu.framework.selected_rows import SelectedRows, SparseGradTensor
    import jax.numpy as jnp

    sr = SelectedRows(jnp.array([0, 2]), jnp.ones((2, 3)), height=4)
    g = SparseGradTensor(sr)
    base = np.asarray(g._value)  # densify
    g._value = g._value * 10.0   # demoting dense write
    sr2 = SelectedRows(jnp.array([1]), jnp.ones((1, 3)), height=4)
    g.accumulate(sr2)
    want = base * 10.0
    want[1] += 1.0
    np.testing.assert_allclose(np.asarray(g._value), want)


def test_istft_nola_violation_raises():
    # a window that is zero over each hop stride can never reconstruct
    win = np.zeros(64, np.float32)
    win[0:4] = 1.0
    x = np.random.RandomState(0).randn(256).astype(np.float32)
    spec = paddle.signal.stft(Tensor(x), 64, hop_length=32,
                              window=Tensor(win))
    with pytest.raises(ValueError, match="NOLA"):
        paddle.signal.istft(spec, 64, hop_length=32, window=Tensor(win))


def test_istft_valid_window_still_works():
    win = np.hanning(64).astype(np.float32)
    x = np.random.RandomState(1).randn(256).astype(np.float32)
    spec = paddle.signal.stft(Tensor(x), 64, hop_length=16,
                              window=Tensor(win))
    back = paddle.signal.istft(spec, 64, hop_length=16, window=Tensor(win))
    assert np.isfinite(back.numpy()).all()


def test_spawn_failing_worker_traceback_surfaces():
    """A worker that dies with a large traceback must not deadlock join;
    the parent collects and re-raises with the rank's traceback."""
    from paddle_tpu.distributed.spawn import spawn

    with pytest.raises(RuntimeError, match="workers failed"):
        spawn(_boom, args=(), nprocs=2, join=True)


def _boom():
    # sizeable traceback payload to stress the queue pipe buffer
    raise RuntimeError("x" * 100_000)
