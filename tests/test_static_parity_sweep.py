"""Static-mode parity sweep: every op-table entry recorded into a Program
and replayed by the Executor must match its eager result — the reference's
dygraph/static cross-checking (unittests/op_test.py runs each op in both
modes) applied across the table."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.op_table import OPS

from tests.test_op_grad_sweep import _ADAPTERS, _draw, _ids, _resolve

# entries whose adapters do python-level introspection the symbolic recorder
# cannot trace, or whose ops are eager-only by design
_SKIP = {
    "F.dropout_eval",           # no-op passthrough, nothing recorded
}


def _entry_ids():
    return _ids()


@pytest.mark.parametrize("entry", OPS, ids=_entry_ids())
def test_static_matches_eager(entry):
    if entry["api"] in _SKIP:
        pytest.skip("eager-only adapter")
    fn = _resolve(entry["api"])
    import zlib

    rng = np.random.RandomState(zlib.crc32(("static" + entry["api"]).encode()) % (2**31))
    arrays = [_draw(s, d, rng) for s, d in entry["inputs"]]
    kwargs = entry["kwargs"]

    # eager reference
    eager_out = fn(*[Tensor(a) for a in arrays], **kwargs)
    if isinstance(eager_out, (tuple, list)):
        eager_out = eager_out[0]
    eager_np = np.asarray(eager_out._value)

    # static: placeholders for every input, record, replay
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            feeds = {}
            args = []
            for i, a in enumerate(arrays):
                name = f"in{i}"
                dt = str(a.dtype)
                v = paddle.static.data(name, list(a.shape), dt)
                feeds[name] = a
                args.append(v)
            out = fn(*args, **kwargs)
            if isinstance(out, (tuple, list)):
                out = out[0]
        exe = paddle.static.Executor()
        exe.run(startup)
        (got,) = exe.run(main, feed=feeds, fetch_list=[out.name])
    finally:
        paddle.disable_static()

    np.testing.assert_allclose(got, eager_np, rtol=1e-5, atol=1e-5,
                               err_msg=entry["api"])


def test_embedding_negative_padding_idx():
    """paddle accepts padding_idx in [-vocab, vocab): -1 masks the last row."""
    import paddle_tpu.nn.functional as F

    w = Tensor(np.ones((4, 3), np.float32))
    ids = Tensor(np.array([0, 3, 2], np.int64))
    out = np.asarray(F.embedding(ids, w, padding_idx=-1)._value)
    np.testing.assert_allclose(out[1], 0.0)   # id 3 == vocab-1 masked
    np.testing.assert_allclose(out[0], 1.0)
