"""SelectedRows sparse embedding gradients + StringTensor.

Reference: ``phi/core/selected_rows.h`` (Embedding(sparse=True) grads),
``operators/math/selected_rows_functor.cc`` (MergeAdd),
``phi/core/string_tensor.h`` + ``phi/kernels/strings/``.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.selected_rows import SelectedRows, SparseGradTensor
from paddle_tpu.framework.string_tensor import (
    StringTensor,
    strings_lower,
    strings_upper,
)


def test_sparse_embedding_grad_is_selected_rows():
    paddle.seed(0)
    emb = nn.Embedding(100, 8, sparse=True)
    ids = paddle.to_tensor(np.array([[1, 5, 5], [7, 1, 3]], np.int64))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SparseGradTensor)
    sr = g.selected_rows
    assert sr.height == 100 and sr.values.shape == (6, 8)
    # dense equivalence matches the dense embedding's gradient
    paddle.seed(0)
    emb_d = nn.Embedding(100, 8, sparse=False)
    out_d = emb_d(ids)
    out_d.sum().backward()
    np.testing.assert_allclose(np.asarray(g._value),
                               emb_d.weight.grad.numpy(), rtol=1e-6)


def test_sparse_sgd_updates_only_touched_rows():
    paddle.seed(1)
    emb = nn.Embedding(50, 4, sparse=True)
    w0 = emb.weight.numpy().copy()
    opt = paddle.optimizer.SGD(0.5, parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([2, 7, 7, 11], np.int64))
    (emb(ids) ** 2).sum().backward()
    opt.step()
    opt.clear_grad()
    w1 = emb.weight.numpy()
    touched = {2, 7, 11}
    for r in range(50):
        if r in touched:
            assert not np.allclose(w1[r], w0[r]), r
        else:
            np.testing.assert_array_equal(w1[r], w0[r])


def test_sparse_matches_dense_training():
    """Sparse and dense embeddings must follow the same SGD trajectory."""
    ids_batches = [np.array([3, 9, 9, 40], np.int64),
                   np.array([0, 3, 17, 9], np.int64)]

    def run(sparse):
        paddle.seed(2)
        emb = nn.Embedding(64, 4, sparse=sparse)
        opt = paddle.optimizer.SGD(0.1, parameters=emb.parameters())
        for ids in ids_batches:
            (emb(paddle.to_tensor(ids)) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()
        return emb.weight.numpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_sparse_padding_idx_rows_frozen():
    paddle.seed(3)
    emb = nn.Embedding(20, 4, sparse=True, padding_idx=0)
    opt = paddle.optimizer.SGD(0.5, parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([0, 1, 0, 2], np.int64))
    (emb(ids) ** 2).sum().backward()
    opt.step()
    np.testing.assert_array_equal(emb.weight.numpy()[0], np.zeros(4))


def test_selected_rows_merge_and_dense():
    import jax.numpy as jnp

    sr = SelectedRows(jnp.asarray([3, 1, 3], jnp.int32),
                      jnp.asarray([[1.0], [2.0], [10.0]]), height=5)
    merged = sr.merge_rows()
    dense = np.asarray(merged.to_dense()).reshape(-1)
    np.testing.assert_allclose(dense, [0, 2, 0, 11, 0])
    np.testing.assert_allclose(np.asarray(sr.to_dense()).reshape(-1),
                               [0, 2, 0, 11, 0])


def test_adam_densifies_sparse_grad():
    """Optimizers without a sparse kernel consume the dense equivalence
    (reference: non-sparse-supporting ops densify SelectedRows)."""
    paddle.seed(4)
    emb = nn.Embedding(30, 4, sparse=True)
    opt = paddle.optimizer.Adam(0.1, parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([5, 6], np.int64))
    (emb(ids) ** 2).sum().backward()
    opt.step()  # must not raise; trajectory equals dense Adam
    paddle.seed(4)
    emb_d = nn.Embedding(30, 4, sparse=False)
    opt_d = paddle.optimizer.Adam(0.1, parameters=emb_d.parameters())
    (emb_d(paddle.to_tensor(np.array([5, 6], np.int64))) ** 2).sum().backward()
    opt_d.step()
    np.testing.assert_allclose(emb.weight.numpy(), emb_d.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_string_tensor_kernels():
    st = StringTensor([["Hello", "WORLD"], ["PaddlePaddle", "TPU"]])
    assert st.shape == [2, 2]
    low = strings_lower(st)
    up = strings_upper(st)
    assert low.tolist() == [["hello", "world"], ["paddlepaddle", "tpu"]]
    assert up.tolist() == [["HELLO", "WORLD"], ["PADDLEPADDLE", "TPU"]]
    assert st[0, 0] == "Hello"
    assert len(st) == 2
    assert (st == st).all()
