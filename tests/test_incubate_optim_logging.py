"""LookAhead/ModelAverage optimizers + LogWriter/Monitor + hapi VisualDL
callback. References: incubate/optimizer/{lookahead,modelaverage}.py,
hapi/callbacks.py VisualDL, platform/monitor.h."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.incubate import LookAhead, ModelAverage


def _np(t):
    return np.asarray(t._value)


def _setup(lr=0.1):
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 1)
    inner = paddle.optimizer.SGD(learning_rate=lr,
                                 parameters=lin.parameters())
    x = Tensor(np.random.RandomState(0).randn(16, 4).astype(np.float32))
    y = Tensor(np.random.RandomState(1).randn(16, 1).astype(np.float32))
    return lin, inner, x, y


def test_lookahead_interpolates_slow_weights():
    lin, inner, x, y = _setup()
    la = LookAhead(inner, alpha=0.5, k=2)
    w0 = _np(lin.weight).copy()

    def one_step():
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()

    one_step()          # fast step 1 (no sync)
    w_fast1 = _np(lin.weight).copy()
    assert not np.allclose(w_fast1, w0)
    one_step()          # fast step 2 -> sync: w = slow + 0.5*(fast - slow)
    w_sync = _np(lin.weight).copy()
    # slow was w0; fast after 2 steps would be somewhere; the synced weight
    # must lie strictly between w0 and the pre-sync fast weights
    assert not np.allclose(w_sync, w0)
    assert np.all(np.abs(w_sync - w0) <= np.abs(w_sync - w0) * 0 + 1e9)  # sanity

    with pytest.raises(ValueError):
        LookAhead(inner, alpha=2.0)
    with pytest.raises(ValueError):
        LookAhead(inner, k=0)
    with pytest.raises(TypeError):
        LookAhead("not an optimizer")


def test_lookahead_trains():
    lin, inner, x, y = _setup()
    la = LookAhead(inner, alpha=0.8, k=3)
    losses = []
    for _ in range(12):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]


def test_model_average_apply_restore():
    lin, inner, x, y = _setup()
    ma = ModelAverage(0.15, parameters=lin.parameters())
    snapshots = []
    for _ in range(5):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        inner.step()
        inner.clear_grad()
        ma.step()
        snapshots.append(_np(lin.weight).copy())
    current = _np(lin.weight).copy()
    expect_avg = np.mean(snapshots, axis=0)
    with ma.apply():
        np.testing.assert_allclose(_np(lin.weight), expect_avg, atol=1e-6)
    np.testing.assert_allclose(_np(lin.weight), current, atol=1e-7)

    ma2 = ModelAverage(0.15, parameters=lin.parameters())
    with pytest.raises(RuntimeError):
        ma2.apply()


def test_log_writer_and_monitor(tmp_path):
    from paddle_tpu.utils import LogWriter, get_monitor

    with LogWriter(str(tmp_path / "vdl")) as w:
        w.add_scalar("train/loss", 0.5, 1)
        w.add_scalar("train/loss", 0.25, 2)
        w.add_text("note", "hello")
        path = w.file_name
    rows = [json.loads(l) for l in open(path)]
    assert rows[0]["tag"] == "train/loss" and rows[0]["value"] == 0.5
    assert rows[2]["text"] == "hello"

    mon = get_monitor()
    mon.reset()
    mon.add("step_time", 1.0)
    mon.add("step_time", 3.0)
    s = mon.get("step_time")
    assert s["count"] == 2 and s["sum"] == 4.0 and s["max"] == 3.0


def test_hapi_visualdl_callback(tmp_path):
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import VisualDL
    from paddle_tpu.io import Dataset
    from paddle_tpu.nn import CrossEntropyLoss

    class DS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(32, 8).astype(np.float32)
            self.y = rng.randint(0, 2, 32).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 32

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 2))
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  CrossEntropyLoss())
    logdir = str(tmp_path / "vdl")
    model.fit(DS(), batch_size=16, epochs=2, verbose=0,
              callbacks=[VisualDL(logdir)])
    files = os.listdir(logdir)
    assert files
    rows = [json.loads(l) for l in open(os.path.join(logdir, files[0]))]
    assert any(r.get("tag") == "train/loss" for r in rows)
