"""Backend capability probes for environment-dependent skips.

The tier-1 suite runs on a virtual 8-device XLA:CPU mesh (conftest.py).
Some programs the framework legitimately emits are rejected by that
backend — e.g. the SPMD partitioner cannot place a ``PartitionId``
instruction (``UNIMPLEMENTED``), which partial-manual ``shard_map`` regions
(manual over pp/sep only, auto over dp/mp) produce via ``axis_index`` /
``ppermute``. Real TPUs partition these fine.

Rather than hard-skipping by platform name, each probe ATTEMPTS the minimal
failing construct and skips only when the backend actually rejects it — so
the tests turn back on by themselves the day the backend learns the
feature. Probes run in a SUBPROCESS: near-miss variants of these programs
die in uncatchable XLA CHECK aborts (SIGABRT), which must not take the
pytest process down with them.
"""
from __future__ import annotations

import functools
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the minimal form of the 4 known-failing tier-1 cases: a dp×sep hybrid
# mesh, replicated inputs entering jit, and the ring-attention shard_map
# (manual over sep ONLY) rotating KV chunks with ppermute/axis_index inside
_PARTITION_ID_PROBE = """
import os
if os.environ.get("PADDLE_TPU_HW_TESTS") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
import numpy as np
import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import ensure_jax_compat
ensure_jax_compat()
from jax.sharding import NamedSharding, PartitionSpec as P
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.tensor import Tensor

strategy = fleet.DistributedStrategy()
strategy.hybrid_configs["dp_degree"] = 2
strategy.hybrid_configs["sep_degree"] = 2
fleet.init(is_collective=True, strategy=strategy)
from paddle_tpu.distributed.meta_parallel import ring_attention
mesh = fleet.get_hybrid_communicate_group().mesh

def f(q, k, v):
    return ring_attention(Tensor(q), Tensor(k), Tensor(v),
                          is_causal=True)._value

x = jax.device_put(jnp.ones((2, 8, 2, 4), jnp.float32),
                   NamedSharding(mesh, P()))
np.asarray(jax.jit(f)(x, x, x))
print("PROBE_OK")
"""


@functools.lru_cache(maxsize=1)
def spmd_partition_id_supported():
    """True when the backend can SPMD-partition programs containing
    ``PartitionId`` (partial-manual shard_map collectives)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PARTITION_ID_PROBE],
            env=env, capture_output=True, timeout=300)
    except Exception:
        return False
    return proc.returncode == 0 and b"PROBE_OK" in proc.stdout


def requires_spmd_partition_id():
    """Skip marker for tests whose mesh/program shape needs PartitionId
    under SPMD partitioning (hybrid meshes with auto axes alongside a
    manual shard_map axis)."""
    import pytest

    return pytest.mark.skipif(
        not spmd_partition_id_supported(),
        reason="backend cannot SPMD-partition PartitionId (partial-manual "
               "shard_map over a hybrid mesh) — UNIMPLEMENTED on XLA:CPU")
