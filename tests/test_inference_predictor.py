"""Inference Predictor exercised end-to-end (round-3 VERDICT weak #7):
jit-save a BERT classifier with a DYNAMIC batch dim, load it through
``create_predictor``, run the handle-oriented API at two batch sizes, and
check parity with the eager model. Reference:
``paddle/fluid/inference/api/analysis_predictor.cc``."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit.save_load import InputSpec
from paddle_tpu.models import BertConfig, BertForSequenceClassification
from paddle_tpu.utils import unique_name


def _tiny_cfg():
    return BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=2, intermediate_size=64,
                      max_position_embeddings=64, type_vocab_size=2,
                      hidden_dropout=0.0, attention_dropout=0.0)


def test_predictor_bert_dynamic_batch(tmp_path):
    with unique_name.guard():
        paddle.seed(0)
        model = BertForSequenceClassification(_tiny_cfg(), num_classes=3)
    model.eval()

    path = str(tmp_path / "bert_cls")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([None, 16], "int64")])

    cfg = Config(path)
    cfg.disable_gpu()
    predictor = create_predictor(cfg)
    names = predictor.get_input_names()
    assert len(names) == 1

    rng = np.random.RandomState(0)
    for batch in (2, 5):  # two DIFFERENT batch sizes through one artifact
        ids = rng.randint(0, 128, (batch, 16)).astype(np.int64)
        h = predictor.get_input_handle(names[0])
        h.copy_from_cpu(ids)
        assert predictor.run()
        out_names = predictor.get_output_names()
        got = predictor.get_output_handle(out_names[0]).copy_to_cpu()
        assert got.shape == (batch, 3)
        want = np.asarray(model(Tensor(ids))._value)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_predictor_requires_model_path():
    with pytest.raises(ValueError, match="model path"):
        create_predictor(Config())


def test_output_accessors_before_run_raise_clearly(tmp_path):
    """ISSUE 6 satellite: get_output_names()/get_output_handle() before
    run() used to return []/raise a bare IndexError — they must explain
    that run() has not been called."""
    with unique_name.guard():
        paddle.seed(2)
        model = BertForSequenceClassification(_tiny_cfg(), num_classes=2)
    model.eval()
    path = str(tmp_path / "bert_prerun")
    paddle.jit.save(model, path, input_spec=[InputSpec([None, 16], "int64")])
    pred = create_predictor(Config(path))
    with pytest.raises(RuntimeError, match="run\\(\\) has not been called"):
        pred.get_output_names()
    with pytest.raises(RuntimeError, match="run\\(\\) has not been called"):
        pred.get_output_handle("output_0")
    # after run(): names work, and an out-of-range handle names the range
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.zeros((2, 16), np.int64))
    assert pred.run()
    assert pred.get_output_names() == ["output_0"]
    with pytest.raises(IndexError, match="1 output"):
        pred.get_output_handle("output_7")


def test_handle_reshape_preallocates():
    """ISSUE 6 satellite: reshape() on an unset handle preallocates zeros
    of the requested shape (reference ZeroCopyTensor.Reshape) instead of
    silently dropping the declared shape."""
    from paddle_tpu.inference import _Handle

    h = _Handle()
    h.reshape([2, 3])
    assert h.shape() == [2, 3]
    out = h.copy_to_cpu()
    assert out.shape == (2, 3) and not out.any()
    # set handles keep plain-reshape semantics
    h.copy_from_cpu(np.arange(6, dtype=np.float32))
    h.reshape([3, 2])
    np.testing.assert_array_equal(h.copy_to_cpu().ravel(), np.arange(6))


def test_config_knobs_act_or_warn_once(tmp_path):
    """Round-5 VERDICT item 8: no silently-ignored public knob — inert
    knobs warn ONCE with the reason; disable_gpu genuinely places the
    run on the host CPU backend."""
    import warnings

    import paddle_tpu.inference as inf

    inf._WARNED.clear()
    cfg = Config()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        cfg.enable_use_gpu(100, 0)
        cfg.enable_use_gpu(100, 0)          # second call: no second warning
        cfg.switch_ir_optim(True)           # default direction: no warning
        cfg.switch_ir_optim(False)
        cfg.enable_memory_optim()
    msgs = [str(x.message) for x in w]
    assert sum("enable_use_gpu" in m for m in msgs) == 1
    assert sum("switch_ir_optim" in m for m in msgs) == 1
    assert sum("memory_optim" in m for m in msgs) == 1

    # disable_gpu ACTS: outputs come from the cpu backend
    import jax

    with unique_name.guard():
        paddle.seed(1)
        model = BertForSequenceClassification(_tiny_cfg(), num_classes=2)
    model.eval()
    path = str(tmp_path / "bert_cpu")
    paddle.jit.save(model, path, input_spec=[InputSpec([None, 16], "int64")])
    cfg2 = Config(path)
    cfg2.disable_gpu()
    pred = create_predictor(cfg2)
    assert pred._device is None or pred._device.platform == "cpu"
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.zeros((2, 16), np.int64))
    assert pred.run()
    out = pred.get_output_handle("output_0").copy_to_cpu()
    assert out.shape == (2, 2)
