"""Flat API batch: paddle.device, distributed.spawn, sparse_attention,
layout autotune, auto_checkpoint, cost_model, incubate.multiprocessing.

Reference parity targets noted per test.
"""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


# -- paddle.device (reference python/paddle/device/) ------------------------


def test_device_namespace():
    assert "cpu" in paddle.device.get_all_device_type()
    devs = paddle.device.get_available_device()
    assert devs and all(":" in d for d in devs)
    assert paddle.device.device_count() >= 1
    paddle.device.synchronize()  # must not raise


def test_device_stream_event():
    ev = paddle.device.cuda.Event()
    assert ev.query()  # nothing recorded yet
    s = paddle.device.cuda.current_stream()
    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    y = x @ x
    ev.record()
    ev.synchronize()
    assert ev.query()
    with paddle.device.cuda.stream_guard(s):
        z = y + 1
    s.synchronize()
    assert float(z.numpy()[0, 0]) == 65.0
    assert paddle.device.cuda.memory_allocated() >= 0
    props = paddle.device.cuda.get_device_properties()
    assert "platform" in props


# -- sparse_attention (reference nn/functional/sparse_attention.py) ---------


def _dense_ref(q, k, v, mask):
    d = q.shape[-1]
    s = (q @ np.swapaxes(k, -1, -2)) / np.sqrt(d)
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = np.where(mask, p, 0)
    p = p / np.maximum(p.sum(-1, keepdims=True), 1e-20)
    return p @ v


def test_sparse_attention_matches_masked_dense():
    rng = np.random.RandomState(0)
    b, h, s, d = 1, 2, 8, 4
    q, k, v = (rng.randn(b, h, s, d).astype(np.float32) for _ in range(3))
    # band pattern: each row attends to itself and its left neighbor
    offset = np.zeros((b, h, s + 1), np.int32)
    cols_list = [[max(i - 1, 0), i] if i > 0 else [0] for i in range(s)]
    flat = [c for row in cols_list for c in row]
    counts = [len(row) for row in cols_list]
    offset[..., 1:] = np.cumsum(counts)
    cols = np.tile(np.asarray(flat, np.int32), (b, h, 1))

    out = F.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offset), paddle.to_tensor(cols))

    mask = np.zeros((b, h, s, s), bool)
    for i, row in enumerate(cols_list):
        for j in row:
            mask[..., i, j] = True
    ref = _dense_ref(q, k, v, mask)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_sparse_attention_gradient_respects_pattern():
    rng = np.random.RandomState(1)
    b, h, s, d = 1, 1, 4, 2
    q = paddle.to_tensor(rng.randn(b, h, s, d).astype(np.float32),
                         stop_gradient=False)
    k = paddle.to_tensor(rng.randn(b, h, s, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(b, h, s, d).astype(np.float32))
    # diagonal-only pattern
    offset = paddle.to_tensor(np.arange(s + 1, dtype=np.int32)[None, None])
    cols = paddle.to_tensor(np.arange(s, dtype=np.int32)[None, None])
    out = F.sparse_attention(q, k, v, offset, cols)
    out.sum().backward()
    assert q.grad is not None
    # diagonal softmax over one element == identity: output is v exactly
    np.testing.assert_allclose(out.numpy(), v.numpy(), rtol=1e-5, atol=1e-6)


# -- layout autotune (reference imperative/layout_autotune.cc) --------------


def test_layout_autotune_conv_parity():
    import paddle_tpu.incubate.autotune as autotune
    from paddle_tpu.framework.layout_autotune import layout_autotune_enabled

    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
    w = paddle.to_tensor(rng.randn(4, 3, 3, 3).astype(np.float32))
    base = F.conv2d(x, w, stride=1, padding=1).numpy()
    try:
        autotune.set_config({"layout": {"enable": True}})
        assert layout_autotune_enabled()
        tuned = F.conv2d(x, w, stride=1, padding=1).numpy()
    finally:
        autotune.set_config({"layout": {"enable": False}})
    assert not layout_autotune_enabled()
    np.testing.assert_allclose(tuned, base, rtol=1e-4, atol=1e-4)


# -- auto checkpoint (reference fluid/incubate/checkpoint/auto_checkpoint.py)


def test_auto_checkpoint_resumes(tmp_path):
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp

    save_dir = str(tmp_path / "acp")

    def run(n_epochs, crash_after=None):
        acp.reset()
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        acp.register(model=net, optimizer=opt)
        seen = []
        for epoch in acp.train_epoch_range(n_epochs, save_dir=save_dir):
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            (net(x) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()
            seen.append(epoch)
            if crash_after is not None and epoch == crash_after:
                break  # simulated failure: no further saves
        return seen, net

    first, net1 = run(6, crash_after=2)
    assert first == [0, 1, 2]
    # the break happens inside epoch 2 BEFORE its end-of-epoch snapshot, so a
    # correct resume redoes epoch 2 from the epoch-1 checkpoint (the
    # reference's semantics: an epoch counts only once its snapshot lands)
    second, net2 = run(6)
    assert second == [2, 3, 4, 5]
    # restored params at epoch 3 came from the epoch-2 snapshot
    assert os.path.exists(os.path.join(save_dir, "acp_meta.json"))


# -- cost model (reference python/paddle/cost_model/) -----------------------


def test_cost_model_static_and_measured():
    import jax.numpy as jnp

    cm = paddle.cost_model.CostModel()

    def f(a, b):
        return jnp.dot(a, b)

    a = np.ones((128, 128), np.float32)
    static = cm.static_cost_data(f, (a, a))
    assert static["flops"] > 0
    measured = cm.profile_measure(f, (a, a), repeat=3, warmup=1)
    assert measured["mean_seconds"] > 0
    assert measured["achieved_flops_per_sec"] > 0


# -- incubate.multiprocessing reductions ------------------------------------


def test_tensor_pickles_across_process_boundary():
    import paddle_tpu.incubate.multiprocessing  # noqa: F401 — installs reducers

    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    t.stop_gradient = False
    blob = pickle.dumps(t)
    t2 = pickle.loads(blob)
    np.testing.assert_array_equal(t2.numpy(), t.numpy())
    assert t2.stop_gradient is False


# -- distributed.spawn (reference python/paddle/distributed/spawn.py) -------


def _spawn_target(result_dir):
    # runs in a fresh process: the env surface must be present
    rank = os.environ["PADDLE_TRAINER_ID"]
    n = os.environ["PADDLE_TRAINERS_NUM"]
    with open(os.path.join(result_dir, f"rank{rank}"), "w") as f:
        f.write(f"{rank}/{n}")


def test_spawn_runs_workers(tmp_path):
    paddle.distributed.spawn(_spawn_target, args=(str(tmp_path),), nprocs=2)
    got = sorted(os.listdir(tmp_path))
    assert got == ["rank0", "rank1"]
    assert (tmp_path / "rank0").read_text() == "0/2"


def test_spawn_propagates_failure(tmp_path):
    def boom(_):
        raise RuntimeError("worker exploded")

    # note: nested functions aren't picklable under spawn; module-level
    # failure path is exercised via a lambda-free helper
    with pytest.raises(RuntimeError):
        paddle.distributed.spawn(_spawn_fail, args=(str(tmp_path),), nprocs=2)


def _spawn_fail(_):
    raise RuntimeError("worker exploded")
