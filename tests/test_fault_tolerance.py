"""Fault tolerance (paddle_tpu.fault): atomic checkpoint/resume, preemption
handling, retry with backoff, worker restart, deterministic fault injection.

The headline contracts (ISSUE 4 acceptance):

* a run killed by an injected SIGTERM mid-epoch and restarted with
  ``Model.fit(resume=...)`` reproduces the uninterrupted run's loss
  trajectory BITWISE (SGD with shuffle on, and Adam with fp32 master
  weights);
* an injected torn write on the newest checkpoint is caught by the
  manifest CRC32 and ``CheckpointManager.load`` falls back to the previous
  verified-good step.
"""
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.io import CheckpointCorruptError, load as pload, \
    save as psave
from paddle_tpu.fault import (CheckpointManager, PreemptionGuard,
                              TrainingPreempted, TransientError, inject,
                              retry)
from paddle_tpu.hapi.callbacks import Callback, ModelCheckpoint
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io.device_loader import DeviceLoader
from paddle_tpu.io.worker import WorkerFailure
from paddle_tpu.nn import CrossEntropyLoss
from paddle_tpu.utils import unique_name


@pytest.fixture(autouse=True)
def _clean_injection(monkeypatch):
    # fork-start for the worker tests (forkserver costs ~10s/pool) and a
    # guaranteed-disarmed injection registry around every test
    monkeypatch.setenv("PADDLE_TPU_WORKER_START", "fork")
    inject.disarm_all()
    yield
    inject.disarm_all()


# ---------------------------------------------------------------------------
# framework.io atomicity + corruption detection
# ---------------------------------------------------------------------------

def test_save_is_atomic_and_roundtrips(tmp_path):
    path = str(tmp_path / "sub" / "state.pdparams")
    psave({"w": paddle.to_tensor(np.arange(6, dtype=np.float32))}, path)
    # no temp litter next to the file
    assert os.listdir(os.path.dirname(path)) == ["state.pdparams"]
    out = pload(path, return_numpy=True)
    np.testing.assert_array_equal(out["w"], np.arange(6, dtype=np.float32))


def test_load_truncated_raises_corrupt_error(tmp_path):
    path = str(tmp_path / "t.pdparams")
    psave({"w": np.arange(1024, dtype=np.float32)}, path)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorruptError, match="t.pdparams"):
        pload(path)


def test_load_garbage_raises_corrupt_error(tmp_path):
    path = str(tmp_path / "g.pdparams")
    with open(path, "wb") as f:
        f.write(b"\x80\x04this is not a pickle at all")
    with pytest.raises(CheckpointCorruptError) as ei:
        pload(path)
    assert ei.value.path == path
    assert ei.value.__cause__ is not None


# ---------------------------------------------------------------------------
# CheckpointManager: versioning, pruning, torn-write fallback
# ---------------------------------------------------------------------------

def test_manager_roundtrip_latest_pointer_and_pruning(tmp_path):
    m = CheckpointManager(str(tmp_path / "ck"), keep_last_n=3)
    for s in range(1, 6):
        m.save(s, {"model": {"w": np.full(4, float(s), np.float32)},
                   "cursor": {"epoch": s}})
    assert m.steps() == [3, 4, 5]          # keep_last_n pruned 1, 2
    assert m.latest_step() == 5
    step, payloads = m.load()
    assert step == 5
    np.testing.assert_array_equal(
        np.asarray(payloads["model"]["w"]._value), np.full(4, 5.0))
    assert payloads["cursor"]["epoch"] == 5
    assert m.verify(4) == []


def test_manager_torn_write_detected_and_falls_back(tmp_path):
    from paddle_tpu.profiler import telemetry

    m = CheckpointManager(str(tmp_path / "ck"))
    m.save(7, {"model": {"w": np.zeros(64, np.float32)}})
    inject.arm("torn", "ckpt.write", at=1)
    m.save(8, {"model": {"w": np.ones(64, np.float32)}})
    assert m.verify(8), "torn write must fail verification"
    telemetry.reset()
    telemetry.enable()
    try:
        with pytest.warns(UserWarning, match="recovered from corrupt"):
            step, payloads = m.load()
        assert step == 7
        assert telemetry.get_telemetry().counters()[
            "fault.ckpt_recoveries"] == 1
    finally:
        telemetry.disable()


def test_manager_all_corrupt_raises(tmp_path):
    m = CheckpointManager(str(tmp_path / "ck"))
    m.save(1, {"model": {"w": np.ones(16, np.float32)}})
    with open(os.path.join(m.step_dir(1), "model.pdparams"), "r+b") as f:
        f.truncate(4)
    with pytest.warns(UserWarning):
        with pytest.raises(CheckpointCorruptError, match="no verifiable"):
            m.load()


def test_manager_empty_dir_returns_none(tmp_path):
    assert CheckpointManager(str(tmp_path / "nothing")).load() is None


# ---------------------------------------------------------------------------
# retry + injection determinism
# ---------------------------------------------------------------------------

def test_retry_backoff_grows_then_succeeds():
    sleeps, state = [], {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return 42

    assert retry(flaky, tries=4, base_delay=0.1, jitter=0.0,
                 sleep=sleeps.append) == 42
    assert sleeps == [0.1, 0.2]  # exponential, no jitter


def test_retry_gives_up_and_nonretryable_propagates():
    sleeps = []

    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry(always, tries=3, base_delay=0.01, sleep=sleeps.append)
    assert len(sleeps) == 2

    def bug():
        raise ValueError("user bug")

    with pytest.raises(ValueError):
        retry(bug, tries=3, base_delay=0.01, sleep=sleeps.append)
    assert len(sleeps) == 2  # no extra sleeps: not retried


def test_injection_fires_deterministically():
    for _ in range(2):  # same arm config -> same fire point, every time
        inject.disarm_all()
        inject.arm("error", "stage", at=3)
        fired = []
        for i in range(6):
            try:
                inject.check("stage")
            except TransientError:
                fired.append(i)
        assert fired == [2]  # 3rd hit, exactly once


def test_injection_env_parsing(monkeypatch):
    monkeypatch.setenv(inject.ENV_VAR, "error:stage:2,torn:ckpt.write:1:/x/y")
    inject.reload_env()
    entries = inject.armed()
    assert [(e["kind"], e["point"], e["at"]) for e in entries] == \
        [("error", "stage", 2), ("torn", "ckpt.write", 1)]
    assert entries[1]["once_file"] == "/x/y"
    assert inject.check("stage") is None
    with pytest.raises(TransientError):
        inject.check("stage")


def test_preemption_guard_latches_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert not g.preempted
        signal.raise_signal(signal.SIGTERM)
        assert g.preempted
    assert signal.getsignal(signal.SIGTERM) is prev


# ---------------------------------------------------------------------------
# DeviceLoader transient-stage retry + elastic heartbeat retry
# ---------------------------------------------------------------------------

def test_device_loader_retries_transient_stage_error():
    inject.arm("error", "stage", at=2)
    batches = [(np.full((2, 2), i, np.float32),) for i in range(4)]
    out = list(DeviceLoader(batches))
    assert len(out) == 4  # the injected failure was absorbed by retry
    np.testing.assert_array_equal(np.asarray(out[1][0]), np.ones((2, 2)))


def test_device_loader_nontransient_stage_error_propagates():
    def batches():
        yield (np.ones(2, np.float32),)
        raise ValueError("source bug")

    with pytest.raises(ValueError, match="source bug"):
        list(DeviceLoader(batches()))


def test_elastic_heartbeat_retries_transient_fs_errors(tmp_path, monkeypatch):
    from paddle_tpu.distributed.elastic import ElasticManager

    em = ElasticManager(elastic_dir=str(tmp_path), rank=0, world_size=1)
    real_replace = os.replace
    state = {"fails": 2}

    def flaky(src, dst):
        if dst.endswith("rank0.json") and state["fails"] > 0:
            state["fails"] -= 1
            raise OSError("EIO: flaky NFS")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", flaky)
    em.heartbeat()  # would raise without retry
    assert state["fails"] == 0
    assert em.world() == [0]


# ---------------------------------------------------------------------------
# worker death: restart + re-dispatch
# ---------------------------------------------------------------------------

class ArrayDS(Dataset):
    def __init__(self, n=64):
        self.x = np.arange(n, dtype=np.float32)

    def __getitem__(self, i):
        return (self.x[i],)

    def __len__(self):
        return len(self.x)


class BoomDS(ArrayDS):
    def __getitem__(self, i):
        if i == 13:
            raise ValueError("boom at 13")
        return super().__getitem__(i)


def _collect_samples(loader):
    return sorted(float(v) for b in loader for v in np.asarray(b[0]).ravel())


def test_killed_worker_restarts_and_epoch_completes(tmp_path, monkeypatch):
    once = str(tmp_path / "kill_once")
    monkeypatch.setenv(inject.ENV_VAR, f"kill:worker.fetch:2:{once}")
    inject.reload_env()  # forked workers inherit the un-loaded registry
    loader = DataLoader(ArrayDS(), batch_size=4, num_workers=2,
                        use_process=True, worker_restart_limit=2)
    got = _collect_samples(loader)
    assert got == [float(i) for i in range(64)]  # every sample exactly once
    assert os.path.exists(once)  # the kill really fired


def test_killed_worker_fails_fast_without_restart_budget(tmp_path,
                                                         monkeypatch):
    once = str(tmp_path / "kill_once0")
    monkeypatch.setenv(inject.ENV_VAR, f"kill:worker.fetch:2:{once}")
    inject.reload_env()
    loader = DataLoader(ArrayDS(), batch_size=4, num_workers=2,
                        use_process=True, worker_restart_limit=0)
    with pytest.raises(WorkerFailure, match="exited unexpectedly"):
        list(loader)


def test_worker_exception_propagates_immediately_despite_restart_budget():
    loader = DataLoader(BoomDS(), batch_size=4, num_workers=2,
                        use_process=True, worker_restart_limit=5)
    with pytest.raises(WorkerFailure, match="boom at 13"):
        list(loader)


# ---------------------------------------------------------------------------
# kill-and-resume: bitwise loss parity (the acceptance criterion)
# ---------------------------------------------------------------------------

@pytest.fixture
def _no_persistent_compile_cache():
    """Bitwise parity needs the reference and the resumed run to execute the
    SAME binary. Executables round-tripped through the persistent XLA:CPU
    compile cache are NOT bit-identical to fresh in-process compiles on this
    stack (measured: warm-cache runs diverge in the last fp16 ulp a few
    steps after any compile boundary; cold-cache and cache-off runs agree
    exactly) — so the parity tests compile everything in-process."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


class ToyClassify(Dataset):
    def __init__(self, n=48, seed=0, dtype=np.float32):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(dtype)
        w = rng.randn(8).astype(np.float32)
        self.y = (self.x.astype(np.float32) @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class LossRecorder(Callback):
    def __init__(self):
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(logs["loss"])


def _make_model(optimizer, dtype=None):
    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(16, 2))
    if dtype:
        net.to(dtype=dtype)
    model = paddle.Model(net)
    opt = optimizer(net)
    model.prepare(opt, CrossEntropyLoss())
    return model


def _parity_run(tmp_path, optimizer, *, shuffle, dtype=None, kill_at=8):
    """Uninterrupted run vs (SIGTERM-killed + resumed) run — losses must be
    bitwise identical, step for step."""
    data = lambda: ToyClassify(dtype=dtype or np.float32)  # noqa: E731
    fit_kw = dict(batch_size=8, epochs=2, verbose=0, shuffle=shuffle,
                  log_freq=1)

    np.random.seed(1234)
    ref = LossRecorder()
    _make_model(optimizer, dtype).fit(data(), callbacks=[ref], **fit_kw)

    ck = str(tmp_path / "resume_ck")
    np.random.seed(1234)
    part1 = LossRecorder()
    inject.arm("sigterm", "train.step", at=kill_at)
    with pytest.raises(TrainingPreempted):
        _make_model(optimizer, dtype).fit(data(), callbacks=[part1],
                                          resume=ck, **fit_kw)
    inject.disarm_all()
    assert len(part1.losses) == kill_at
    # fresh process stand-in: a brand-new model/optimizer, state from disk
    part2 = LossRecorder()
    _make_model(optimizer, dtype).fit(data(), callbacks=[part2],
                                      resume=ck, **fit_kw)
    resumed = part1.losses + part2.losses
    assert len(resumed) == len(ref.losses)
    assert resumed == ref.losses  # BITWISE: float equality, no tolerance


def test_kill_and_resume_loss_parity_sgd_shuffled(tmp_path, _no_persistent_compile_cache):
    _parity_run(
        tmp_path,
        lambda net: paddle.optimizer.SGD(learning_rate=0.1,
                                         parameters=net.parameters()),
        shuffle=True)


def test_kill_and_resume_loss_parity_adam_master_weights(tmp_path, _no_persistent_compile_cache):
    _parity_run(
        tmp_path,
        lambda net: paddle.optimizer.Adam(learning_rate=0.05,
                                          parameters=net.parameters(),
                                          multi_precision=True),
        shuffle=False, dtype="float16", kill_at=7)


def test_fit_resume_writes_epoch_and_periodic_checkpoints(tmp_path):
    ck = str(tmp_path / "ck")
    model = _make_model(lambda net: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()))
    model.fit(ToyClassify(), batch_size=8, epochs=2, verbose=0,
              shuffle=False, resume=ck, ckpt_freq=2, keep_last_n=3)
    mgr = CheckpointManager(ck)
    steps = mgr.steps()
    assert steps, "resume-enabled fit must leave checkpoints behind"
    assert len(steps) <= 3  # keep_last_n enforced
    # cursor of the newest checkpoint points past the last epoch
    _, payloads = mgr.load()
    assert payloads["cursor"]["epoch"] == 2
    # resuming a completed run is a no-op (no steps to execute)
    again = LossRecorder()
    model2 = _make_model(lambda net: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()))
    model2.fit(ToyClassify(), batch_size=8, epochs=2, verbose=0,
               shuffle=False, resume=ck, callbacks=[again])
    assert again.losses == []


# ---------------------------------------------------------------------------
# Engine.fit(resume=...)
# ---------------------------------------------------------------------------

class ToyRegress(Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(1)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = rng.randn(n, 4).astype(np.float32)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _make_engine():
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.process_mesh import ProcessMesh

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    loss = lambda out, y: ((out - y) ** 2).mean()  # noqa: E731
    eng = Engine(model=net, loss=loss, optimizer=opt,
                 process_mesh=ProcessMesh(np.array([0]), dim_names=["dp"]))
    return eng, net


def _param(net, name="weight"):
    v = net.state_dict()[name]
    return np.asarray(v._value if hasattr(v, "_value") else v)


def test_engine_kill_and_resume_params_bitwise(tmp_path, _no_persistent_compile_cache):
    np.random.seed(7)
    eng, net_a = _make_engine()
    eng.fit(ToyRegress(), batch_size=8, epochs=2, prefetch=2, log_freq=1)
    ref = _param(net_a)

    ck = str(tmp_path / "eng_ck")
    np.random.seed(7)
    eng, _ = _make_engine()
    inject.arm("sigterm", "train.step", at=5)
    with pytest.raises(TrainingPreempted):
        eng.fit(ToyRegress(), batch_size=8, epochs=2, prefetch=2,
                log_freq=1, resume=ck)
    inject.disarm_all()
    eng, net_b = _make_engine()
    eng.fit(ToyRegress(), batch_size=8, epochs=2, prefetch=2, log_freq=1,
            resume=ck)
    assert np.array_equal(ref, _param(net_b))


# ---------------------------------------------------------------------------
# ModelCheckpoint: final aliasing + keep_last_n
# ---------------------------------------------------------------------------

def _fit_with_ckpt(tmp_path, epochs, save_freq, keep_last_n=None):
    model = _make_model(lambda net: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()))
    d = str(tmp_path / "mc")
    mc = ModelCheckpoint(save_freq, d, keep_last_n=keep_last_n)
    model.fit(ToyClassify(32), batch_size=16, epochs=epochs, verbose=0,
              callbacks=[mc])
    return d


def test_model_checkpoint_final_aliases_last_saved_epoch(tmp_path):
    d = _fit_with_ckpt(tmp_path, epochs=2, save_freq=1)
    final = os.path.join(d, "final.pdparams")
    assert os.path.exists(final)
    # the last epoch WAS saved by save_freq: final must alias it, not be a
    # second serialization of the same state
    assert os.path.samefile(final, os.path.join(d, "1.pdparams"))


def test_model_checkpoint_final_written_when_not_covered(tmp_path):
    d = _fit_with_ckpt(tmp_path, epochs=2, save_freq=2)  # saves epoch 0 only
    final = os.path.join(d, "final.pdparams")
    assert os.path.exists(final)
    assert not os.path.samefile(final, os.path.join(d, "0.pdparams"))


def test_model_checkpoint_keep_last_n_prunes(tmp_path):
    d = _fit_with_ckpt(tmp_path, epochs=4, save_freq=1, keep_last_n=2)
    present = sorted(f for f in os.listdir(d) if f.endswith(".pdparams"))
    assert present == ["2.pdparams", "3.pdparams", "final.pdparams"]


# ---------------------------------------------------------------------------
# incubate auto_checkpoint: marker lands last, resume works
# ---------------------------------------------------------------------------

def test_auto_checkpoint_marker_names_existing_state(tmp_path):
    import json

    from paddle_tpu.incubate.checkpoint import auto_checkpoint as acp

    acp.reset()
    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    acp.register(model=net, optimizer=opt)
    d = str(tmp_path / "acp")
    ran = [e for e in acp.train_epoch_range(3, d)]
    assert ran == [0, 1, 2]
    with open(os.path.join(d, "acp_meta.json")) as f:
        marker = json.load(f)
    assert marker["epoch"] == 2
    for fname in marker["state_files"]:
        assert os.path.exists(os.path.join(d, fname)), fname
    # a rerun resumes past the completed range
    assert [e for e in acp.train_epoch_range(3, d)] == []
    acp.reset()
