"""Fusion simulation (ISSUE 18): the static fusion pass over the jaxpr
(:mod:`paddle_tpu.analysis.fusion`), its integration into the mem-lint
liveness timeline and shard-lint comm_fraction, the hbm-unfused-chain
registry rule, and the ratcheted measured-zoo crosscheck.

Acceptance (ISSUE 18):
  * producer-consumer chains of elementwise/shape ops cluster into one
    fusion group; dot/conv/collectives/unknown prims are barriers;
    reductions absorb producers but root their group;
  * expensive elementwise producers are never duplicated, cheap ones
    only up to the duplication limit (conservative default: 1);
  * ``MEM_RTOL`` is ratcheted to 0.10 (from 0.15) and the full zoo's
    measured crosscheck certifies it: every measurable config agrees
    within ``rtol*m + MEM_ATOL`` and never under-predicts beyond it —
    including the dp-plain/dp-zero pair flipped to measurable.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import fusion, mem_lint

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _plan(fn, *args, **kwargs):
    return fusion.plan_jaxpr(jax.make_jaxpr(fn)(*args), **kwargs)


def _eqn_out(closed, i):
    return closed.jaxpr.eqns[i].outvars[0]


# ---------------------------------------------------------------------------
# FusionPlan: chains, barriers, duplication limits
# ---------------------------------------------------------------------------

def test_elementwise_chain_one_group():
    """mul → add → neg clusters into a single fusion group; only the
    chain's program output materializes."""
    def f(x):
        return -((x * 2.0) + 1.0)

    closed = jax.make_jaxpr(f)(jnp.ones((8, 8)))
    plan = fusion.plan_jaxpr(closed)
    assert plan.n_groups == 1
    assert plan.is_fused(_eqn_out(closed, 0))      # x*2
    assert plan.reason(_eqn_out(closed, 0)) == ""
    out = closed.jaxpr.outvars[0]
    assert not plan.is_fused(out)
    assert plan.reason(out) == "output"
    d = plan.as_dict()
    assert d["n_eqns"] == len(closed.jaxpr.eqns)
    assert d["n_fused"] == plan.n_fused >= 2


def test_dot_is_barrier():
    """A dot_general consumer neither fuses nor absorbs: the elementwise
    producer feeding it materializes with a barrier reason."""
    def f(x, w):
        return (x + 1.0) @ w

    closed = jax.make_jaxpr(f)(jnp.ones((8, 8)), jnp.ones((8, 8)))
    plan = fusion.plan_jaxpr(closed)
    h = _eqn_out(closed, 0)
    assert not plan.is_fused(h)
    assert plan.reason(h) == "barrier:dot_general"
    assert plan.n_groups == len(closed.jaxpr.eqns)  # nothing fused


def test_unknown_prim_is_barrier_by_default():
    """Default-deny: a primitive in none of the fusion sets (sort) blocks
    its fusible producer."""
    def f(x):
        return jax.lax.sort(x * 2.0)

    closed = jax.make_jaxpr(f)(jnp.ones((16,)))
    plan = fusion.plan_jaxpr(closed)
    assert plan.reason(_eqn_out(closed, 0)) == "barrier:sort"


def test_reduce_absorbs_but_roots_group():
    """XLA input fusion: reduce_sum absorbs its fusible producer (the
    square's buffer is elided) but the reduce output itself is a group
    root, never classified fused."""
    def f(x):
        return jnp.sum(x * x)

    closed = jax.make_jaxpr(f)(jnp.ones((64, 64)))
    plan = fusion.plan_jaxpr(closed)
    sq = _eqn_out(closed, 0)
    assert plan.is_fused(sq)
    assert plan.n_groups < len(closed.jaxpr.eqns)
    for v in closed.jaxpr.outvars:
        assert not plan.is_fused(v)


@needs_8_devices
def test_collective_is_barrier():
    """Inside a shard_map body a psum consumer materializes its fusible
    operand — collectives move bytes over the interconnect, nothing
    fuses through them."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    def body(x):
        return jax.lax.psum(x * 2.0, "dp")

    g = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P())
    closed = jax.make_jaxpr(g)(jnp.ones((8, 4)))
    (sm_eqn,) = [e for e in closed.jaxpr.eqns
                 if e.primitive.name == "shard_map"]
    inner = getattr(sm_eqn.params["jaxpr"], "jaxpr",
                    sm_eqn.params["jaxpr"])
    plan = fusion.plan_jaxpr(inner)
    (mul_eqn,) = [e for e in inner.eqns if e.primitive.name == "mul"]
    mul_out = mul_eqn.outvars[0]
    assert not plan.is_fused(mul_out)
    # the collective lowers to psum2 inside shard_map — either spelling
    # is the same barrier
    assert plan.reason(mul_out).startswith("barrier:psum")


def test_expensive_producer_never_duplicated():
    """XLA IsExpensive: exp fuses into exactly one consumer; with two it
    materializes no matter how high the duplication limit is."""
    def one(x):
        return jnp.exp(x) * 2.0

    closed = jax.make_jaxpr(one)(jnp.ones((8,)))
    assert fusion.plan_jaxpr(closed).is_fused(_eqn_out(closed, 0))

    def two(x):
        e = jnp.exp(x)
        return e * 2.0 + e * 3.0

    closed = jax.make_jaxpr(two)(jnp.ones((8,)))
    plan = fusion.plan_jaxpr(closed, max_fanout=16)
    e = _eqn_out(closed, 0)
    assert not plan.is_fused(e)
    assert plan.reason(e) == "expensive-fanout:2"


def test_cheap_fanout_duplication_limit():
    """A cheap producer with two consumer groups materializes at the
    conservative default limit (1 — the upper-bound contract refuses to
    guess duplication) and fuses when the limit admits it."""
    assert fusion.MAX_FANOUT == 1  # the certified conservative default

    def f(x):
        y = x + 1.0
        return y * 2.0, y * 3.0

    closed = jax.make_jaxpr(f)(jnp.ones((8,)))
    y = _eqn_out(closed, 0)
    strict = fusion.plan_jaxpr(closed)
    assert not strict.is_fused(y)
    assert strict.reason(y) == "fanout:2"
    loose = fusion.plan_jaxpr(closed, max_fanout=4)
    assert loose.is_fused(y)
    assert loose.n_groups < strict.n_groups


def test_output_seam():
    """A program output consumed mid-chain: the forced HBM write (the
    donation-alias target when state is donated) splits the chain."""
    def f(x):
        y = x * 2.0
        return y, y + 1.0

    closed = jax.make_jaxpr(f)(jnp.ones((8,)))
    plan = fusion.plan_jaxpr(closed)
    y = _eqn_out(closed, 0)
    assert not plan.is_fused(y)
    assert plan.reason(y) == "output-seam"


def test_dropvar_dead_eqn_tolerated():
    """An unused value traces to a DropVar outvar — the plan must skip
    it (no verdict, no crash) and keep the dead eqn in its own group."""
    def f(x):
        _ = x + 1.0  # no consumer, not an output → DropVar
        return x * 2.0

    closed = jax.make_jaxpr(f)(jnp.ones((8,)))
    plan = fusion.plan_jaxpr(closed)
    assert plan.n_groups == 2 and plan.n_fused == 0
    assert plan.reason(closed.jaxpr.outvars[0]) == "output"


# ---------------------------------------------------------------------------
# mem-lint integration: elision, soundness, remat interaction
# ---------------------------------------------------------------------------

def _chain_jaxpr():
    w = jnp.ones((64, 64), jnp.float32)

    def step(x):
        h = jnp.tanh(x @ w)
        g = h * 2.0 + 1.0
        return jnp.sum(g * g)

    return jax.make_jaxpr(step)(jnp.ones((64, 64), jnp.float32))


def test_timeline_elides_fused_temporaries():
    closed = _chain_jaxpr()
    tl_on = mem_lint.timeline_from_jaxpr(closed)
    tl_off = mem_lint.timeline_from_jaxpr(closed, fusion=False)
    assert tl_on.fusion is True and tl_off.fusion is False
    assert tl_on.fused_bytes > 0 and tl_off.fused_bytes == 0
    assert tl_on.peak_bytes <= tl_off.peak_bytes
    fused = [b for b in tl_on.buffers if b.fused]
    assert fused and all(b.eff_bytes == 0 for b in fused)
    assert "fusion elides" in tl_on.table()
    d = tl_on.as_dict()
    assert d["fusion"] is True and d["fused_bytes"] == tl_on.fused_bytes


def test_fused_chain_keeps_sources_live():
    """Soundness: eliding a fused temporary must NOT shorten the life of
    the materialized value its chain reads — the consumer recomputes the
    chain from that source, so the source stays live to the consumer."""
    w = jnp.ones((32, 32), jnp.float32)

    def step(x):
        a = x @ w          # materialized (dot)
        b = a * 2.0        # fused
        c = b + 1.0        # fused
        return c @ w       # reads c ⇒ reads a inside the fused loop

    closed = jax.make_jaxpr(step)(jnp.ones((32, 32), jnp.float32))
    tl_on = mem_lint.timeline_from_jaxpr(closed)
    tl_off = mem_lint.timeline_from_jaxpr(closed, fusion=False)
    a_on = [b for b in tl_on.buffers if b.kind == "temp" and b.birth == 0]
    a_off = [b for b in tl_off.buffers
             if b.kind == "temp" and b.birth == 0]
    assert a_on and a_off
    # fusion-blind: a dies at its direct consumer (the mul). Fusion-aware:
    # a must survive to the second dot that absorbs the b→c chain.
    assert a_on[0].death > a_off[0].death


def test_delta_if_remat_ignores_fused_buffers():
    """The remat planner must not buy back phantom bytes: a fused-away
    buffer's predicted remat win is exactly zero."""
    tl = mem_lint.timeline_from_jaxpr(_chain_jaxpr())
    fused = [b for b in tl.buffers if b.fused]
    assert fused
    for b in fused:
        assert tl.delta_if_remat(b.key) == 0.0


def test_hbm_unfused_chain_rule():
    """The rule flags a large fusible temporary forced through HBM by an
    output seam, stays quiet when everything fuses or when fusion is
    off, and respects the byte floor."""
    def seam(x):
        y = x * 2.0
        return y, y + 1.0

    x = jnp.ones((1024, 512), jnp.float32)  # y is 2 MiB, over the floor
    rep = analysis.lint_step(seam, x)
    hits = rep.by_rule("hbm-unfused-chain")
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["reason"] == "output-seam"
    assert "output" in hits[0].message
    # fusion off: the rule is gated on the fusion-aware timeline
    legacy = analysis.lint_step(seam, x, config={"fusion": False})
    assert not legacy.by_rule("hbm-unfused-chain")
    # under the floor: a small seam is not worth a finding
    small = analysis.lint_step(seam, jnp.ones((8, 8), jnp.float32))
    assert not small.by_rule("hbm-unfused-chain")
    # a chain that fuses end-to-end never fires
    def clean_fn(z):
        return paddle.sum((z * 2.0) + 1.0)

    clean = analysis.lint_step(clean_fn, jnp.ones((1024, 512), jnp.float32))
    assert not clean.by_rule("hbm-unfused-chain")


# ---------------------------------------------------------------------------
# shard-lint integration: materialized-bytes comm denominator
# ---------------------------------------------------------------------------

def test_comm_fraction_fusion_denominator():
    """The fusion-aware comm_fraction divides by materialized bytes only:
    it is ≥ the legacy proxy-based fraction and carries both counters."""
    from paddle_tpu.analysis import shard_lint

    def step(x):
        return jnp.sum(jnp.tanh(x * 2.0 + 1.0), axis=1)

    closed = jax.make_jaxpr(step)(jnp.ones((64, 256), jnp.float32))
    spec = (("dp",), ())  # batch dim sharded over dp, features replicated
    sa_on = shard_lint.propagate_jaxpr(closed, [spec], {"dp": 8})
    sa_off = shard_lint.propagate_jaxpr(closed, [spec], {"dp": 8},
                                        fusion=False)
    assert sa_on.fusion is True and sa_off.fusion is False
    assert 0 < sa_on.bytes_materialized < sa_on.bytes_proxy
    assert sa_off.comm_fraction <= sa_on.comm_fraction
    d = sa_on.as_dict()
    assert d["fusion"] is True
    assert d["bytes_materialized"] == sa_on.bytes_materialized
    assert "materialized" in sa_on.table()


# ---------------------------------------------------------------------------
# the ratchet: measured-zoo certification
# ---------------------------------------------------------------------------

def test_mem_rtol_ratcheted():
    """ISSUE 18 headline: the fusion-aware band is 0.10, down from the
    fusion-blind 0.15 kept for the legacy path."""
    assert analysis.MEM_RTOL == 0.10
    assert analysis.MEM_RTOL_UNFUSED == 0.15
    assert analysis.MEM_RTOL < analysis.MEM_RTOL_UNFUSED


def _cli(*argv):
    """Run the mem-lint CLI in a SUBPROCESS: the measured crosscheck needs
    a real alias term, and this test process's persistent compile cache
    would report alias_unavailable on warm runs (see test_mem_lint.py)."""
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "mem_lint.py")
    return subprocess.run(
        [sys.executable, path, *argv], capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


@needs_8_devices
def test_crosscheck_dp_plain_zero_measured():
    """The dp-plain/dp-zero pair — static-only before ISSUE 18 — now
    compiles and certifies the fusion-aware prediction against
    ``compiled.memory_analysis()`` at the ratcheted band."""
    out = _cli("--models", "dp-plain", "dp-zero", "--measure")
    assert out.returncode == 0, out.stdout + out.stderr
    checks = [l for l in out.stdout.splitlines()
              if l.startswith("crosscheck:")]
    assert len(checks) == 2, out.stdout
    for line in checks:
        assert "agrees=True" in line and "under_predicted=False" in line, \
            line
    assert "0 crosscheck disagreement(s)" in out.stdout


@needs_8_devices
def test_fusion_ab_fixture():
    """The A/B fixture proves the simulation elides real bytes on the
    dp-plain step without dipping under the donated-state floor."""
    out = _cli("--fixture", "fusion-ab")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "-> OK" in out.stdout
