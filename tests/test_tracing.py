"""Request-scoped tracing (ISSUE 8): span model, propagation through the
serving scheduler and the hapi fit loop, compile attribution, exports.

Contracts under test:
  * zero overhead while disabled — ``span()``/``start_span()`` hand back a
    shared no-op singleton, nothing is recorded;
  * one exported trace reconstructs a served request END TO END: submit →
    queue wait → prefill (with the bucket compile attributed inside it) →
    every decode token interval → evict, all sharing the request's trace
    id (acceptance criterion);
  * a decode step shared by multiple slots yields exactly ONE span per
    active request, each linked to the shared batched-dispatch span;
  * ``Model.fit`` emits epoch/step spans under the same API, with the
    train-step compile parented inside the first step span;
  * the PR 2/3/6 compile-count contracts hold with tracing on: decode
    still compiles exactly once.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.nn import CrossEntropyLoss
from paddle_tpu.profiler import telemetry, tracing
from paddle_tpu.serving import GenerationEngine, Request, Scheduler
from paddle_tpu.utils import unique_name


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset()
    yield
    tracing.disable()
    tracing.reset()


@pytest.fixture
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _gpt(seed=0, max_pos=64):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=max_pos, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    return model


# ---------------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------------
def test_disabled_by_default_null_singletons():
    assert not tracing.enabled()
    s1 = tracing.span("a")
    s2 = tracing.start_span("b")
    assert s1 is s2 is tracing.NULL_SPAN
    # the whole Span surface no-ops
    with s1 as s:
        s.set_attr("k", 1).end()
    assert tracing.current_span() is None
    with tracing.activate(s1):
        pass
    assert tracing.get_tracer().spans() == []
    assert tracing.note_compile("step", 0, 1) is None


def test_span_nesting_parenting_and_ids():
    tracing.enable()
    with tracing.span("root", attrs={"k": "v"}) as root:
        assert tracing.current_span() is root
        with tracing.span("child") as child:
            with tracing.span("grandchild") as gc:
                pass
        with tracing.span("sibling") as sib:
            pass
    assert tracing.current_span() is None
    assert child.trace_id == root.trace_id == gc.trace_id == sib.trace_id
    assert child.parent_id == root.span_id
    assert sib.parent_id == root.span_id
    assert gc.parent_id == child.span_id
    assert root.parent_id is None
    assert root.attrs["k"] == "v"
    # ends are monotone and every span landed in the ring
    names = [s.name for s in tracing.get_tracer().spans()]
    assert names == ["grandchild", "child", "sibling", "root"]
    assert root.duration_s >= child.duration_s >= gc.duration_s >= 0


def test_separate_roots_get_separate_traces():
    tracing.enable()
    with tracing.span("a") as a:
        pass
    with tracing.span("b") as b:
        pass
    assert a.trace_id != b.trace_id
    assert set(tracing.get_tracer().trace_ids()) == {a.trace_id, b.trace_id}


def test_manual_spans_and_activation():
    tracing.enable()
    tr = tracing.get_tracer()
    root = tracing.start_span("request")
    # not current until activated
    assert tracing.current_span() is None
    with tracing.activate(root):
        assert tracing.current_span() is root
        inner = tracing.span("work")
        with inner as w:
            pass
    assert tracing.current_span() is None
    assert w.parent_id == root.span_id
    assert root.end_ns is None  # activation must NOT end it
    root.end()
    root.end()  # idempotent
    assert len(tr.spans(root.trace_id)) == 2


def test_ring_bound_and_dropped_counter():
    tracing.enable(ring_size=8)
    for i in range(20):
        with tracing.span(f"s{i}"):
            pass
    tr = tracing.get_tracer()
    assert len(tr.spans()) == 8
    assert tr.dropped == 12
    tracing.enable(ring_size=8192)  # restore the default for later tests


def test_export_jsonl_and_chrome(tmp_path):
    tracing.enable()
    with tracing.span("outer", attrs={"rid": 7}):
        with tracing.span("inner"):
            pass
    p = tmp_path / "trace.jsonl"
    n = tracing.get_tracer().export_jsonl(str(p))
    rows = [json.loads(l) for l in p.read_text().splitlines()]
    assert n == len(rows) == 2
    by_name = {r["name"]: r for r in rows}
    assert by_name["inner"]["parent"] == by_name["outer"]["span"]
    assert by_name["inner"]["trace"] == by_name["outer"]["trace"]
    assert by_name["outer"]["attrs"]["rid"] == 7
    assert all(r["end_ns"] >= r["start_ns"] for r in rows)

    cp = tmp_path / "trace_chrome.json"
    ne = tracing.get_tracer().export_chrome(str(cp))
    doc = json.loads(cp.read_text())
    assert ne == 2
    evs = doc["traceEvents"]
    assert all(e["ph"] == "X" for e in evs)
    assert {e["name"] for e in evs} == {"outer", "inner"}
    assert evs[0]["ts"] <= evs[1]["ts"]


def test_export_chrome_merges_telemetry(tmp_path, _clean_telemetry):
    telemetry.enable()
    tracing.enable()
    with telemetry.phase_span("dispatch"):
        pass
    with tracing.span("req"):
        pass
    cp = tmp_path / "merged.json"
    n = tracing.get_tracer().export_chrome(str(cp), include_telemetry=True)
    evs = json.loads(cp.read_text())["traceEvents"]
    assert n == len(evs) == 2
    assert {e["name"] for e in evs} == {"req", "telemetry::dispatch"}


# ---------------------------------------------------------------------------
# serving: the end-to-end request reconstruction (acceptance criterion)
# ---------------------------------------------------------------------------
def _serve(n_requests=3, max_batch=2, max_new=4, slo=None):
    model = _gpt()
    eng = GenerationEngine(model, max_batch=max_batch, max_len=64,
                           prefill_buckets=(8, 16))
    sched = Scheduler(eng, slo=slo)
    rng = np.random.RandomState(0)
    reqs = [Request(prompt=rng.randint(0, 97, 5).tolist(),
                    max_new_tokens=max_new) for _ in range(n_requests)]
    for r in reqs:
        sched.submit(r)
    sched.run()
    sched.shutdown()  # closes the serve_session span
    return eng, sched, reqs


def test_request_trace_reconstructs_end_to_end(tmp_path, _clean_telemetry):
    telemetry.enable()
    tracing.enable()
    eng, sched, reqs = _serve(n_requests=3, max_batch=2, max_new=4)
    tr = tracing.get_tracer()

    for req in reqs:
        assert req.trace_id is not None
        spans = {s.span_id: s for s in tr.spans(req.trace_id)}
        by_name = {}
        for s in spans.values():
            by_name.setdefault(s.name, []).append(s)
        root = by_name["request"][0]
        queue = by_name["queue"][0]
        prefill = by_name["prefill"][0]
        decodes = sorted(by_name["decode_token"],
                         key=lambda s: s.attrs["index"])

        # all spans share the request's trace and hang off its root
        assert root.parent_id is None
        assert queue.parent_id == root.span_id
        assert prefill.parent_id == root.span_id
        assert all(d.parent_id == root.span_id for d in decodes)

        # the life cycle is ordered: submit → queue wait → prefill →
        # every decode token interval → evict
        assert root.start_ns <= queue.start_ns <= queue.end_ns
        assert queue.end_ns <= prefill.start_ns <= prefill.end_ns
        prev = prefill.end_ns
        for d in decodes:
            assert d.start_ns >= prev - 1  # shared batched interval
            prev = d.end_ns
        assert root.end_ns >= prev

        # token accounting: prefill's token + one decode span per
        # subsequent token
        assert len(decodes) == len(req.tokens) - 1
        assert [d.attrs["token"] for d in decodes] == req.tokens[1:]
        assert root.attrs["finish_reason"] == req.finish_reason
        assert root.attrs["ttft_s"] == pytest.approx(req.ttft_s)
        assert root.attrs["latency_s"] == pytest.approx(req.latency_s)

        # the engine's serve_prefill span nests inside the scheduler's
        # prefill span — same trace, so compile attribution joins up
        engine_pf = by_name["serve_prefill"][0]
        assert engine_pf.parent_id == prefill.span_id

    # compile attribution: the FIRST request through a cold bucket carries
    # the serve_prefill compile span inside its own trace
    first = reqs[0]
    comp = [s for s in tr.spans(first.trace_id) if s.name == "compile"]
    assert comp, "no compile span attributed to the first request"
    assert comp[0].attrs["step"] == "serve_prefill"
    assert comp[0].attrs["compile_index"] == 1

    # JSONL export round-trips the whole reconstruction
    p = tmp_path / "req.jsonl"
    tr.export_jsonl(str(p), trace_id=first.trace_id)
    rows = [json.loads(l) for l in p.read_text().splitlines()]
    assert {r["trace"] for r in rows} == {first.trace_id}
    assert {"request", "queue", "prefill", "decode_token",
            "compile"} <= {r["name"] for r in rows}

    # PR 6 contract unchanged under tracing: decode compiled EXACTLY once
    assert telemetry.get_telemetry().compile_counts()["serve_decode"] == 1


def test_shared_decode_step_one_span_per_active_request(_clean_telemetry):
    """Two requests decoding in the same batched step: each gets its OWN
    decode_token span over the shared interval, linked to the shared
    decode_step span."""
    telemetry.enable()
    tracing.enable()
    eng, sched, reqs = _serve(n_requests=2, max_batch=2, max_new=4)
    tr = tracing.get_tracer()

    session = [s for s in tr.spans() if s.name == "serve_session"]
    shared = [s for s in tr.spans() if s.name == "decode_step"]
    assert session and shared
    assert all(s.parent_id == session[0].span_id for s in shared)
    # both requests were admitted in tick 0, so every decode_step ran 2
    # slots: per shared span, exactly one decode_token per request
    for ds in shared:
        linked = [s for s in tr.spans()
                  if s.name == "decode_token"
                  and s.attrs.get("decode_span") == ds.span_id]
        assert len(linked) == ds.attrs["active"] == 2
        assert ({s.trace_id for s in linked}
                == {r.trace_id for r in reqs})
        # the fan-out reuses the shared dispatch interval verbatim
        assert all(s.start_ns == ds.start_ns and s.end_ns == ds.end_ns
                   for s in linked)


def test_scheduler_tracing_off_is_free(_clean_telemetry):
    """Tracing disabled: no Request picks up spans and the tracer stays
    empty — the serving loop's disabled path does zero tracing work."""
    telemetry.enable()
    eng, sched, reqs = _serve(n_requests=2, max_batch=2, max_new=3)
    assert all(r.trace_span is None and r.trace_id is None for r in reqs)
    assert tracing.get_tracer().spans() == []


def test_generate_emits_its_own_trace():
    tracing.enable()
    model = _gpt()
    eng = GenerationEngine(model, max_batch=1, max_len=64,
                           prefill_buckets=(8,))
    out = eng.generate([1, 2, 3], max_new_tokens=3)
    tr = tracing.get_tracer()
    gen = [s for s in tr.spans() if s.name == "generate"]
    assert len(gen) == 1
    inside = tr.spans(gen[0].trace_id)
    names = [s.name for s in inside]
    assert names.count("serve_prefill") == 1
    assert names.count("serve_decode") == len(out) - 1


# ---------------------------------------------------------------------------
# training: Model.fit under the same span model
# ---------------------------------------------------------------------------
class _ToyDS:
    def __init__(self, n=48):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def test_model_fit_emits_step_spans(_clean_telemetry):
    tracing.enable()
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    model.prepare(opt, CrossEntropyLoss())
    model.fit(_ToyDS(), batch_size=16, epochs=1, verbose=0)

    tr = tracing.get_tracer()
    epochs = [s for s in tr.spans() if s.name == "train_epoch"]
    steps = [s for s in tr.spans() if s.name == "train_step"]
    assert len(epochs) == 1
    assert len(steps) == 3  # 48 samples / batch 16
    root = epochs[0]
    assert all(s.parent_id == root.span_id for s in steps)
    assert all(s.trace_id == root.trace_id for s in steps)
    assert [s.attrs["step"] for s in
            sorted(steps, key=lambda s: s.start_ns)] == [0, 1, 2]
    assert root.attrs["samples"] == 48
    # the train-step compile is attributed inside the first step span —
    # even though telemetry was off (tracing-only compile attribution)
    comps = [s for s in tr.spans(root.trace_id) if s.name == "compile"]
    assert comps, "train-step compile not attributed to the trace"
    first_step = min(steps, key=lambda s: s.start_ns)
    assert comps[0].parent_id == first_step.span_id
    # telemetry stayed untouched: tracing alone must not populate it
    assert telemetry.get_telemetry().counters() == {}
