"""Serving speed v2 (ISSUE 13): speculative decoding, chunked prefill
and real sampling.

Contracts under test:
  * speculative decoding NEVER changes output — 64+ tokens served with
    n-gram drafts + batched verify are byte-identical to plain greedy,
    for mid-bucket AND bucket-boundary prompt lengths (the acceptance
    gate: rejection falls back to the verifier's own token);
  * chunked prefill is invisible to the stream — a prompt prefilled in
    fixed-size chunks interleaved with decode produces the same tokens
    as one-shot bucketed prefill, and ``chunked_prefill_fits`` gates the
    DUS-clamp hazard (a final chunk that would overhang ``max_len``);
  * sampling is real and deterministic — per-slot seeded PRNG keys as
    traced data: same seed -> same stream, different seed diverges, and
    a sampled neighbor in the batch NEVER perturbs a greedy slot;
  * the compile contract holds with everything on — verify and chunk
    steps compile EXACTLY once each, decode at most once, prefill once
    per bucket, and ``recompile_count`` is 0 against the engine's
    declared variants;
  * ``NgramProposer`` prompt-lookup semantics (longest-match-first,
    cyclic extrapolation to the static window, empty on novel text).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler import telemetry
from paddle_tpu.serving import (
    DraftProposer,
    GenerationEngine,
    NgramProposer,
    Request,
    Scheduler,
)
from paddle_tpu.utils import unique_name

MAX_LEN = 96
BUCKETS = (8, 16)


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compile_cache():
    """Parity here compares streams across DIFFERENT executables (decode
    [b,1] vs verify [b,k+1] vs chunk [1,c]); executables round-tripped
    through the persistent XLA:CPU compile cache are not bit-identical
    to in-process compiles on this stack (conftest warm-cache hazard
    note), so the whole module compiles in-process."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _gpt(seed=0):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=128, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    return model


@pytest.fixture(scope="module")
def model():
    return _gpt()


def _engine(model, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", MAX_LEN)
    kw.setdefault("prefill_buckets", BUCKETS)
    return GenerationEngine(model, **kw)


def _serve(eng, reqs, speculative=None):
    sched = Scheduler(eng, speculative=speculative,
                      retry_sleep=lambda s: None)
    for r in reqs:
        sched.submit(r)
    sched.run()
    return [tuple(r.tokens) for r in reqs]


def _reqs(prompts, max_new=64, **kw):
    return [Request(prompt=list(p), max_new_tokens=max_new, **kw)
            for p in prompts]


# ---------------------------------------------------------------------------
# NgramProposer units
# ---------------------------------------------------------------------------
def test_ngram_proposer_lookup_extrapolates_to_full_window():
    # trailing 3-gram [1,2,3] recurs at the front; the continuation is
    # extrapolated cyclically (period d=4) to fill the static window
    p = NgramProposer()
    assert p.propose([1, 2, 3, 4, 1, 2, 3], 4) == [4, 1, 2, 3]
    assert p.propose([1, 2, 3, 4, 1, 2, 3], 2) == [4, 1]


def test_ngram_proposer_prefers_longest_then_most_recent_match():
    # no 3-gram recurs; the trailing 1-gram `2` matches at i=1 and i=3 —
    # the MOST RECENT earlier occurrence (i=3) wins, continuation 9
    p = NgramProposer()
    assert p.propose([5, 2, 7, 2, 9, 2], 3)[0] == 9


def test_ngram_proposer_novel_text_and_degenerate_inputs():
    p = NgramProposer()
    assert p.propose([1, 2, 3, 4, 5], 4) == []  # no repeated n-gram
    assert p.propose([7], 4) == []              # too short to match
    assert p.propose([1, 2, 1], 0) == []        # no window to fill
    p.observe([1, 2, 1], 0)  # stateless hook: must simply not raise


def test_ngram_proposer_validates_ngram_bounds():
    with pytest.raises(ValueError):
        NgramProposer(max_ngram=1, min_ngram=2)
    with pytest.raises(ValueError):
        NgramProposer(min_ngram=0)


def test_draft_proposer_interface_is_abstract():
    with pytest.raises(NotImplementedError):
        DraftProposer().propose([1, 2], 4)


# ---------------------------------------------------------------------------
# speculative parity (the acceptance gate)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("prompt_len", [5, 16],
                         ids=["mid-bucket", "bucket-boundary"])
def test_spec_byte_identical_to_plain_greedy_64_tokens(model, prompt_len):
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, 97, prompt_len).tolist() for _ in range(4)]
    plain = _serve(_engine(model), _reqs(prompts))
    spec = _serve(_engine(model, spec_k=4), _reqs(prompts))
    assert spec == plain
    assert all(len(t) == 64 for t in spec)


def test_spec_with_chunked_prefill_matches_plain(model):
    rng = np.random.RandomState(12)
    # mixed lengths straddling the chunk width (4): 3 one-shot, rest
    # chunked — both admission paths feed the same speculative loop
    prompts = [rng.randint(0, 97, n).tolist() for n in (3, 6, 11, 16)]
    plain = _serve(_engine(model), _reqs(prompts, max_new=32))
    both = _serve(_engine(model, spec_k=4, prefill_chunk=4),
                  _reqs(prompts, max_new=32))
    assert both == plain


def test_scheduler_speculative_false_forces_plain_path(model):
    rng = np.random.RandomState(13)
    prompts = [rng.randint(0, 97, 7).tolist() for _ in range(2)]
    eng = _engine(model, spec_k=4)
    telemetry.reset()
    telemetry.enable()
    try:
        out = _serve(eng, _reqs(prompts, max_new=16), speculative=False)
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert not counters.get("serve.spec_ticks")
    assert out == _serve(_engine(model), _reqs(prompts, max_new=16))


def test_spec_compile_contract_everything_on(model):
    rng = np.random.RandomState(14)
    prompts = [rng.randint(0, 97, n).tolist() for n in (5, 9, 13, 16)]
    telemetry.reset()
    telemetry.enable()
    try:
        eng = _engine(model, spec_k=4, prefill_chunk=4)
        _serve(eng, _reqs(prompts, max_new=48))
        tm = telemetry.get_telemetry()
        compiles = dict(tm.compile_counts())
        counters = dict(tm.counters())
        recompiles = tm.recompile_count
    finally:
        telemetry.disable()
        telemetry.reset()
    assert counters.get("serve.spec_ticks", 0) > 0, \
        "speculation never engaged"
    assert counters.get("serve.prefill_chunks", 0) > 0, \
        "chunked prefill never engaged"
    assert compiles.get("serve_verify") == 1
    assert compiles.get("serve_prefill_chunk") == 1
    assert compiles.get("serve_decode", 0) <= 1  # fallback ticks only
    assert compiles.get("serve_prefill", 0) <= len(BUCKETS)
    # per-(bucket|step) compiles are DECLARED variants, not churn
    assert recompiles == 0


def test_spec_acceptance_telemetry_accounts(model):
    # a cyclic prompt is the n-gram proposer's best case: drafts must be
    # proposed, (mostly) accepted, and the counters must reconcile
    prompts = [[1, 2, 3] * 5 for _ in range(2)]
    eng = _engine(model, spec_k=4)
    telemetry.reset()
    telemetry.enable()
    try:
        _serve(eng, _reqs(prompts, max_new=24))
        tm = telemetry.get_telemetry()
        counters = dict(tm.counters())
        rate = tm.gauges().get("serve.spec_acceptance_rate")
    finally:
        telemetry.disable()
        telemetry.reset()
    proposed = counters.get("serve.spec_proposed", 0)
    accepted = counters.get("serve.spec_accepted", 0)
    assert proposed > 0
    assert 0 <= accepted <= proposed
    assert rate == pytest.approx(accepted / proposed)


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------
def test_chunked_prefill_fits_gates_the_clamp_hazard(model):
    eng = GenerationEngine(model, max_batch=2, max_len=10,
                           prefill_buckets=(8,), prefill_chunk=4)
    assert eng.chunked_prefill_fits(7)        # rounds to 8 <= 10
    assert not eng.chunked_prefill_fits(9)    # rounds to 12 > 10: clamp
    assert not eng.chunked_prefill_fits(0)
    assert not _engine(model).chunked_prefill_fits(7)  # chunking off


def test_unchunkable_prompt_falls_back_to_one_shot_prefill(model):
    # 9 tokens round to 12 > max_len=10: the scheduler must take the
    # bucketed one-shot path and still finish the request normally
    eng = GenerationEngine(model, max_batch=2, max_len=10,
                           prefill_buckets=(4, 9), prefill_chunk=4)
    req = Request(prompt=list(range(1, 10)), max_new_tokens=1)
    telemetry.reset()
    telemetry.enable()
    try:
        _serve(eng, [req])
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert req.finish_reason == "length"
    assert not counters.get("serve.prefill_chunks")


def test_chunk_step_rejects_misaligned_and_overhanging_offsets(model):
    eng = _engine(model, prefill_chunk=4)
    prompt = list(range(1, 12))
    with pytest.raises(ValueError):
        eng.prefill_chunk_step(0, prompt, 3)   # not a chunk multiple
    with pytest.raises(ValueError):
        eng.prefill_chunk_step(0, prompt, 12)  # outside the prompt


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------
def test_seeded_sampling_is_deterministic_and_seeds_diverge(model):
    prompts = [[5, 7, 11]] * 3
    streams = []
    for _ in range(2):
        eng = _engine(model, spec_k=4, prefill_chunk=4)
        reqs = [Request(prompt=list(prompts[i]), max_new_tokens=12,
                        temperature=0.8, top_k=10, top_p=0.9, seed=s)
                for i, s in enumerate((7, 7, 8))]
        streams.append(tuple(_serve(eng, reqs)))
    same_a, same_b, other = streams[0]
    assert streams[0] == streams[1]  # replay: byte-identical
    assert same_a == same_b          # same seed, same prompt: same draw
    assert same_a != other           # different seed diverges


def test_greedy_slot_unperturbed_by_sampled_neighbors(model):
    prompt = [5, 7, 11, 3]
    eng = _engine(model, spec_k=4)
    sampled = Request(prompt=list(prompt), max_new_tokens=12,
                      temperature=0.9, top_k=20, seed=21)
    greedy = Request(prompt=list(prompt), max_new_tokens=12)
    _serve(eng, [sampled, greedy])
    solo = Request(prompt=list(prompt), max_new_tokens=12)
    _serve(_engine(model), [solo])
    assert greedy.tokens == solo.tokens
    assert sampled.tokens != solo.tokens or True  # sampled may coincide


def test_sampling_state_is_data_not_shape(model):
    """Arming/clearing sampling must not recompile: the knobs ride fixed
    [max_batch] arrays through the same executables."""
    telemetry.reset()
    telemetry.enable()
    try:
        eng = _engine(model)
        eng.prefill(0, [1, 2, 3])
        eng.decode_once(np.zeros(4, np.int32))
        eng.set_slot_sampling(0, temperature=0.7, top_k=5, seed=3)
        eng.decode_once(np.zeros(4, np.int32))
        eng.clear_slot_sampling(0)
        eng.decode_once(np.zeros(4, np.int32))
        compiles = dict(telemetry.get_telemetry().compile_counts())
    finally:
        telemetry.disable()
        telemetry.reset()
    assert compiles.get("serve_decode") == 1
    assert not eng.slot_is_sampled(0)


def test_set_slot_sampling_validates(model):
    eng = _engine(model)
    with pytest.raises(ValueError):
        eng.set_slot_sampling(9, temperature=0.5)
    with pytest.raises(ValueError):
        eng.set_slot_sampling(0, temperature=-1.0)
    with pytest.raises(ValueError):
        eng.set_slot_sampling(0, temperature=0.5, top_p=0.0)
    with pytest.raises(ValueError):
        eng.set_slot_sampling(0, temperature=0.5, top_k=-2)
