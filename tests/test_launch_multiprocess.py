"""Two-process launch CLI test (VERDICT item 7): python -m
paddle_tpu.distributed.launch spawns ranks, init_parallel_env performs the
jax.distributed rendezvous, cross-process collectives verified for parity.
Reference pattern: unittests/test_collective_base.py:33."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.timeout(300)
def test_two_process_launch_collective_parity(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["LAUNCH_TEST_OUT"] = str(tmp_path)
    # each child is a fresh process: 1 local CPU device per rank
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "gloo",
         "--log_dir", str(tmp_path / "logs"), "--job_id", "t2p",
         os.path.join(REPO, "tests", "launch_rank_script.py")],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=280,
    )
    logs = ""
    log_dir = tmp_path / "logs"
    if log_dir.exists():
        for p in sorted(log_dir.iterdir()):
            logs += f"\n--- {p.name} ---\n" + p.read_text()[-3000:]
    assert r.returncode == 0, f"launch failed: {r.stdout}\n{r.stderr}\n{logs}"

    results = []
    for rank in (0, 1):
        f = tmp_path / f"rank{rank}.json"
        assert f.exists(), f"rank {rank} wrote no result\n{logs}"
        results.append(json.load(open(f)))

    for res in results:
        assert res["world"] == 2
        assert res["psum"] == 12.0
    # data-parallel step: both ranks must agree on loss and updated weights
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-6)
    np.testing.assert_allclose(results[0]["w"], results[1]["w"], rtol=1e-6)


def test_launch_cli_reports_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         str(bad)],
        env=env, cwd=str(tmp_path), capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1
    assert "failed" in r.stderr


def test_two_node_launch_dcn_collectives(tmp_path):
    """2 nodes x 2 procs (round-3 VERDICT missing #5): two launcher
    invocations share one coordinator; the hybrid mesh gets an explicit
    dcn axis (= node boundary) and collectives cross it."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{s.getsockname()[1]}"

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["LAUNCH_TEST_OUT"] = str(tmp_path)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    script = os.path.join(REPO, "tests", "launch_multinode_script.py")
    launchers = []
    for node in (0, 1):
        launchers.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--rank", str(node),
             "--nproc_per_node", "2", "--master", master,
             "--backend", "gloo",
             "--log_dir", str(tmp_path / f"logs{node}"),
             "--job_id", f"n{node}", script],
            env=env, cwd=str(tmp_path),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in launchers:
        try:
            out, _ = p.communicate(timeout=280)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)

    logs = ""
    for node in (0, 1):
        d = tmp_path / f"logs{node}"
        if d.exists():
            for f in sorted(d.iterdir()):
                logs += f"\n--- {f.name} ---\n" + f.read_text()[-2500:]
    assert all(p.returncode == 0 for p in launchers), \
        f"launchers failed: {outs}\n{logs}"
    for rank in range(4):
        f = tmp_path / f"rank{rank}.json"
        assert f.exists(), f"rank {rank} wrote no result\n{logs}"
        res = json.load(open(f))
        assert res["world"] == 4 and res["psum"] == 40.0
        assert res["node"] == rank // 2


def _run_elastic_job(tmp_path, kill, tag):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        store = f"127.0.0.1:{s.getsockname()[1]}"

    out = tmp_path / tag
    out.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["LAUNCH_TEST_OUT"] = str(out)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PADDLE_ELASTIC_STORE"] = store
    env["ELASTIC_TEST_KILL"] = "1" if kill else "0"

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "gloo", "--max_restart", "2",
         "--log_dir", str(out / "logs"), "--job_id", tag,
         os.path.join(REPO, "tests", "elastic_rank_script.py")],
        env=env, cwd=str(out), capture_output=True, text=True, timeout=280,
    )
    logs = ""
    if (out / "logs").exists():
        for p in sorted((out / "logs").iterdir()):
            logs += f"\n--- {p.name} ---\n" + p.read_text()[-2500:]
    assert r.returncode == 0, f"job failed: {r.stdout}\n{r.stderr}\n{logs}"
    res = []
    for rank in (0, 1):
        f = out / f"final_rank{rank}.json"
        assert f.exists(), f"rank {rank} wrote no result\n{logs}"
        res.append(json.load(open(f)))
    return res


def test_elastic_sigkill_restart_resumes_with_parity(tmp_path):
    """Round-3 VERDICT missing #4: SIGKILL one of two ranks mid-epoch; the
    survivor detects the dead peer through the TCPStore heartbeat watch,
    the launcher restarts, auto_checkpoint resumes from the last saved
    epoch, and the final state matches an uninterrupted run bit-for-bit."""
    killed = _run_elastic_job(tmp_path, kill=True, tag="killed")
    clean = _run_elastic_job(tmp_path, kill=False, tag="clean")

    for res in killed:
        assert res["attempt"] == "restarted"
        # the restarted attempt resumed AT epoch 1 (checkpoint after epoch
        # 0), not from scratch
        assert res["epochs"] == [1, 2, 3], res["epochs"]
    for res in clean:
        assert res["attempt"] == "clean"
        assert res["epochs"] == [0, 1, 2, 3]

    # ranks agree within each job; killed-and-resumed == uninterrupted
    for pair in (killed, clean):
        np.testing.assert_allclose(pair[0]["w"], pair[1]["w"], rtol=1e-6)
    np.testing.assert_allclose(killed[0]["w"], clean[0]["w"], rtol=1e-6)
    np.testing.assert_allclose(killed[0]["b"], clean[0]["b"], rtol=1e-6)
    assert killed[0]["last_loss"] == pytest.approx(clean[0]["last_loss"],
                                                   rel=1e-6)
