"""Distributed tests on the 8-device CPU mesh (SURVEY.md §4: multi-device
parity vs single-device results, the TPU analogue of the reference's
multi-process collective harness ``test_collective_base.py``)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.collective import _default_group


@pytest.fixture(autouse=True)
def _fresh_groups():
    yield


def test_eight_devices_visible():
    assert len(jax.devices()) == 8


# ---------------------------------------------------------------------------
# collectives — eager path (sharded arrays)
# ---------------------------------------------------------------------------

def test_all_reduce_sum_eager():
    g = _default_group()
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    dist.all_reduce(x)
    # postcondition: every per-rank shard holds the sum of all shards
    np.testing.assert_allclose(x.numpy(), np.full(8, np.arange(8).sum(), np.float32))


def test_all_reduce_max_min():
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    dist.all_reduce(x, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(x.numpy(), np.full(8, 7, np.float32))
    y = paddle.to_tensor(np.arange(8, dtype=np.float32) + 1)
    dist.all_reduce(y, op=dist.ReduceOp.PROD)
    np.testing.assert_allclose(y.numpy(), np.full(8, np.prod(np.arange(8) + 1.0)))


def test_broadcast_eager():
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    dist.broadcast(x, src=3)
    np.testing.assert_allclose(x.numpy(), np.full(8, 3, np.float32))


def test_reduce_to_dst():
    x = paddle.to_tensor(np.ones(8, np.float32))
    dist.reduce(x, dst=2)
    expect = np.ones(8, np.float32)
    expect[2] = 8.0
    np.testing.assert_allclose(x.numpy(), expect)


def test_all_gather_eager():
    out = []
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    dist.all_gather(out, x)
    assert len(out) == 8
    for i, t in enumerate(out):
        np.testing.assert_allclose(t.numpy(), [i])


def test_reduce_scatter_eager():
    # sharded-array model: [8, 8] = 8 rank-shards of [8]; rank i ends with
    # sum_j shard_j[i] — all ones → every rank's piece is 8
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    out = dist.reduce_scatter(x)
    np.testing.assert_allclose(np.asarray(out.numpy()).ravel(), np.full(8, 8.0))


def test_scatter_eager():
    parts = [paddle.to_tensor(np.full((1, 2), i, np.float32)) for i in range(8)]
    x = paddle.to_tensor(np.zeros((1, 2), np.float32))
    dist.scatter(x, parts, src=0)
    got = x.numpy().reshape(8, 2)
    np.testing.assert_allclose(got, np.arange(8, dtype=np.float32)[:, None].repeat(2, 1))


def test_alltoall_single_eager():
    # [8, 8]: rank r owns row r; piece exchange ≙ block transpose
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    out = dist.alltoall_single(x)
    np.testing.assert_allclose(
        out.numpy().reshape(8, 8), np.arange(64, dtype=np.float32).reshape(8, 8).T
    )


def test_barrier_and_wait():
    dist.barrier()
    t = paddle.ones([4])
    dist.wait(t)


# ---------------------------------------------------------------------------
# collectives — inside shard_map (the c_* ops in a Program position)
# ---------------------------------------------------------------------------

def test_collectives_in_shard_map():
    g = _default_group()

    def body(x):
        t = paddle.to_tensor(x)
        dist.all_reduce(t)
        return t._value

    f = shard_map(body, mesh=g.mesh, in_specs=(P(g.axis_name),), out_specs=P(g.axis_name), check_vma=False)
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_ppermute_ring_via_send_recv_shapes():
    g = _default_group()

    def body(x):
        from paddle_tpu.distributed.collective import _shift

        return _shift(paddle.to_tensor(x), g, 1)

    f = shard_map(body, mesh=g.mesh, in_specs=(P(g.axis_name),), out_specs=P(g.axis_name), check_vma=False)
    out = np.asarray(f(jnp.arange(8.0)))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


# ---------------------------------------------------------------------------
# DataParallel parity: sharded-batch training == single-device training
# ---------------------------------------------------------------------------

def _train(model, xs, ys, wrap_dp):
    paddle.seed(7)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    m = dist.DataParallel(model) if wrap_dp else model
    losses = []
    for x, y in zip(xs, ys):
        out = m(paddle.to_tensor(x))
        loss = ((out - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses, [p.numpy().copy() for p in model.parameters()]


def test_data_parallel_parity_with_single_device():
    rng = np.random.RandomState(0)
    xs = [rng.randn(16, 4).astype(np.float32) for _ in range(5)]
    ys = [rng.randn(16, 2).astype(np.float32) for _ in range(5)]

    paddle.seed(3)
    m1 = nn.Linear(4, 2)
    paddle.seed(3)
    m2 = nn.Linear(4, 2)
    # identical init
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy())

    l_single, w_single = _train(m1, xs, ys, wrap_dp=False)
    l_dp, w_dp = _train(m2, xs, ys, wrap_dp=True)
    np.testing.assert_allclose(l_single, l_dp, rtol=1e-5)
    for a, b in zip(w_single, w_dp):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------

def test_communicate_topology_coords():
    topo = dist.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 1)
    rings = topo.get_comm_list("model")
    assert [0, 1] in rings and [6, 7] in rings
    assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]


def test_hybrid_communicate_group_mesh():
    hcg = dist.HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_parallel_mode() == "pipeline_parallel"
    assert hcg.mesh.devices.size == 8
    g = hcg.get_model_parallel_group()
    assert g.nranks == 2


# ---------------------------------------------------------------------------
# TP layers: parity with dense equivalents
# ---------------------------------------------------------------------------

def test_column_row_parallel_linear_parity():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["mp_degree"] = 8
    fleet.fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(11)
    col = dist.meta_parallel.ColumnParallelLinear(16, 32, gather_output=True)
    row = dist.meta_parallel.RowParallelLinear(32, 16, input_is_parallel=False)

    x = paddle.randn([4, 16])
    y = col(x)
    assert y.shape == [4, 32]
    z = row(y)
    assert z.shape == [4, 16]

    # parity against dense matmul with the same (gathered) weights
    y_ref = x.numpy() @ col.weight.numpy() + col.bias.numpy()
    np.testing.assert_allclose(y.numpy(), y_ref, rtol=2e-5, atol=1e-5)
    z_ref = y_ref @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(z.numpy(), z_ref, rtol=2e-5, atol=1e-5)

    # gradients flow through sharded weights
    z.sum().backward()
    assert col.weight.grad is not None and row.weight.grad is not None


def test_vocab_parallel_embedding_parity():
    paddle.seed(12)
    emb = dist.meta_parallel.VocabParallelEmbedding(64, 8)
    ids = paddle.to_tensor(np.array([[1, 5, 63], [0, 32, 31]], np.int64))
    out = emb(ids)
    assert out.shape == [2, 3, 8]
    np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6)


def test_parallel_cross_entropy_spmd_matches_dense():
    from paddle_tpu.distributed.meta_parallel.mp_layers import parallel_softmax_ce_spmd

    g = _default_group()
    rng = np.random.RandomState(5)
    logits = rng.randn(4, 64).astype(np.float32)
    labels = rng.randint(0, 64, (4,))

    f = shard_map(
        lambda lg, lb: parallel_softmax_ce_spmd(lg, lb, g.axis_name),
        mesh=g.mesh,
        in_specs=(P(None, g.axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    got = np.asarray(f(jnp.asarray(logits), jnp.asarray(labels)))
    # dense reference
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[:, 0]
    expect = lse - logits[np.arange(4), labels]
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# group_sharded (ZeRO) parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_parity(level):
    rng = np.random.RandomState(1)
    xs = [rng.randn(16, 8).astype(np.float32) for _ in range(4)]
    ys = [rng.randn(16, 8).astype(np.float32) for _ in range(4)]

    def build():
        paddle.seed(21)
        m = nn.Linear(8, 8)
        o = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
        return m, o

    m_ref, o_ref = build()
    for x, y in zip(xs, ys):
        loss = ((m_ref(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        o_ref.step()
        o_ref.clear_grad()

    m, o = build()
    m, o, _ = dist.sharding.group_sharded_parallel(m, o, level=level)
    for x, y in zip(xs, ys):
        loss = ((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()

    for (n1, p1), (n2, p2) in zip(m_ref.named_parameters(), m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-5, atol=1e-6, err_msg=n1)


# ---------------------------------------------------------------------------
# fleet facade
# ---------------------------------------------------------------------------

def test_fleet_dp_end_to_end():
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    fleet.fleet.init(is_collective=True, strategy=strategy)
    assert fleet.fleet.worker_num() >= 1

    paddle.seed(5)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    model = fleet.fleet.distributed_model(model)
    opt = fleet.fleet.distributed_optimizer(opt)

    x = paddle.randn([16, 4])
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# review-found paths: rank inside spmd, eager p2p channel, rs list form
# ---------------------------------------------------------------------------

def test_group_rank_traced_in_spmd():
    g = _default_group()

    def body(x):
        return x + g.rank

    f = shard_map(body, mesh=g.mesh, in_specs=(P(g.axis_name),), out_specs=P(g.axis_name), check_vma=False)
    out = np.asarray(f(jnp.zeros(8)))
    np.testing.assert_allclose(out, np.arange(8.0))


def test_eager_send_recv_moves_data():
    t = paddle.to_tensor(np.arange(8, dtype=np.float32))
    buf = paddle.to_tensor(np.zeros(8, np.float32))
    dist.send(t, dst=1)
    dist.recv(buf, src=0)
    np.testing.assert_allclose(buf.numpy(), np.arange(8, dtype=np.float32))


def test_recv_without_send_raises():
    with pytest.raises(RuntimeError):
        dist.recv(paddle.zeros([4]), src=0)


def test_reduce_scatter_tensor_list_form():
    out = paddle.zeros([8, 2])
    parts = [paddle.to_tensor(np.full((2,), i, np.float32)) for i in range(8)]
    dist.reduce_scatter(out, parts)
    got = out.numpy().reshape(8, 2)
    # all "ranks" contribute the same list → rank i gets nranks * entry i
    np.testing.assert_allclose(got, 8.0 * np.arange(8, dtype=np.float32)[:, None].repeat(2, 1))


def test_spmd_recv_relative_offset():
    g = _default_group()

    def body(x):
        t = paddle.to_tensor(x)
        return dist.recv(t, src=1, group=g)._value  # receive from rank-1

    f = shard_map(body, mesh=g.mesh, in_specs=(P(g.axis_name),), out_specs=P(g.axis_name), check_vma=False)
    out = np.asarray(f(jnp.arange(8.0)))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))
