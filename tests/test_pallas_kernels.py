"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

Mirrors the reference's fused-op tests (e.g.
``unittests/test_fused_attention_op.py``): the fused kernel must match the
naive composition in both forward values and gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import pallas
from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm


def _ref_attention(q, k, v, bias=None, causal=False):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -1e30)
    if bias is not None:
        logits = logits + bias
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _rand_qkv(b=2, s=256, h=2, d=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_parity(causal):
    q, k, v = _rand_qkv()
    with pallas.interpret_mode():
        out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_forward_bias():
    q, k, v = _rand_qkv()
    rng = np.random.RandomState(1)
    bias = jnp.asarray(rng.randn(1, 1, 256, 256).astype(np.float32))
    with pallas.interpret_mode():
        out = flash_attention(q, k, v, bias=bias, block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_forward_bool_padding_mask():
    q, k, v = _rand_qkv()
    keep = np.ones((1, 1, 256, 256), bool)
    keep[..., 200:] = False  # mask out trailing keys
    with pallas.interpret_mode():
        out = flash_attention(q, k, v, bias=jnp.asarray(keep),
                              block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, bias=jnp.where(jnp.asarray(keep), 0.0, -1e30))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_parity(causal):
    q, k, v = _rand_qkv(s=128)

    def loss_flash(q, k, v):
        with pallas.interpret_mode():
            out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = _ref_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal})",
        )


def test_flash_multi_kblock_grad():
    # sequence spanning several k blocks exercises the scratch accumulators
    q, k, v = _rand_qkv(s=512)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v)
            return jnp.sum(out**2)
        return f

    with pallas.interpret_mode():
        gf = jax.grad(
            loss(lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=128, block_k=128)),
            argnums=(0, 1, 2),
        )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: _ref_attention(q, k, v, causal=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_flash_causal_cross_length():
    """sq != sk: causal alignment must match the einsum path's bottom-right
    convention (tril with k = sk - sq)."""
    rng = np.random.RandomState(3)
    b, h, d = 2, 2, 64
    q = jnp.asarray(rng.randn(b, 128, h, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, 256, h, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, 256, h, d).astype(np.float32)) * 0.3
    with pallas.interpret_mode():
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_causal_fully_masked_rows():
    """Advisor regression (layout-swapping kernel): causal sq > sk with the
    masked-row boundary inside a q tile (offset=-128, block_q=256) — fully
    masked rows must emit output 0 and zero gradients, not a uniform
    softmax over v."""
    rng = np.random.RandomState(11)
    b, h, d = 1, 2, 64
    q = jnp.asarray(rng.randn(b, 512, h, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, 384, h, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, 384, h, d).astype(np.float32)) * 0.3

    def masked_ref(q, k, v):
        out = _ref_attention(q, k, v, causal=True)
        sq, sk = q.shape[1], k.shape[1]
        vis = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq).any(-1)
        return jnp.where(vis[None, :, None, None], out, 0.0)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v)
            return jnp.sum(out**2), out
        return f

    with pallas.interpret_mode():
        (val, out), gf = jax.value_and_grad(
            loss(lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=256, block_k=128)),
            argnums=(0, 1, 2), has_aux=True,
        )(q, k, v)
    np.testing.assert_array_equal(np.asarray(out[:, :128]), 0.0)
    np.testing.assert_array_equal(np.asarray(gf[0][:, :128]), 0.0)
    (_, ref), gr = jax.value_and_grad(loss(masked_ref), argnums=(0, 1, 2),
                                      has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    for a, bb in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=5e-5, rtol=5e-4)


def test_flash_causal_fully_masked_rows_dbias():
    """Review regression: the trainable-bias backward must also zero
    fully-masked causal rows — dbias on those rows is exactly 0 (the
    forward output there is constant 0)."""
    rng = np.random.RandomState(13)
    b, h, d = 1, 2, 64
    q = jnp.asarray(rng.randn(b, 256, h, d).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, 128, h, d).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, 128, h, d).astype(np.float32)) * 0.3
    bias = jnp.asarray(rng.randn(256, 128).astype(np.float32)) * 0.1

    def loss(bias):
        with pallas.interpret_mode():
            out = flash_attention(q, k, v, bias=bias, causal=True,
                                  block_q=256, block_k=128, bias_grad=True)
        return jnp.sum(out**2)

    dbias = jax.grad(loss)(bias)
    # offset = -128: rows 0..127 attend nothing
    np.testing.assert_array_equal(np.asarray(dbias[:128]), 0.0)
    assert np.abs(np.asarray(dbias[128:])).max() > 0


def test_bn_running_stats_keep_declared_dtype():
    """Review regression: bf16 running mean/var must not get silently
    promoted to fp32 by the (fp32-internal) training-stat update."""
    import paddle_tpu as paddle

    bn = paddle.nn.BatchNorm2D(3)
    bn.to(dtype="bfloat16")
    bn.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 4, 4).astype(np.float32)
    ).astype("bfloat16")
    bn(x)
    assert str(bn._mean.dtype).endswith("bfloat16"), bn._mean.dtype
    assert str(bn._variance.dtype).endswith("bfloat16"), bn._variance.dtype


def test_sdpa_broadcast_padding_mask_routes_to_einsum():
    """(b,1,1,sk) key-padding masks can't stream through the flash kernel;
    routing must fall back to the broadcasting einsum path, not crash."""
    import paddle_tpu  # noqa: F401
    from paddle_tpu.framework.tensor import Tensor
    import paddle_tpu.nn.functional as F

    q, k, v = _rand_qkv(s=128)
    mask = np.zeros((2, 1, 1, 128), np.float32)
    mask[..., 100:] = -1e30
    with pallas.interpret_mode():
        out = F.scaled_dot_product_attention(
            Tensor(q), Tensor(k), Tensor(v), attn_mask=Tensor(mask)
        )
    ref = _ref_attention(q, k, v, bias=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_fused_layer_norm_parity():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 37, 256).astype(np.float32))
    gamma = jnp.asarray(rng.randn(256).astype(np.float32))
    beta = jnp.asarray(rng.randn(256).astype(np.float32))

    def ref(x, gamma, beta):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * gamma + beta

    with pallas.interpret_mode():
        out = fused_layer_norm(x, gamma, beta, eps=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(x, gamma, beta)),
                               atol=1e-5, rtol=1e-5)

    def loss_fused(x, gamma, beta):
        with pallas.interpret_mode():
            return jnp.sum(fused_layer_norm(x, gamma, beta, eps=1e-5) ** 2)

    def loss_ref(x, gamma, beta):
        return jnp.sum(ref(x, gamma, beta) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gr, ["dx", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-4, err_msg=name)


def test_flash_seq_384_uses_128_block():
    """128-aligned lengths that aren't multiples of the preferred 256 block
    must still take the flash path (block falls back to 128)."""
    from paddle_tpu.ops.pallas.flash_attention import supports

    assert supports(384, 384, 64)
    q, k, v = _rand_qkv(s=384, seed=7)
    with pallas.interpret_mode():
        out = flash_attention(q, k, v, causal=True)
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fused_layer_norm_multiblock_grads():
    """rows > BLOCK_ROWS exercises the cross-block dgamma/dbeta accumulation
    (init-at-block-0 + revisited output block)."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(700, 128).astype(np.float32))  # 3 row blocks
    gamma = jnp.asarray(rng.randn(128).astype(np.float32))
    beta = jnp.asarray(rng.randn(128).astype(np.float32))

    def ref(x, gamma, beta):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * gamma + beta

    def loss_fused(x, gamma, beta):
        with pallas.interpret_mode():
            return jnp.sum(fused_layer_norm(x, gamma, beta, eps=1e-5) ** 2)

    def loss_ref(x, gamma, beta):
        return jnp.sum(ref(x, gamma, beta) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gr, ["dx", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-4, err_msg=name)


def test_sdpa_routes_to_flash_under_interpret():
    """F.scaled_dot_product_attention picks the Pallas path when available."""
    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu.framework.tensor import Tensor
    import paddle_tpu.nn.functional as F

    q, k, v = _rand_qkv(s=128)
    with pallas.interpret_mode():
        out = F.scaled_dot_product_attention(
            Tensor(q), Tensor(k), Tensor(v), is_causal=True
        )
    ref = _ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_sdpa_dropout_actually_drops():
    """dropout_p must change the output in training (was a silent no-op)."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    q, k, v = _rand_qkv(s=64)  # small seq -> einsum path
    out_nodrop = F.scaled_dot_product_attention(
        Tensor(q), Tensor(k), Tensor(v), dropout_p=0.0, training=True
    )
    out_drop = F.scaled_dot_product_attention(
        Tensor(q), Tensor(k), Tensor(v), dropout_p=0.5, training=True
    )
    diff = np.abs(np.asarray(out_drop._value) - np.asarray(out_nodrop._value)).max()
    assert diff > 1e-3, "attention dropout had no effect"
    # eval mode: dropout disabled
    out_eval = F.scaled_dot_product_attention(
        Tensor(q), Tensor(k), Tensor(v), dropout_p=0.5, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out_eval._value), np.asarray(out_nodrop._value), atol=1e-6
    )
