"""Collectives recorded into static Programs (round-3 VERDICT missing #2).

Reference: the ``c_*`` collective op set recordable into a ProgramDesc
(``operators/collective/c_allreduce_op.h:364``, fleet's static
sharding/pipeline optimizers inserting collectives into blocks). Here a
collective called on a static ``Variable`` records a program op whose
replay is the same one-op shard_map the eager path runs — so Executor
replay, append_backward, and save_inference_model all carry the
communication. Conventions match the eager single-controller model:
tensors are stacked along dim0 over the group axis.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.static as static
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.framework.tensor import Tensor

N_DEV = 8


def _hybrid_groups():
    mesh = build_mesh({"dp": 4, "mp": 2})
    return coll.Group(mesh, "dp", gid=101), coll.Group(mesh, "mp", gid=102)


def test_allreduce_records_and_replays():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [N_DEV, 4], "float32")
        out = dist.all_reduce(x)
    assert any(op.op_name.startswith("c_allreduce") for op in main.ops)

    exe = static.Executor()
    x_np = np.random.RandomState(0).randn(N_DEV, 4).astype(np.float32)
    (got,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])
    want = np.broadcast_to(x_np.sum(0, keepdims=True), x_np.shape)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_broadcast_and_reduce_scatter_record():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [N_DEV, 4], "float32")
        b = dist.broadcast(x, src=2)
        y = static.data("y", [N_DEV, N_DEV], "float32")
        rs = dist.reduce_scatter(y)
    names = [op.op_name for op in main.ops]
    assert "c_broadcast" in names and "c_reducescatter" in names

    exe = static.Executor()
    rng = np.random.RandomState(1)
    x_np = rng.randn(N_DEV, 4).astype(np.float32)
    y_np = rng.randn(N_DEV, N_DEV).astype(np.float32)
    got_b, got_rs = exe.run(main, feed={"x": x_np, "y": y_np},
                            fetch_list=[b, rs])
    np.testing.assert_allclose(
        got_b, np.broadcast_to(x_np[2:3], x_np.shape), rtol=1e-5)
    # eager parity for the stacked reduce_scatter convention
    t = Tensor(jnp.asarray(y_np))
    dist.reduce_scatter(t)
    np.testing.assert_allclose(got_rs, np.asarray(t._value), rtol=1e-5)


def test_static_dp_tp_train_program_parity_and_save(tmp_path):
    """A DP+TP train program on the hybrid dp4 x mp2 mesh: TP rowsum
    all_reduce in forward, append_backward, DP all_reduce on the weight
    grad — loss and synced grads match the hand-computed reference, and
    save_inference_model round-trips the collective."""
    gdp, gmp = _hybrid_groups()
    rng = np.random.RandomState(2)
    xs_np = rng.randn(2, 4, 16).astype(np.float32)   # mp-stacked partials
    t_np = rng.randn(4, 8).astype(np.float32)

    main = static.Program()
    with static.program_guard(main):
        xs = static.data("xs", [2, 4, 16], "float32")
        lin = paddle.nn.Linear(16, 8, bias_attr=False)
        # row-parallel TP: each mp rank holds a partial activation; the
        # rowsum all_reduce completes the matmul
        part = lin(xs)                                # [2, 4, 8] partials
        full = dist.all_reduce(part, group=gmp)       # mp rowsum
        y = full[0]                                   # any mp replica
        loss = (y - paddle.to_tensor(t_np)).pow(2).mean()
        pairs = static.append_backward(loss)
        (w, gw), = pairs
        gw_sync = dist.all_reduce(gw, group=gdp)      # DP grad sync
    w_np = np.asarray(w._value)

    exe = static.Executor()
    loss_v, gw_v = exe.run(main, feed={"xs": xs_np},
                           fetch_list=[loss, gw_sync])

    # hand-computed reference (same math, plain numpy)
    part_ref = xs_np @ w_np
    y_ref = part_ref.sum(0)
    loss_ref = ((y_ref - t_np) ** 2).mean()
    dy = 2.0 * (y_ref - t_np) / t_np.size
    # d loss/d w through both mp partials, then DP sum = 4x row blocks...
    gw_ref = sum(xs_np[i].T @ dy for i in range(2))
    # DP all_reduce over dim0 blocks of the [16, 8] grad: each 4-row block
    # becomes the sum of all four blocks (stacked-global convention)
    blocks = gw_ref.reshape(4, 4, 8).sum(0)
    gw_ref_sync = np.tile(blocks, (4, 1))
    np.testing.assert_allclose(loss_v, loss_ref, rtol=1e-5)
    np.testing.assert_allclose(gw_v, gw_ref_sync, rtol=1e-4, atol=1e-5)

    # serialization round-trip keeps the in-forward collective: the
    # exported artifact is an 8-device program, so the caller presents
    # mesh-placed inputs (exactly how a multi-chip serving job would)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    path = str(tmp_path / "dp_tp_model")
    static.save_inference_model(path, [xs], [y], program=main)
    loaded, _, _ = static.load_inference_model(path)
    xs_dev = jax.device_put(jnp.asarray(xs_np),
                            NamedSharding(gmp.mesh, P()))
    out = loaded(xs_dev)
    np.testing.assert_allclose(np.asarray(out), y_ref, rtol=1e-5)


def test_allgather_identity_recorded():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [N_DEV, 4], "float32")
        out = dist.all_gather(x)
    assert any(op.op_name == "c_allgather" for op in main.ops)
    exe = static.Executor()
    x_np = np.random.RandomState(3).randn(N_DEV, 4).astype(np.float32)
    (got,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])
    np.testing.assert_allclose(got, x_np, rtol=1e-6)


def test_optimizer_consumes_synced_grad():
    """Review regression: when a grad-sync collective rebinds the @GRAD
    variable, the in-program optimizer must consume the SYNCED value."""
    from paddle_tpu.utils import unique_name

    g = coll.Group(build_mesh({"dp8": 8}), "dp8", gid=103)

    def run(sync):
        with unique_name.guard():
            paddle.seed(0)
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [8, 8], "float32")
                lin = paddle.nn.Linear(8, 8, bias_attr=False)
                loss = lin(x).pow(2).mean()
                opt = paddle.optimizer.SGD(learning_rate=1.0,
                                           parameters=lin.parameters())
                opt.minimize(loss)
                if sync:
                    (w,) = lin.parameters()
                    gv = main._grad_vars[w.name]
                    dist.all_reduce(gv, group=g)
            exe = static.Executor()
            x_np = np.random.RandomState(5).randn(8, 8).astype(np.float32)
            exe.run(main, feed={"x": x_np}, fetch_list=[loss])
            return np.asarray(lin.parameters()[0]._value)

    w_plain = run(False)
    w_sync = run(True)
    # the all_reduce sums 8 stacked row-blocks of the (8, 8) grad: the
    # synced update must differ from the raw one (and be finite)
    assert np.isfinite(w_sync).all()
    assert not np.allclose(w_plain, w_sync)


def test_shard_tensor_records_in_static_mode():
    """Review regression: shard_tensor on a static Variable must record
    through the Program (the eager in-place fast path would crash on a
    ShapeDtypeStruct)."""
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.auto_parallel import shard_tensor

    pm = ProcessMesh(np.arange(8), dim_names=["d"])
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 4], "float32")
        y = shard_tensor(x, process_mesh=pm, shard_spec=["d", None])
    assert any(op.op_name == "shard_tensor" for op in main.ops)
    exe = static.Executor()
    x_np = np.random.RandomState(6).randn(8, 4).astype(np.float32)
    (got,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
    np.testing.assert_allclose(got, x_np, rtol=1e-6)


def test_static_zero_stage1_shards_optimizer_state():
    """Round-5 VERDICT item 6: ZeRO stage-1 for static Programs — the
    registered optimizer's accumulators materialize sharded over the
    sharding group's axis (1/nranks per device) and the training update
    matches the unsharded replay exactly.
    Reference: fleet/meta_optimizers/sharding_optimizer.py:46."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.utils import unique_name

    g = coll.Group(build_mesh({"sh": 8}), "sh", gid=104)
    x_np = np.random.RandomState(7).randn(8, 16).astype(np.float32)

    def run(shard):
        with unique_name.guard():
            paddle.seed(0)
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [8, 16], "float32")
                lin = paddle.nn.Linear(16, 8, bias_attr=False)
                loss = lin(x).pow(2).mean()
                opt = paddle.optimizer.Adam(learning_rate=0.1,
                                            parameters=lin.parameters())
                opt.minimize(loss)
            if shard:
                static.shard_static_optimizer(main, group=g)
            exe = static.Executor()
            for _ in range(2):
                exe.run(main, feed={"x": x_np}, fetch_list=[loss])
            return lin.parameters()[0], opt

    w_plain, _ = run(False)
    w_shard, opt = run(True)
    # identical math under the sharded placement
    np.testing.assert_allclose(np.asarray(w_shard._value),
                               np.asarray(w_plain._value),
                               rtol=1e-5, atol=1e-6)
    # moments really live sharded: 1/8 of the (16, 8) moment per device
    m = opt._accumulators["moment1"][opt._pkey(w_shard)]
    assert m.sharding.spec != P(), m.sharding
    local = m.addressable_shards[0].data
    assert local.size == m.size // 8, (local.shape, m.shape)


def test_static_zero_stage1_requires_minimize():
    main = static.Program()
    with pytest.raises(ValueError, match="no registered optimizer"):
        static.shard_static_optimizer(main)
