"""Jitted SPMD pipeline-schedule parity tests (8-device CPU mesh).

Mirrors the reference hybrid-parallel PP tests
(``unittests/hybrid_parallel_pp_transformer.py``): the pipelined model must
produce the same losses and updates as the plain single-mesh model.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.utils import unique_name

from capability import requires_spmd_partition_id


def _cfg(layers=4, vocab=128, hidden=64, heads=4, seq=32):
    return GPTConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=heads, max_position_embeddings=max(64, seq),
        hidden_dropout=0.0, attention_dropout=0.0,
    )


def _init_fleet(dp=1, mp=1, pp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = dp
    strategy.hybrid_configs["mp_degree"] = mp
    strategy.hybrid_configs["pp_degree"] = pp
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _copy_gpt_into_pipeline(model, piped, pp, per):
    """Copy a GPTForCausalLM's weights into the pipelined twin."""
    import jax.numpy as jnp

    src_emb = model.gpt.embeddings.state_dict()
    piped.pre.set_state_dict(src_emb)
    piped.post.ln_f.set_state_dict(model.gpt.ln_f.state_dict())
    # stacked decoder params: stack layer i of each stage chunk
    tmpl_names = [n for n, _ in piped._template.named_parameters()]
    layers = list(model.gpt.layers)
    for sp, name in zip(piped._stacked, tmpl_names):
        idx, sub = name.split(".", 1)
        per_stage = []
        for s in range(pp):
            lay = layers[s * per + int(idx)]
            per_stage.append(dict(lay.named_parameters())[sub]._value)
        sp._value = jnp.stack(per_stage).astype(sp._value.dtype)
    return piped


def _loss_of(model, ids, labels):
    logits = model(ids)
    return F.cross_entropy(
        logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1, 1])
    ).mean()


@pytest.mark.parametrize("dp,mp,pp,micro", [
    (1, 1, 2, 2),
    (1, 1, 4, 4),
    # dp/mp auto axes alongside the pp-manual shard_map emit PartitionId,
    # which not every SPMD backend can place (capability-probed skip)
    pytest.param(2, 2, 2, 2, marks=requires_spmd_partition_id()),
])
def test_pipelined_gpt_matches_single_device(dp, mp, pp, micro):
    from paddle_tpu.distributed.meta_parallel import build_pipelined_gpt
    from paddle_tpu.distributed.data_parallel import shard_batch

    hcg = _init_fleet(dp=dp, mp=mp, pp=pp)
    cfg = _cfg(layers=4)
    per = cfg.num_layers // pp

    with unique_name.guard():
        paddle.seed(0)
        ref = GPTForCausalLM(cfg)
    with unique_name.guard():
        paddle.seed(1)  # different init; weights are copied below
        piped = build_pipelined_gpt(cfg, hcg, num_microbatches=micro)
    _copy_gpt_into_pipeline(ref, piped, pp, per)

    rng = np.random.RandomState(0)
    batch = 4 * dp
    ids_np = rng.randint(0, cfg.vocab_size, (batch, 32)).astype(np.int64)
    ids = Tensor(ids_np)
    labels = Tensor(ids_np.copy())

    # ---- forward/loss parity
    ref_loss = float(np.asarray(_loss_of(ref, ids, labels)._value))
    pl = piped.loss(shard_batch(ids, hcg.get_data_parallel_group()),
                    shard_batch(labels, hcg.get_data_parallel_group()))
    pipe_loss = float(np.asarray(pl._value))
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=2e-5,
                               err_msg=f"loss parity dp={dp} mp={mp} pp={pp}")

    # ---- one SGD step parity (gradients flow through the pipeline)
    opt_ref = paddle.optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
    opt_pipe = paddle.optimizer.SGD(learning_rate=0.1, parameters=piped.parameters())

    loss = _loss_of(ref, ids, labels)
    loss.backward()
    opt_ref.step()
    opt_ref.clear_grad()

    pl = piped.loss(shard_batch(ids, hcg.get_data_parallel_group()),
                    shard_batch(labels, hcg.get_data_parallel_group()))
    pl.backward()
    opt_pipe.step()
    opt_pipe.clear_grad()

    # compare a first-stage decoder weight and the tied embedding
    ref_w = np.asarray(ref.gpt.layers[0].qkv_proj.weight._value, np.float32)
    name = [n for n, _ in piped._template.named_parameters()
            if n.endswith("qkv_proj.weight")][0]
    i = [n for n, _ in piped._template.named_parameters()].index(name)
    pipe_w = np.asarray(piped._stacked[i]._value[0], np.float32)
    np.testing.assert_allclose(pipe_w, ref_w, atol=2e-5, rtol=1e-4,
                               err_msg="stage-0 qkv weight after step")

    ref_e = np.asarray(ref.gpt.embeddings.word_embeddings.weight._value, np.float32)
    pipe_e = np.asarray(piped.pre.word_embeddings.weight._value, np.float32)
    np.testing.assert_allclose(pipe_e, ref_e, atol=2e-5, rtol=1e-4,
                               err_msg="tied embedding after step")


@requires_spmd_partition_id()
def test_pipelined_gpt_compiled_step_trains():
    """Full hybrid dp*mp*pp CompiledStep over the pipelined model: loss
    decreases and stays finite (the dryrun_multichip path)."""
    from paddle_tpu.distributed.meta_parallel import build_pipelined_gpt
    from paddle_tpu.distributed.data_parallel import shard_batch

    hcg = _init_fleet(dp=2, mp=2, pp=2)
    cfg = _cfg(layers=4)
    paddle.seed(0)
    piped = build_pipelined_gpt(cfg, hcg, num_microbatches=2)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=piped.parameters())

    def train_step(ids, labels):
        loss = piped.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[piped, opt], donate_state=True)
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64)
    dpg = hcg.get_data_parallel_group()
    losses = []
    for _ in range(4):
        loss = step(shard_batch(Tensor(ids_np), dpg),
                    shard_batch(Tensor(ids_np.copy()), dpg))
        losses.append(float(np.asarray(loss._value)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
