"""End-to-end dygraph training (BASELINE config 1: MNIST LeNet).
Mirrors reference book tests (``tests/book/test_recognize_digits.py`` idea):
loss must decrease and accuracy must beat chance on a learnable problem."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_mnist_training_loss_decreases():
    paddle.seed(0)
    np.random.seed(0)  # DataLoader shuffle order: decouple from prior tests
    train_ds = MNIST(mode="train")
    loader = DataLoader(train_ds, batch_size=64, shuffle=True, drop_last=True)
    model = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    losses = []
    it = iter(loader)
    for step in range(30):
        img, label = next(it)
        logits = model(img)
        loss = loss_fn(logits, label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    first = np.mean(losses[:5])
    last = np.mean(losses[-5:])
    assert last < first, f"loss did not decrease: {first} -> {last}"

    # eval accuracy on a training slice should beat chance by a wide margin
    model.eval()
    img, label = next(iter(DataLoader(train_ds, batch_size=256)))
    with paddle.no_grad():
        acc = paddle.metric.accuracy(model(img), label)
    assert float(acc) > 0.3, f"accuracy too low: {float(acc)}"


def test_sgd_momentum_training():
    paddle.seed(1)
    x = paddle.randn([128, 10])
    w_true = paddle.randn([10, 1])
    y = paddle.matmul(x, w_true) + 0.01 * paddle.randn([128, 1])

    lin = nn.Linear(10, 1)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=lin.parameters())
    for _ in range(50):
        loss = F.mse_loss(lin(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < 0.05


def test_lr_scheduler_integration():
    lin = nn.Linear(2, 2)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.1)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=lin.parameters())
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-9


def test_grad_clip_global_norm():
    lin = nn.Linear(4, 4)
    clip = nn.ClipGradByGlobalNorm(clip_norm=0.1)
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=lin.parameters(), grad_clip=clip)
    (lin(paddle.randn([8, 4])).sum() * 100).backward()
    pgs = [(p, p.grad) for p in lin.parameters()]
    clipped = clip(pgs)
    total = np.sqrt(sum(float((g.numpy() ** 2).sum()) for _, g in clipped))
    assert total <= 0.11


def test_save_load_roundtrip(tmp_path):
    model = LeNet()
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    model(paddle.randn([1, 1, 28, 28])).sum().backward()
    opt.step()
    paddle.save(model.state_dict(), str(tmp_path / "model.pdparams"))
    paddle.save(opt.state_dict(), str(tmp_path / "opt.pdopt"))

    model2 = LeNet()
    model2.set_state_dict(paddle.load(str(tmp_path / "model.pdparams")))
    for (n1, p1), (n2, p2) in zip(model.named_parameters(), model2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), err_msg=n1)

    opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
    opt2.set_state_dict(paddle.load(str(tmp_path / "opt.pdopt")))


def test_amp_autocast_o1():
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = paddle.matmul(a, b)
        assert str(c.dtype) == "bfloat16"
        s = F.softmax(c)  # blacklist -> fp32
        assert str(s.dtype) == "float32"
    c2 = paddle.matmul(a, b)
    assert str(c2.dtype) == "float32"


def test_grad_scaler_dynamics():
    lin = nn.Linear(2, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0, incr_every_n_steps=1)
    loss = lin(paddle.ones([1, 2])).sum()
    scaled = scaler.scale(loss)
    assert abs(float(scaled) - float(loss) * 128.0) < 1e-3
    scaled.backward()
    w_before = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    assert not np.allclose(lin.weight.numpy(), w_before)
    assert scaler.get_init_loss_scaling() == 256.0  # incr after 1 good step


def test_dataloader_workers_and_samplers():
    ds = MNIST(mode="test")
    loader = DataLoader(ds, batch_size=32, num_workers=2, shuffle=False)
    batches = list(loader)
    assert len(batches) == len(loader)
    img, label = batches[0]
    assert img.shape == [32, 1, 28, 28]
    # parity with single-process
    loader0 = DataLoader(ds, batch_size=32, num_workers=0, shuffle=False)
    img0, label0 = next(iter(loader0))
    np.testing.assert_allclose(img.numpy(), img0.numpy())
