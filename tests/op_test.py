"""OpTest-style numeric gradient checker.

Clone of the reference harness idea (``python/paddle/fluid/tests/unittests/
op_test.py:309`` — ``check_grad:1851`` compares analytic grads against
central-difference numeric grads via ``get_numeric_gradient:126``)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def numeric_grad(fn, inputs, idx, out_grad=None, delta=1e-3):
    """Central-difference gradient of sum(fn(*inputs) * out_grad) w.r.t inputs[idx]."""
    # note: jax->numpy arrays may be F-ordered; force C-contiguous copies so
    # in-place perturbation below actually lands in the evaluated array
    base = [np.ascontiguousarray(t.numpy(), dtype=np.float64) for t in inputs]

    def eval_at(vals):
        ts = [paddle.to_tensor(v.astype(np.float32)) for v in vals]
        out = fn(*ts)
        o = out.numpy().astype(np.float64)
        w = out_grad if out_grad is not None else np.ones_like(o)
        return float((o * w).sum())

    x = base[idx]
    g = np.zeros_like(x)
    for i in range(x.size):
        orig = x.flat[i]
        x.flat[i] = orig + delta
        fp = eval_at(base)
        x.flat[i] = orig - delta
        fm = eval_at(base)
        x.flat[i] = orig
        g.flat[i] = (fp - fm) / (2 * delta)
    return g


def analytic_grads(fn, tensors):
    """Forward + backward once; returns the list of input gradients (fp64
    numpy). Gradient seed is ones in the output dtype."""
    out = fn(*tensors)
    out.backward(paddle.ones(out.shape, out.dtype))
    return [np.asarray(t.grad._value, dtype=np.float64) for t in tensors], out


def check_grad_lowp(fn, input_arrays, dtype="bfloat16", rtol=6e-2, atol=1e-2):
    """Low-precision gradient check (reference ``unittests/op_test.py:1851``
    per-dtype check_grad): run the op end-to-end in `dtype` and compare its
    analytic gradient against the fp32 analytic gradient evaluated at the
    SAME low-precision-representable input points. The fp32 analytic path is
    itself validated against finite differences by the fp32 sweep, so this
    chain checks exactly the low-precision computation error."""
    import ml_dtypes

    np_dt = ml_dtypes.bfloat16 if dtype == "bfloat16" else np.float16
    snapped = [np.asarray(a, dtype=np_dt).astype(np.float32)
               for a in input_arrays]
    assert all(np.isfinite(s).all() for s in snapped), \
        f"inputs not representable in {dtype}"

    ref_ts = [paddle.to_tensor(a, stop_gradient=False) for a in snapped]
    ref_grads, _ = analytic_grads(fn, ref_ts)

    lp_ts = [paddle.to_tensor(np.asarray(a, dtype=np_dt), stop_gradient=False)
             for a in snapped]
    lp_grads, out = analytic_grads(fn, lp_ts)

    for i, (lp, ref) in enumerate(zip(lp_grads, ref_grads)):
        np.testing.assert_allclose(
            lp, ref, rtol=rtol, atol=atol,
            err_msg=(f"{dtype} gradient deviates from fp32 reference for "
                     f"input {i} of {getattr(fn, '__name__', fn)}"),
        )
    return out


def check_grad(fn, input_arrays, rtol=1e-2, atol=1e-3, delta=1e-3, out_grad=None):
    """Compare analytic backward() grads to finite differences for all inputs."""
    tensors = [paddle.to_tensor(a.astype(np.float32), stop_gradient=False) for a in input_arrays]
    out = fn(*tensors)
    if out_grad is not None:
        out.backward(paddle.to_tensor(out_grad.astype(np.float32)))
    else:
        seed = paddle.ones(out.shape, out.dtype)
        out.backward(seed)
    for i, t in enumerate(tensors):
        ng = numeric_grad(fn, tensors, i, out_grad=out_grad, delta=delta)
        ag = t.grad.numpy().astype(np.float64)
        np.testing.assert_allclose(
            ag, ng, rtol=rtol, atol=atol,
            err_msg=f"gradient mismatch for input {i} of {getattr(fn, '__name__', fn)}",
        )
    return out
