"""In-kernel flash-attention dropout — TPU-hardware tests.

The keep mask comes from the TPU hardware PRNG (``pltpu.prng_seed``), which
has no interpret-mode lowering, so these tests need a real (compiled) TPU
backend; under the CPU suite they skip. Run manually on the chip:

    PYTHONPATH=/root/.axon_site:/root/repo python -m pytest \
        tests/test_flash_dropout_tpu.py -q -p no:cacheprovider

Validation strategy (the mask never leaves VMEM, so tests treat the kernel
as a deterministic function of its seed):
  * same seed -> bit-identical output; different seed -> different output
  * E_seed[output] ~= no-dropout output  (dropout is unbiased)
  * effect magnitude matches the rate (output != no-dropout for p>0)
  * autodiff gradients vs central finite differences of the SAME seeded
    function for q, k, v — this exercises the dq and dk/dv kernels' mask
    regeneration and the dS = P(dP.M/keep - delta) recurrence.

Reference capability: in-kernel curand dropout in
``paddle/fluid/operators/fused/fused_attention_op.cu``.
"""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="in-kernel dropout needs the TPU hardware PRNG",
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fa(**kw):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    return flash_attention(block_q=128, block_k=128, interpret=False, **kw)


def _inputs(b=1, h=2, s=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, h, d), dtype)
    v = jax.random.normal(ks[2], (b, s, h, d), dtype)
    return q, k, v


def test_deterministic_given_seed():
    q, k, v = _inputs()
    seed = jnp.array([123, 456], jnp.int32)
    o1 = _fa(q=q, k=k, v=v, dropout_p=0.2, dropout_seed=seed)
    o2 = _fa(q=q, k=k, v=v, dropout_p=0.2, dropout_seed=seed)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = _fa(q=q, k=k, v=v, dropout_p=0.2,
             dropout_seed=jnp.array([124, 456], jnp.int32))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))


def test_dropout_unbiased_mean():
    q, k, v = _inputs()
    base = np.asarray(_fa(q=q, k=k, v=v, dropout_p=0.0))
    n = 96
    acc = np.zeros_like(base, np.float64)
    run = jax.jit(lambda s: _fa(q=q, k=k, v=v, dropout_p=0.3, dropout_seed=s))
    for i in range(n):
        o = np.asarray(run(jnp.array([i, 9000 + i], jnp.int32)))
        assert not np.allclose(o, base), "p=0.3 must perturb the output"
        acc += o
    mean = acc / n
    # measured scaling on v5e: err 0.091@n=48, 0.066@n=96, 0.046@n=192 —
    # the clean 1/sqrt(n) of an unbiased estimator
    err = np.abs(mean - base).mean() / (np.abs(base).mean() + 1e-9)
    assert err < 0.08, err


@pytest.mark.parametrize("wrt", [0, 1, 2])
def test_grad_matches_finite_difference(wrt):
    # small shapes keep central differences affordable on-chip
    q, k, v = _inputs(b=1, h=1, s=128, d=64)
    seed = jnp.array([77, 88], jnp.int32)
    co = jax.random.normal(jax.random.key(3), q.shape, jnp.float32)

    def f(*args):
        out = _fa(q=args[0], k=args[1], v=args[2], dropout_p=0.25,
                  dropout_seed=seed, causal=True)
        return jnp.vdot(out, co)

    args = [q, k, v]
    g = jax.grad(f, argnums=wrt)(*args)
    g = np.asarray(g)

    rng = np.random.RandomState(0)
    x = np.asarray(args[wrt])
    eps = 1e-2
    for _ in range(6):
        idx = tuple(rng.randint(0, dim) for dim in x.shape)
        e = np.zeros_like(x)
        e[idx] = eps
        hi = [a if i != wrt else jnp.asarray(x + e) for i, a in enumerate(args)]
        lo = [a if i != wrt else jnp.asarray(x - e) for i, a in enumerate(args)]
        fd = (float(f(*hi)) - float(f(*lo))) / (2 * eps)
        assert abs(fd - g[idx]) < 2e-2 + 0.05 * abs(fd), (idx, fd, g[idx])


@pytest.mark.parametrize("wrt", [0, 1, 2])
def test_packed_grad_matches_finite_difference(wrt):
    """Packed-kernel dropout: fwd and bwd MUST re-tile identically (the
    PRNG mask depends on tile index and shape) — this FD check fails if
    bwd_block were allowed to diverge from the forward blocks."""
    from paddle_tpu.ops.pallas.flash_attention_packed import (
        flash_attention_packed,
    )

    b, s, h, d = 1, 256, 2, 64
    ks = jax.random.split(jax.random.key(11), 3)
    args = [jax.random.normal(k_, (b, s, h * d), jnp.float32) for k_ in ks]
    seed = jnp.array([55, 66], jnp.int32)
    co = jax.random.normal(jax.random.key(4), args[0].shape, jnp.float32)

    def f(*a):
        out = flash_attention_packed(
            a[0], a[1], a[2], h, causal=True, dropout_p=0.25,
            dropout_seed=seed, block_q=256, block_k=256, bwd_block=128,
            interpret=False)
        return jnp.vdot(out, co)

    g = np.asarray(jax.grad(f, argnums=wrt)(*args))
    rng = np.random.RandomState(1)
    x = np.asarray(args[wrt])
    eps = 1e-2
    for _ in range(6):
        idx = tuple(rng.randint(0, dim) for dim in x.shape)
        e = np.zeros_like(x)
        e[idx] = eps
        hi = [a if i != wrt else jnp.asarray(x + e) for i, a in enumerate(args)]
        lo = [a if i != wrt else jnp.asarray(x - e) for i, a in enumerate(args)]
        fd = (float(f(*hi)) - float(f(*lo))) / (2 * eps)
        assert abs(fd - g[idx]) < 2e-2 + 0.05 * abs(fd), (idx, fd, g[idx])


@pytest.mark.parametrize("wrt", [0, 2])
def test_packed_canonical_units_grad_fd(wrt):
    """Flagship tiling with dropout: fwd at 1024 single-k tiles, bwd at
    512 — the canonical 512x512 dropout units must give both the SAME
    mask; a finite-difference check fails if they diverge."""
    from paddle_tpu.ops.pallas.flash_attention_packed import (
        flash_attention_packed,
    )

    b, s, h, d = 1, 1024, 2, 64
    ks = jax.random.split(jax.random.key(21), 3)
    args = [jax.random.normal(k_, (b, s, h * d), jnp.float32) * 0.3
            for k_ in ks]
    seed = jnp.array([7, 9], jnp.int32)
    co = jax.random.normal(jax.random.key(2), args[0].shape, jnp.float32)

    def f(*a):
        out = flash_attention_packed(
            a[0], a[1], a[2], h, causal=True, dropout_p=0.25,
            dropout_seed=seed, block_q=1024, block_k=1024, bwd_block=512,
            interpret=False)
        return jnp.vdot(out, co)

    g = np.asarray(jax.grad(f, argnums=wrt)(*args))
    rng = np.random.RandomState(3)
    x = np.asarray(args[wrt])
    eps = 1e-2
    for _ in range(4):
        idx = tuple(rng.randint(0, dim) for dim in x.shape)
        e = np.zeros_like(x)
        e[idx] = eps
        hi = [a if i != wrt else jnp.asarray(x + e) for i, a in enumerate(args)]
        lo = [a if i != wrt else jnp.asarray(x - e) for i, a in enumerate(args)]
        fd = (float(f(*hi)) - float(f(*lo))) / (2 * eps)
        assert abs(fd - g[idx]) < 2e-2 + 0.05 * abs(fd), (idx, fd, g[idx])


def test_sdpa_router_keeps_flash_with_dropout():
    """F.scaled_dot_product_attention with dropout>0 must stay on the flash
    path on a compiled TPU backend (round-3 VERDICT weak #2)."""
    import paddle_tpu  # noqa: F401  (registers flags)
    from paddle_tpu.nn.functional.attention import _flash_ok

    assert _flash_ok((8, 1024, 12, 64), (8, 1024, 12, 64), None, 0.1, True)
