"""ZeRO stages must actually shard memory (VERDICT weak #2): per-device
bytes of grads/accumulators/params shrink ~1/N, grads are sharded at
production, accumulators at creation, and offload= places optimizer state
in host memory. Reference: fleet/meta_parallel/sharding/group_sharded_*."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name

N = 8  # sharding degree = full virtual mesh


def _init_fleet():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 1
    strategy.hybrid_configs["sharding_degree"] = N
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _mlp():
    with unique_name.guard():
        paddle.seed(0)
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 16)
        )


def _shard_bytes(arr):
    return arr.addressable_shards[0].data.nbytes


def _total_bytes(arr):
    return arr.nbytes


def test_stage1_accumulators_sharded_at_creation():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os")

    x = Tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()

    checked = 0
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "ndim") and acc.ndim >= 1 and acc.shape[0] % N == 0:
                assert _shard_bytes(acc) == _total_bytes(acc) // N, acc.shape
                checked += 1
    assert checked >= 4  # moment1/moment2 for both weights at least


def test_stage2_grads_sharded_at_production():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g")

    x = Tensor(np.random.RandomState(1).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    # BEFORE any optimizer step: grads already sharded 1/N
    checked = 0
    for p in model.parameters():
        if p.grad is not None and p.shape[0] % N == 0:
            g = p.grad._value
            assert _shard_bytes(g) == _total_bytes(g) // N, p.name
            checked += 1
    assert checked >= 2


def test_stage3_params_sharded_at_rest():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")
    checked = 0
    for p in model.parameters():
        if p.shape[0] % N == 0:
            assert _shard_bytes(p._value) == _total_bytes(p._value) // N
            checked += 1
    assert checked >= 2
    # and the model still runs + trains
    x = Tensor(np.random.RandomState(2).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(np.asarray(loss._value)))


def test_sharding_survives_jitted_step():
    """Inside a CompiledStep the sharding constraints hold: post-step
    accumulators and grads-in-trace stay 1/N."""
    from paddle_tpu.jit.functionalize import CompiledStep

    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g")

    def step(x):
        loss = model(x).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[model, opt._inner_opt], donate_state=False)
    x = Tensor(np.random.RandomState(3).randn(8, 16).astype(np.float32))
    l0 = float(np.asarray(cs(x)._value))
    l1 = float(np.asarray(cs(x)._value))
    assert l1 < l0
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "ndim") and acc.ndim >= 1 and acc.shape[0] % N == 0:
                assert _shard_bytes(acc) == _total_bytes(acc) // N


def test_offload_places_optimizer_state_on_host():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g",
                                           offload=True)
    x = Tensor(np.random.RandomState(4).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    kinds = set()
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "sharding"):
                kinds.add(getattr(acc.sharding, "memory_kind", None))
    if "pinned_host" not in kinds:
        pytest.skip(f"backend has no host memory space (kinds={kinds})")


def test_stage2_parity_with_unsharded():
    """Sharded placement must not change the math."""
    _init_fleet()

    def run(level):
        net = _mlp()
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters())
        if level:
            net, opt, _ = group_sharded_parallel(net, opt, level=level)
        losses = []
        x = Tensor(np.random.RandomState(5).randn(8, 16).astype(np.float32))
        for _ in range(5):
            loss = net(x).square().mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._value)))
        return losses

    base = run(None)
    for level in ("os", "os_g", "p_g_os"):
        np.testing.assert_allclose(run(level), base, rtol=1e-5,
                                   err_msg=f"level={level}")
