"""ZeRO stages must actually shard memory (VERDICT weak #2): per-device
bytes of grads/accumulators/params shrink ~1/N, grads are sharded at
production, accumulators at creation, and offload= places optimizer state
in host memory. Reference: fleet/meta_parallel/sharding/group_sharded_*."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name

N = 8  # sharding degree = full virtual mesh


def _init_fleet():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 1
    strategy.hybrid_configs["sharding_degree"] = N
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _mlp():
    with unique_name.guard():
        paddle.seed(0)
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 16)
        )


def _shard_bytes(arr):
    return arr.addressable_shards[0].data.nbytes


def _total_bytes(arr):
    return arr.nbytes


def test_stage1_accumulators_sharded_at_creation():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os")

    x = Tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()

    checked = 0
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "ndim") and acc.ndim >= 1 and acc.shape[0] % N == 0:
                assert _shard_bytes(acc) == _total_bytes(acc) // N, acc.shape
                checked += 1
    assert checked >= 4  # moment1/moment2 for both weights at least


def test_stage2_grads_sharded_at_production():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g")

    x = Tensor(np.random.RandomState(1).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    # BEFORE any optimizer step: grads already sharded 1/N
    checked = 0
    for p in model.parameters():
        if p.grad is not None and p.shape[0] % N == 0:
            g = p.grad._value
            assert _shard_bytes(g) == _total_bytes(g) // N, p.name
            checked += 1
    assert checked >= 2


def test_stage3_params_sharded_at_rest():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")
    checked = 0
    for p in model.parameters():
        if p.shape[0] % N == 0:
            assert _shard_bytes(p._value) == _total_bytes(p._value) // N
            checked += 1
    assert checked >= 2
    # and the model still runs + trains
    x = Tensor(np.random.RandomState(2).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(np.asarray(loss._value)))


def test_sharding_survives_jitted_step():
    """Inside a CompiledStep the sharding constraints hold: post-step
    accumulators and grads-in-trace stay 1/N."""
    from paddle_tpu.jit.functionalize import CompiledStep

    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g")

    def step(x):
        loss = model(x).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[model, opt._inner_opt], donate_state=False)
    x = Tensor(np.random.RandomState(3).randn(8, 16).astype(np.float32))
    l0 = float(np.asarray(cs(x)._value))
    l1 = float(np.asarray(cs(x)._value))
    assert l1 < l0
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "ndim") and acc.ndim >= 1 and acc.shape[0] % N == 0:
                assert _shard_bytes(acc) == _total_bytes(acc) // N


def test_offload_places_optimizer_state_on_host():
    _init_fleet()
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="os_g",
                                           offload=True)
    x = Tensor(np.random.RandomState(4).randn(8, 16).astype(np.float32))
    loss = model(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    kinds = set()
    for store in opt._accumulators.values():
        for acc in store.values():
            if hasattr(acc, "sharding"):
                kinds.add(getattr(acc.sharding, "memory_kind", None))
    if "pinned_host" not in kinds:
        pytest.skip(f"backend has no host memory space (kinds={kinds})")


def _gpt2ish():
    """Real-vocab shapes (round-3 VERDICT weak #3): the 50257-row embedding
    is NOT divisible by N=8 on dim0 — the placement must shard its hidden
    dim instead of silently replicating 154 MB of fp32 Adam state."""
    with unique_name.guard():
        paddle.seed(0)
        return paddle.nn.Sequential(
            paddle.nn.Embedding(50257, 64),
            paddle.nn.Linear(64, 64),
            paddle.nn.LayerNorm(64),
        )


def _every_array_sharded(arrs, names):
    """Every array with ANY N-divisible dim must occupy exactly 1/N bytes
    per device; only no-divisible-dim stragglers may replicate."""
    checked = replicated = 0
    for arr, name in zip(arrs, names):
        if not hasattr(arr, "ndim") or arr.ndim == 0 or arr.size < N:
            continue  # beta-pow style scalars: nothing to shard
        if any(s % N == 0 and s > 0 for s in arr.shape):
            assert _shard_bytes(arr) == _total_bytes(arr) // N, (name, arr.shape)
            checked += 1
        else:
            replicated += 1
    return checked, replicated


def test_zero_gpt2_vocab_shapes_fully_shard():
    _init_fleet()
    net = _gpt2ish()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, level="p_g_os")

    # stage-3: every param sharded — INCLUDING the (50257, 64) embedding
    arrs = [p._value for p in model.parameters()]
    names = [p.name for p in model.parameters()]
    checked, replicated = _every_array_sharded(arrs, names)
    assert checked == len(arrs) and replicated == 0

    ids = Tensor(np.random.RandomState(0).randint(0, 50257, (4, 8)))
    loss = model(ids).square().mean()
    loss.backward()

    # stage-2: every grad sharded at production (embedding grad included)
    grads = [p.grad._value for p in model.parameters() if p.grad is not None]
    checked, replicated = _every_array_sharded(grads, names)
    assert checked == len(grads) and replicated == 0

    opt.step()
    opt.clear_grad()

    # stage-1: every Adam accumulator sharded (moment1/2 of the embedding
    # are the arrays whose replication the old dim0-only policy hid)
    accs, anames = [], []
    for aname, store in opt._accumulators.items():
        for key, acc in store.items():
            accs.append(acc)
            anames.append(f"{aname}/{key}")
    checked, replicated = _every_array_sharded(accs, anames)
    assert checked == 10 and replicated == 0  # moment1+2 for all 5 params
    emb_m1 = opt._accumulators["moment1"][model.parameters()[0].name]
    assert emb_m1.shape == (50257, 64)
    assert _shard_bytes(emb_m1) == _total_bytes(emb_m1) // N


def test_stage2_parity_with_unsharded():
    """Sharded placement must not change the math."""
    _init_fleet()

    def run(level):
        net = _mlp()
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters())
        if level:
            net, opt, _ = group_sharded_parallel(net, opt, level=level)
        losses = []
        x = Tensor(np.random.RandomState(5).randn(8, 16).astype(np.float32))
        for _ in range(5):
            loss = net(x).square().mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._value)))
        return losses

    base = run(None)
    for level in ("os", "os_g", "p_g_os"):
        np.testing.assert_allclose(run(level), base, rtol=1e-5,
                                   err_msg=f"level={level}")


def test_group_sharded_preserves_tp_placements():
    """Review regression: ZeRO over the data axis must not re-replicate a
    parameter deliberately sharded over another mesh axis (the planner's
    tensor-parallel placements compose with ZeRO)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.collective import Group

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "mp"))
    g = Group(mesh, "dp", gid=151)
    net = paddle.nn.Linear(8, 16, bias_attr=False)
    w = net.parameters()[0]
    w._value = jax.device_put(w._value, NamedSharding(mesh, P(None, "mp")))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    net2, opt2, _ = group_sharded_parallel(net, opt, level="os_g", group=g)
    assert net2.parameters()[0]._value.sharding.spec == P(None, "mp")
