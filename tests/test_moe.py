"""MoE stack tests (ADVICE round-2: moe_layer shipped without coverage):
dense-loop parity vs the dispatched-einsum path, capacity-drop behavior,
aux-loss value, gradient flow through gate and experts.
Reference: incubate/distributed/models/moe/moe_layer.py:244, moe/gate/."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.incubate.distributed.models.moe import MoELayer
from paddle_tpu.utils import unique_name


def _experts(n, d, seed=0):
    with unique_name.guard():
        paddle.seed(seed)
        return [paddle.nn.Sequential(paddle.nn.Linear(d, 2 * d),
                                     paddle.nn.ReLU(),
                                     paddle.nn.Linear(2 * d, d))
                for _ in range(n)]


def _np(t):
    return np.asarray(t._value)


def test_naive_gate_dense_parity():
    """top-1 gate with generous capacity == dense per-expert loop."""
    d, n_exp, tokens = 8, 4, 16
    experts = _experts(n_exp, d)
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "naive"},
                   capacity_factor=float(n_exp))  # no drops
    x = Tensor(np.random.RandomState(0).randn(tokens, d).astype(np.float32))
    out = moe(x)

    # dense reference: route each token to argmax expert, scale by softmax prob
    logits = _np(moe.gate.logits(x))
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    top = logits.argmax(-1)
    ref = np.zeros((tokens, d), np.float32)
    for t in range(tokens):
        e = int(top[t])
        y = experts[e](Tensor(_np(x)[t:t + 1]))
        ref[t] = _np(y)[0] * probs[t, e]
    np.testing.assert_allclose(_np(out), ref, atol=1e-5)


def test_capacity_drop():
    """With capacity 1 token/expert, overflow tokens produce zero output."""
    d, n_exp = 4, 2
    experts = _experts(n_exp, d, seed=1)
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "naive"})
    # force tiny capacity
    moe.gate.capacity = lambda num_tokens, k=1: 1
    x = Tensor(np.random.RandomState(1).randn(8, d).astype(np.float32))
    out = _np(moe(x))
    zero_rows = (np.abs(out).sum(-1) < 1e-7).sum()
    # 8 tokens, 2 experts x capacity 1 -> at least 6 dropped
    assert zero_rows >= 6, zero_rows


def test_gshard_aux_loss_value_and_balance():
    """aux loss == num_experts * sum(me * ce) (GShard eq.); uniform routing
    gives ~1.0, concentrated routing gives ~num_experts."""
    d, n_exp, tokens = 6, 3, 300
    experts = _experts(n_exp, d, seed=2)
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "gshard"})
    x = Tensor(np.random.RandomState(2).randn(tokens, d).astype(np.float32))
    moe(x)
    aux = float(_np(moe.aux_loss))
    assert 0.5 < aux < float(n_exp) + 0.5, aux

    # concentrated: bias the gate so everything routes to expert 0
    w = moe.gate.parameters()[0]
    wv = _np(w).copy()
    wv[:, 0] += 50.0
    w._value = wv
    # positive inputs so the +50 weight column dominates every logit
    x = Tensor(np.abs(np.random.RandomState(2).randn(tokens, d)).astype(np.float32))
    moe(x)
    aux_conc = float(_np(moe.aux_loss))
    assert aux_conc > aux, (aux_conc, aux)
    np.testing.assert_allclose(aux_conc, float(n_exp), rtol=0.05)


def test_gradients_flow_through_gate_and_experts():
    d, n_exp = 6, 2
    experts = _experts(n_exp, d, seed=3)
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "gshard"})
    params = moe.parameters()
    x = Tensor(np.random.RandomState(3).randn(12, d).astype(np.float32),
               stop_gradient=False)
    out = moe(x)
    loss = (out * out).mean() + 0.01 * moe.aux_loss
    loss.backward()
    assert x.grad is not None
    got_grad = sum(
        1 for p in params
        if p.grad is not None and float(np.abs(_np(p.grad)).sum()) > 0
    )
    # the gate weight and the stacked expert weights all get gradients
    assert got_grad >= len(params) - 1, (got_grad, len(params))


def test_moe_trains_in_jitted_step():
    from paddle_tpu.jit.functionalize import CompiledStep

    d = 4
    experts = _experts(2, d, seed=4)
    moe = MoELayer(d_model=d, experts=experts, gate={"type": "switch"})
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=moe.parameters())
    x = Tensor(np.random.RandomState(4).randn(16, d).astype(np.float32))

    def step(xb):
        out = moe(xb)
        loss = (out - 1.0).square().mean() + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[moe, opt])
    l0 = float(_np(cs(x)))
    for _ in range(6):
        l1 = float(_np(cs(x)))
    assert np.isfinite(l1) and l1 < l0
