"""Auto-parallel planner (round-5 VERDICT item 5): degree search from the
alpha-beta cost model, per-param placements, Engine(strategy=None) wiring,
and a measured best-vs-worst check on the CPU mesh.
Reference: auto_parallel/planner.py:829, auto_parallel/cost_model.py:192."""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import ChipSpec, Planner

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def _wide_ffn_stats(batch=8):
    """Huge weights, tiny activations: TP should win (dp's grad all-reduce
    dwarfs mp's activation all-reduce)."""
    return {
        "step_flops": 1e12,
        "param_bytes": 2e9,
        "opt_state_bytes": 4e9,
        "act_bytes": 1e7,
        "layers": 1,
        "batch": batch,
        "mp_divisible": 8,
    }


def _small_model_stats(batch=64):
    """Tiny weights, big batch/activations: pure dp should win."""
    return {
        "step_flops": 1e11,
        "param_bytes": 1e6,
        "opt_state_bytes": 2e6,
        "act_bytes": 1e8,
        "layers": 1,
        "batch": batch,
        "mp_divisible": 8,
    }


def test_planner_picks_mp_for_wide_ffn():
    plan = Planner(8, _wide_ffn_stats()).plan()
    assert plan.mp >= 2, plan.degrees


def test_planner_picks_pure_dp_for_small_model():
    plan = Planner(8, _small_model_stats()).plan()
    assert plan.degrees == dict(dp=8, mp=1, pp=1, sharding=1), plan.degrees


def test_planner_memory_forces_sharding():
    """When replicated optimizer state overflows HBM, only ZeRO plans are
    feasible and the planner must emit one."""
    stats = _small_model_stats(batch=64)
    stats["param_bytes"] = 6e9
    stats["opt_state_bytes"] = 12e9   # >16 GB replicated: infeasible
    stats["act_bytes"] = 1e8
    plan = Planner(8, stats).plan()
    assert plan.feasible
    assert plan.sharding > 1 or plan.mp > 1, plan.degrees
    assert plan.est_device_bytes <= ChipSpec().hbm_bytes


def test_planner_respects_divisibility_and_batch():
    stats = _wide_ffn_stats(batch=4)
    stats["mp_divisible"] = 4          # mp limited to {1, 2, 4}
    stats.pop("param_shapes", None)
    plans = Planner(8, stats).enumerate_plans()
    assert plans                       # satisfiable: e.g. mp=2, dp*sh=4
    assert all(p.mp in (1, 2, 4) for p in plans)
    assert all(p.dp * p.sharding <= 4 for p in plans)
    # batch=4 with mp<=4 forbids dp*sh=8, so every plan uses mp>1
    assert all(p.mp > 1 for p in plans)


def test_planner_raises_when_nothing_fits_hbm():
    stats = _small_model_stats(batch=64)
    stats["param_bytes"] = 100e9       # 100 GB of params: hopeless at n=8
    stats["opt_state_bytes"] = 200e9
    with pytest.raises(ValueError, match="HBM"):
        Planner(8, stats).plan()


def test_planner_param_shapes_allow_mp_despite_odd_head():
    """A small odd classifier head must not disable mp for a model whose
    bytes are dominated by mp-divisible matrices (review regression)."""
    stats = _wide_ffn_stats()
    stats["param_shapes"] = [
        (64 * 8192 * 4, (64, 8192)), (8192 * 64 * 4, (8192, 64)),
        (8192 * 10 * 4, (8192, 10)),   # odd head: would gcd down to 2
    ]
    plan = Planner(8, stats).plan()
    assert plan.mp >= 2, plan.degrees


def test_engine_auto_plan_falls_back_when_unplannable():
    """Engine(strategy=None) must keep the legacy replicated/dp behavior
    (not crash) when no factorization fits the batch (review regression)."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.io import TensorDataset

    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(6, 10))  # gcd 2, odd dims
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    xs = np.random.RandomState(0).randn(9, 6).astype(np.float32)
    ys = np.zeros((9, 10), np.float32)
    ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
    eng = Engine(model=model, loss=paddle.nn.MSELoss(), optimizer=opt)
    with pytest.warns(UserWarning, match="no applicable plan"):
        hist = eng.fit(ds, batch_size=3, epochs=1)["loss"]
    assert eng.plan_ is None
    assert all(np.isfinite(v) for v in hist)


def test_param_placements_shard_largest_divisible_dim():
    planner = Planner(8, _wide_ffn_stats())
    plan = planner.plan()
    placements = planner.param_placements(
        [("w1", (64, 8192)), ("w2", (8192, 64)), ("b", (8192,)),
         ("odd", (7, 13))], plan)
    assert placements["w1"] == [None, "mp"]
    assert placements["w2"] == ["mp", None]
    assert placements["b"] == [None]           # 1-D: replicated
    assert placements["odd"] == [None, None]   # nothing divisible


def _run_plan_measured(plan, iters=8):
    """Execute a 2-layer FFN train step under the plan's placements on the
    (dp·sharding, mp) mesh; returns min step seconds."""
    import time

    n = 8
    d, f, batch = 256, 32768, 8
    data_ways = plan.dp * plan.sharding
    mesh = Mesh(np.array(jax.devices()[:n]).reshape(data_ways, plan.mp),
                ("dp", "mp"))
    rng = np.random.RandomState(0)
    w1 = jnp_put(rng.randn(d, f).astype(np.float32) * 0.02, mesh,
                 P(None, "mp") if plan.mp > 1 else P())
    w2 = jnp_put(rng.randn(f, d).astype(np.float32) * 0.02, mesh,
                 P("mp", None) if plan.mp > 1 else P())
    x = jnp_put(rng.randn(batch, d).astype(np.float32), mesh, P("dp", None))

    def loss_fn(w1, w2, x):
        h = jax.nn.relu(x @ w1)
        y = h @ w2
        return (y * y).mean()

    @jax.jit
    def step(w1, w2, x):
        loss, (g1, g2) = jax.value_and_grad(loss_fn, (0, 1))(w1, w2, x)
        return w1 - 0.01 * g1, w2 - 0.01 * g2, loss

    w1, w2, loss = step(w1, w2, x)   # compile
    jax.block_until_ready(loss)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        w1, w2, loss = step(w1, w2, x)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    return float(np.min(times))


def jnp_put(a, mesh, spec):
    return jax.device_put(a, NamedSharding(mesh, spec))


def test_planner_choice_beats_worst_measured():
    """The planner's pick must beat the worst enumerated plan in MEASURED
    CPU-mesh step time (VERDICT done-criterion). The wide-FFN shape makes
    dp's 32 MB grad all-reduce the dominant cost, which both the model and
    the measurement agree on."""
    d, f = 256, 32768
    pbytes = (d * f + f * d) * 4.0
    stats = {
        "step_flops": 6.0 * 8 * (d * f + f * d),
        "param_bytes": pbytes,
        "opt_state_bytes": 2 * pbytes,
        "act_bytes": 8 * (d + f) * 4.0,
        "layers": 1,
        "batch": 8,
        "mp_divisible": int(np.gcd(d, f)),
    }
    planner = Planner(8, stats)
    plans = [p for p in planner.enumerate_plans()
             if p.feasible and p.pp == 1 and p.sharding == 1]
    best, worst = plans[0], plans[-1]
    assert best.degrees != worst.degrees
    t_best = _run_plan_measured(best)
    t_worst = _run_plan_measured(worst)
    assert t_best <= t_worst * 1.10, (
        f"planner pick {best.degrees} ({t_best*1e3:.2f} ms) not faster than "
        f"worst {worst.degrees} ({t_worst*1e3:.2f} ms)")


def test_engine_auto_plans_without_strategy():
    """Engine(strategy=None) on a multi-device mesh runs the planner on the
    first batch, applies the placements, and trains."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.io import DataLoader, TensorDataset

    d, f = 64, 4096
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(d, f), paddle.nn.ReLU(), paddle.nn.Linear(f, d))
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    loss = paddle.nn.MSELoss()

    xs = np.random.RandomState(0).randn(32, d).astype(np.float32)
    ys = np.zeros((32, d), np.float32)
    ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])

    eng = Engine(model=model, loss=loss, optimizer=opt)
    loader = DataLoader(ds, batch_size=8, shuffle=False, drop_last=True)
    hist = eng.fit(loader, epochs=2)["loss"]
    assert eng.plan_ is not None
    assert eng.plan_.dp * eng.plan_.mp * eng.plan_.sharding == 8
    assert all(np.isfinite(v) for v in hist)
    # same 4 batches each epoch: the second pass must be cheaper on average
    assert np.mean(hist[4:]) < np.mean(hist[:4])
