"""Comm-optimized data parallelism (distributed/sharding/zero.py):

* ``ShardedOptimizer`` — ZeRO cross-replica sharded weight update over the
  dp axis (reduce-scatter grads → update the local 1/dp shard → all-gather
  params) must match the replicated-Adam step's losses and cut per-replica
  optimizer-state bytes ~dp-fold;
* int8 collectives with per-block scales and error-feedback residuals —
  the EF telescoping identity makes the quantized stream unbiased over
  steps;
* checkpoint kill-and-resume round-trips the SHARDED optimizer state;
* the ``spmd-replicated-optimizer-state`` lint rule goes quiet under the
  sharded update, and the deliberate param all-gather is a declared
  reshard (no ``spmd-implicit-resharding`` error);
* ``Engine(zero_stage=...)`` / ``Model.prepare(zero=...)`` knobs wire the
  same wrapper.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.distributed.collective import Group
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.sharding import (
    ShardedOptimizer,
    int8_all_gather,
    int8_all_reduce,
    int8_reduce_scatter,
)
from paddle_tpu.distributed.sharding.zero import (
    dequantize_int8_block,
    quantize_int8_block,
)
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.utils import unique_name

DP = 8
FP32_RTOL = 1e-5   # XLA:CPU reduction scheduling wiggles the last ulp
INT8_RTOL = 2e-2   # quantized wire: looser, documented contract


def _mlp(seed=0):
    with unique_name.guard():
        paddle.seed(seed)
        return paddle.nn.Sequential(
            paddle.nn.Linear(16, 64), paddle.nn.ReLU(),
            paddle.nn.Linear(64, 16))


def _build(dp=DP, zero=True, quantize=None, seed=0, lr=1e-2):
    mesh = build_mesh({"dp": dp})
    net = _mlp(seed)
    rep = NamedSharding(mesh, P())
    for p in net.parameters():
        p._value = jax.device_put(p._value, rep)
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=net.parameters())
    stepper = (ShardedOptimizer(opt, axis="dp", mesh=mesh,
                                quantize=quantize) if zero else opt)

    def train_step(x, y):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        stepper.step()
        stepper.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[net, opt], donate_state=True)
    return mesh, net, opt, step


def _batches(mesh, n, seed=0, batch=16):
    sh = NamedSharding(mesh, P("dp", None))
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = jax.device_put(rng.randn(batch, 16).astype(np.float32), sh)
        y = jax.device_put(rng.randn(batch, 16).astype(np.float32), sh)
        out.append((Tensor(x), Tensor(y)))
    return out


def _losses(step, mesh, n=4, seed=0):
    return [float(np.asarray(step(x, y)._value))
            for x, y in _batches(mesh, n, seed)]


def _local_bytes(arr):
    if hasattr(arr, "sharding") and hasattr(arr.sharding, "shard_shape"):
        shape = arr.sharding.shard_shape(arr.shape)
    else:
        shape = arr.shape
    return int(np.prod(shape)) * arr.dtype.itemsize


def _acc_bytes(opt):
    return sum(_local_bytes(v) for store in opt._accumulators.values()
               for v in store.values())


# ---------------------------------------------------------------------------
# parity + state sharding
# ---------------------------------------------------------------------------

def test_fp32_zero_parity_with_replicated_adam():
    mesh, _, _, base = _build(zero=False)
    want = _losses(base, mesh)
    mesh, _, _, step = _build(zero=True)
    got = _losses(step, mesh)
    np.testing.assert_allclose(got, want, rtol=FP32_RTOL)


def test_int8_zero_parity_within_quantized_contract():
    mesh, _, _, base = _build(zero=False)
    want = _losses(base, mesh)
    mesh, _, _, step = _build(zero=True, quantize="int8")
    got = _losses(step, mesh)
    np.testing.assert_allclose(got, want, rtol=INT8_RTOL)


def test_optimizer_state_bytes_drop_dp_fold():
    mesh, _, base_opt, base = _build(zero=False)
    _losses(base, mesh, n=1)
    mesh, _, zero_opt, step = _build(zero=True)
    _losses(step, mesh, n=1)
    rep, shard = _acc_bytes(base_opt), _acc_bytes(zero_opt)
    # both Linear weights shard over dp; only the tiny biases (and the
    # scalar beta powers) stay replicated — the ratio lands near DP
    assert rep / shard > 0.8 * DP, (rep, shard)
    # every dp-divisible >=2-D accumulator is born sharded
    checked = 0
    for store in zero_opt._accumulators.values():
        for acc in store.values():
            if getattr(acc, "ndim", 0) >= 2 and acc.shape[0] % DP == 0:
                assert _local_bytes(acc) == acc.nbytes // DP, acc.shape
                checked += 1
    assert checked >= 4  # moment1/moment2 x both weights


# ---------------------------------------------------------------------------
# int8 collectives + error feedback
# ---------------------------------------------------------------------------

def test_int8_quantize_roundtrip_blockwise():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 300).astype(np.float32) * 3.0  # pads 300 -> 2 blocks
    q, scales = quantize_int8_block(x)
    assert q.dtype == jnp.int8 and q.shape == (4, 512)
    assert scales.shape == (4, 2)
    deq = np.asarray(dequantize_int8_block(q, scales, 300))
    assert deq.shape == x.shape
    # per-element error bounded by half a scale step
    bound = np.repeat(np.asarray(scales), 256, axis=-1)[:, :300] * 0.5 + 1e-7
    assert (np.abs(deq - x) <= bound).all()


def test_int8_error_feedback_unbiased_over_steps():
    """EF telescoping: with a CONSTANT input stream the naive quantizer's
    per-step rounding error accumulates linearly, while the residual-
    compensated stream's cumulative error stays bounded by ONE step's
    quantization error (sum_t dequant_t = sum_t x_t + r_0 - r_T)."""
    mesh = build_mesh({"dp": DP})
    g = Group(mesh, "dp")
    rng = np.random.RandomState(1)
    x = (rng.randn(DP, 96).astype(np.float32) * 2.0)
    true_step = np.asarray(x).sum(0)
    T = 30

    acc_ef = np.zeros_like(true_step)
    r = None
    for _ in range(T):
        out, r = int8_all_reduce(x, group=g, residual=r)
        acc_ef += np.asarray(out)
    # naive: same collective, residual thrown away every step
    out0, _ = int8_all_reduce(x, group=g)
    acc_naive = np.asarray(out0) * T

    err_ef = np.abs(acc_ef - true_step * T).max()
    err_naive = np.abs(acc_naive - true_step * T).max()
    one_step = np.abs(np.asarray(out0) - true_step).max()
    assert err_ef <= one_step * 2.0 + 1e-5, (err_ef, one_step)
    # the naive stream's bias grows ~T-fold; EF must beat it decisively
    assert err_ef < err_naive / 5.0, (err_ef, err_naive)
    # telescoping identity: what's missing is exactly the final residuals
    assert np.allclose(acc_ef + np.asarray(r).sum(0), true_step * T,
                       atol=1e-2)


def test_int8_reduce_scatter_and_all_gather_shapes():
    mesh = build_mesh({"dp": DP})
    g = Group(mesh, "dp")
    rng = np.random.RandomState(2)
    x = rng.randn(DP, DP * 4, 32).astype(np.float32)
    out, r = int8_reduce_scatter(x, group=g)
    assert out.shape == (DP * 4, 32) and r.shape == x.shape
    want = np.asarray(x).sum(0)
    assert np.abs(np.asarray(out) - want).max() < 0.2 * np.abs(want).max()

    shards = rng.randn(DP, 4, 32).astype(np.float32)
    gat, _ = int8_all_gather(shards, group=g)
    assert gat.shape == (DP * 4, 32)
    want = np.asarray(shards).reshape(DP * 4, 32)
    assert np.abs(np.asarray(gat) - want).max() < 0.1 * np.abs(want).max()


# ---------------------------------------------------------------------------
# checkpoint kill-and-resume round-trips sharded optimizer state
# ---------------------------------------------------------------------------

def test_checkpoint_resume_with_sharded_state_dp2(tmp_path):
    from paddle_tpu.fault import CheckpointManager

    dp = 2
    # uninterrupted reference: 5 straight steps
    mesh, _, _, step = _build(dp=dp, zero=True, seed=3)
    want = _losses(step, mesh, n=5, seed=7)

    # killed run: 3 steps, checkpoint, rebuild from scratch, 2 more
    mesh, net, opt, step = _build(dp=dp, zero=True, seed=3)
    first = _losses(step, mesh, n=3, seed=7)
    m = CheckpointManager(str(tmp_path / "ck"))
    m.save(3, {"model": net.state_dict(), "opt": opt.state_dict()})

    mesh2, net2, opt2, step2 = _build(dp=dp, zero=True, seed=99)
    loaded_step, payloads = m.load()
    assert loaded_step == 3
    net2.set_state_dict(payloads["model"])
    opt2.set_state_dict(payloads["opt"])
    # restore re-applies the accumulator transform: moments come back
    # SHARDED, not replicated
    resharded = 0
    for store in opt2._accumulators.values():
        for acc in store.values():
            if getattr(acc, "ndim", 0) >= 2 and acc.shape[0] % dp == 0:
                assert _local_bytes(acc) == acc.nbytes // dp, acc.shape
                resharded += 1
    assert resharded >= 4
    batches = _batches(mesh2, 5, seed=7)
    rest = [float(np.asarray(step2(x, y)._value)) for x, y in batches[3:]]
    np.testing.assert_allclose(first + rest, want, rtol=1e-4)


# ---------------------------------------------------------------------------
# lint contract: rule quiet under the sharded update, all-gather declared
# ---------------------------------------------------------------------------

def test_replicated_state_rule_quiet_and_gather_declared():
    mesh, _, _, step = _build(zero=True)
    x, y = _batches(mesh, 1)[0]
    report = analysis.lint_step(step, x, y, mesh=mesh,
                                config={"zero_min_bytes": 1024})
    assert not report.by_rule("spmd-replicated-optimizer-state")
    # the deliberate ZeRO param all-gather comes from a sharding-policy
    # module: priced, but never an implicit-resharding finding
    assert not report.by_rule("spmd-implicit-resharding")
    # the plain step DOES trip the rule with the same floor (the contrast
    # proves quiet-for-the-right-reason, not a broken rule)
    mesh, _, _, base = _build(zero=False)
    x, y = _batches(mesh, 1)[0]
    dirty = analysis.lint_step(base, x, y, mesh=mesh,
                               config={"zero_min_bytes": 1024})
    assert dirty.by_rule("spmd-replicated-optimizer-state")


# ---------------------------------------------------------------------------
# Engine / hapi knobs
# ---------------------------------------------------------------------------

def test_engine_zero_stage_wraps_optimizer():
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    net = _mlp(seed=4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    pm = ProcessMesh(np.arange(DP), dim_names=["dp"])
    eng = Engine(model=net, loss=lambda out, y: ((out - y) ** 2).mean(),
                 optimizer=opt, process_mesh=pm, zero_stage=1)
    eng._apply_strategy()
    assert isinstance(eng._optimizer, ShardedOptimizer)
    assert eng._optimizer._inner_opt is opt

    class _DS:
        def __init__(self, n=DP * 4):
            rng = np.random.RandomState(5)
            self.x = rng.randn(n, 16).astype(np.float32)
            self.y = rng.randn(n, 16).astype(np.float32)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    hist = eng.fit(_DS(), batch_size=DP * 2, epochs=1, prefetch=0)
    assert np.isfinite(hist["loss"][-1])
    for store in opt._accumulators.values():
        for acc in store.values():
            if getattr(acc, "ndim", 0) >= 2 and acc.shape[0] % DP == 0:
                assert _local_bytes(acc) == acc.nbytes // DP


def test_hapi_prepare_zero_knob():
    from paddle_tpu.hapi import Model

    mesh = build_mesh({"dp": DP})
    net = _mlp(seed=6)
    rep = NamedSharding(mesh, P())
    for p in net.parameters():
        p._value = jax.device_put(p._value, rep)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    model = Model(net)
    model.prepare(optimizer=opt, loss=paddle.nn.MSELoss(),
                  zero={"axis": "dp", "mesh": mesh, "quantize": "int8"})
    assert isinstance(model._optimizer, ShardedOptimizer)
    assert model._optimizer._inner_opt is opt
    assert model._optimizer._quantize == "int8"
