"""nn.utils (weight_norm / spectral_norm / parameter transforms),
DistributedFusedLamb, and the tape-vs-functional grad cross-check.

Reference: ``nn/utils/weight_norm_hook.py`` (w = g·v/‖v‖ with grads to g,v),
``spectral_norm_hook.py`` (power iteration), ``transform_parameters.py``;
``incubate/optimizer/distributed_fused_lamb.py``.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_weight_norm_reparameterizes_and_trains():
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    nn.utils.weight_norm(lin, "weight", dim=0)
    names = [n for n, _ in lin.named_parameters()]
    assert "weight_g" in names and "weight_v" in names
    assert "weight" not in names  # the derived tensor is not a leaf param
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
    # forward recomputes w from (g, v): initially identical to original w
    y = lin(x)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5, atol=1e-6)
    # grads flow to g and v, not to the derived weight
    loss = (y ** 2).mean()
    loss.backward()
    g = lin.weight_g
    v = lin.weight_v
    assert g.grad is not None and v.grad is not None
    # training moves (g, v) and therefore the effective weight
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    opt.step()
    opt.clear_grad()
    y2 = lin(x)
    assert not np.allclose(lin.weight.numpy(), w0)
    assert not np.allclose(y2.numpy(), y.numpy())


def test_remove_weight_norm_restores_plain_param():
    paddle.seed(1)
    lin = nn.Linear(4, 3)
    nn.utils.weight_norm(lin, "weight")
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    ref = lin(x).numpy()
    nn.utils.remove_weight_norm(lin, "weight")
    names = [n for n, _ in lin.named_parameters()]
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5, atol=1e-6)


def test_spectral_norm_bounds_singular_value():
    paddle.seed(2)
    lin = nn.Linear(6, 8)
    # inflate the weight so sigma >> 1
    lin.weight.set_value(paddle.to_tensor(
        np.random.RandomState(3).randn(6, 8).astype(np.float32) * 5.0))
    nn.utils.spectral_norm(lin, "weight", n_power_iterations=20)
    x = paddle.to_tensor(np.random.RandomState(4).randn(2, 6).astype(np.float32))
    lin(x)  # hook refreshes w
    sigma = np.linalg.svd(lin.weight.numpy(), compute_uv=False).max()
    assert sigma == pytest.approx(1.0, rel=1e-2)
    # gradient flows to the orig parameterization
    (lin(x) ** 2).mean().backward()
    assert lin.weight_orig.grad is not None


def test_parameters_to_vector_roundtrip():
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(3, 4), nn.Linear(4, 2))
    params = net.parameters()
    vec = nn.utils.parameters_to_vector(params)
    total = sum(int(np.prod(p.shape)) for p in params)
    assert list(vec.shape) == [total]
    new_vec = paddle.to_tensor(np.arange(total, dtype=np.float32))
    nn.utils.vector_to_parameters(new_vec, params)
    back = nn.utils.parameters_to_vector(params)
    np.testing.assert_allclose(back.numpy(), new_vec.numpy())
    with pytest.raises(ValueError, match="elements"):
        nn.utils.vector_to_parameters(
            paddle.to_tensor(np.zeros(3, np.float32)), params)


def test_distributed_fused_lamb_matches_lamb_single_process():
    rng = np.random.RandomState(6)
    xs = [paddle.to_tensor(rng.randn(8, 4).astype(np.float32)) for _ in range(4)]

    def build(cls, **kw):
        paddle.seed(7)
        net = nn.Linear(4, 3)
        opt = cls(learning_rate=0.01, lamb_weight_decay=0.01,
                  parameters=net.parameters(), **kw)
        return net, opt

    net_a, opt_a = build(paddle.incubate.DistributedFusedLamb)
    net_b, opt_b = build(paddle.optimizer.Lamb)
    for x in xs:
        (net_a(x) ** 2).mean().backward()
        opt_a.step()
        opt_a.clear_grad()
        (net_b(x) ** 2).mean().backward()
        opt_b.step()
        opt_b.clear_grad()
    np.testing.assert_allclose(net_a.weight.numpy(), net_b.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_tape_and_functional_grad_agree():
    """Round-2 review item: paddle.grad (tape) and incubate.autograd.grad
    (functional jax) must agree on shared cases."""
    import paddle_tpu.incubate.autograd as iag

    rng = np.random.RandomState(8)
    xv = rng.randn(5).astype(np.float32)

    def f_tensor(x):
        return (x ** 3 + 2.0 * x).sum()

    # tape path
    x1 = paddle.to_tensor(xv, stop_gradient=False)
    (g_tape,) = paddle.grad(f_tensor(x1), [x1])
    # functional path
    x2 = paddle.to_tensor(xv)
    g_fn = iag.grad(f_tensor, x2)
    g_fn = g_fn[0] if isinstance(g_fn, (list, tuple)) else g_fn
    np.testing.assert_allclose(g_tape.numpy(), g_fn.numpy(), rtol=1e-5)
    # analytic: 3x^2 + 2
    np.testing.assert_allclose(g_tape.numpy(), 3 * xv ** 2 + 2, rtol=1e-4)
