"""fp32 master weights / multi-precision optimizer tests.

Reference: adam op multi-precision path
(``paddle/fluid/operators/optimizers/adam_op.h`` MasterParam in/out) and
``python/paddle/amp/auto_cast.py decorate:81`` master_weight semantics.
"""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor


def _bf16_model():
    # fresh name scope: twin models must produce identical state_dict keys
    # (mimics cross-process save/restore, reference unique_name.guard)
    from paddle_tpu.utils import unique_name

    with unique_name.guard():
        paddle.seed(0)
        m = nn.Linear(16, 16)
    m.to(dtype="bfloat16")
    return m


def test_moments_and_master_are_fp32_under_bf16():
    m = _bf16_model()
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=m.parameters(), multi_precision=True
    )
    x = Tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32)).astype("bfloat16")
    loss = m(x).pow(2).mean()
    loss.backward()
    opt.step()
    for store_name in ("moment1", "moment2", "master_weight"):
        store = opt._accumulators[store_name]
        assert store, f"{store_name} empty"
        for v in store.values():
            assert v.dtype == jnp.float32, f"{store_name} is {v.dtype}"
    for p in m.parameters():
        assert p._value.dtype == jnp.bfloat16


def test_master_weights_accumulate_small_updates():
    """bf16 has ~8 bits of mantissa: a 1e-3 relative update vanishes without a
    master copy but must accumulate with one."""
    paddle.seed(0)

    def run(multi_precision):
        p = paddle.framework.tensor.Parameter(jnp.full((128,), 256.0, jnp.bfloat16))
        p.name = f"p_mp{multi_precision}"
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=[p], multi_precision=multi_precision
        )
        for _ in range(64):
            p.grad = jnp.full((128,), 1e-3, jnp.float32)  # update << bf16 ulp(256)=2
            opt.step()
            opt.clear_grad()
        master = opt._accumulators.get("master_weight")
        return np.asarray(p._value, np.float32)[0], master

    final_plain, _ = run(False)
    final_master, master_store = run(True)
    # without master weights each 1e-3 step rounds away entirely
    assert final_plain == 256.0
    # with master weights 64 * 1e-3 accumulates in fp32 (param itself still
    # rounds to the nearest bf16, but the master must carry the sum)
    mv = float(np.asarray(next(iter(master_store.values()))[0]))
    np.testing.assert_allclose(mv, 256.0 - 0.064, rtol=1e-5)


def test_decorate_enables_master_and_keeps_ln_fp32():
    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)
            self.ln = nn.LayerNorm(8)

        def forward(self, x):
            return self.ln(self.fc(x))

    net = Net()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=net.parameters())
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    assert opt._multi_precision is True
    assert net.fc.weight._value.dtype == jnp.bfloat16
    assert net.ln.weight._value.dtype == jnp.float32


def test_master_weight_state_dict_roundtrip():
    m = _bf16_model()
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=m.parameters(), multi_precision=True
    )
    x = Tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32)).astype("bfloat16")
    for _ in range(3):
        loss = m(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    msd = m.state_dict()

    m2 = _bf16_model()
    m2.set_state_dict(msd)
    opt2 = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=m2.parameters(), multi_precision=True
    )
    opt2.set_state_dict(sd)
    loss = m2(x).pow(2).mean()
    loss.backward()
    opt2.step()  # consumes pending master_weight instead of re-init

    loss = m(x).pow(2).mean()
    loss.backward()
    opt.step()

    for (k1, v1), (k2, v2) in zip(
        sorted(opt._accumulators["master_weight"].items()),
        sorted(opt2._accumulators["master_weight"].items()),
    ):
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-6)


def test_scaler_inf_skip_after_restore_keeps_checkpoint_state():
    """First scaled step after set_state_dict overflows: the inf-skip must
    restore the CHECKPOINT accumulator values (still pending, materialized
    lazily during that very step), not the init fills."""
    m = _bf16_model()
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=m.parameters(), multi_precision=True
    )
    x = Tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32)).astype("bfloat16")
    for _ in range(3):
        loss = m(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    msd = m.state_dict()
    ckpt_m1 = {k: np.asarray(v) for k, v in opt._accumulators["moment1"].items()}
    ckpt_mw = {k: np.asarray(v) for k, v in opt._accumulators["master_weight"].items()}

    m2 = _bf16_model()
    m2.set_state_dict(msd)
    opt2 = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=m2.parameters(), multi_precision=True
    )
    opt2.set_state_dict(sd)  # everything lands in _pending_state
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0**15)

    bad = Tensor(np.full((4, 16), 1e30, np.float32)).astype("bfloat16")
    loss = m2(bad).pow(2).mean()  # overflow -> inf grads
    scaler.scale(loss).backward()
    scaler.step(opt2)
    scaler.update()

    for key, want in ckpt_m1.items():
        np.testing.assert_allclose(
            np.asarray(opt2._accumulators["moment1"][key]), want, rtol=1e-6,
            err_msg=f"moment1[{key}] lost its checkpoint value on the inf step",
        )
    for key, want in ckpt_mw.items():
        np.testing.assert_allclose(
            np.asarray(opt2._accumulators["master_weight"][key]), want, rtol=1e-6,
            err_msg=f"master_weight[{key}] lost its checkpoint value on the inf step",
        )


def test_scaler_inf_skip_preserves_master_weights():
    """A scaled step that overflows must leave the master weights untouched,
    including masters born during that very step."""
    m = _bf16_model()
    opt = paddle.optimizer.SGD(
        learning_rate=1.0, parameters=m.parameters(), multi_precision=True
    )
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0**15)
    pre = {p.name: np.asarray(p._value, np.float32).copy() for p in m.parameters()}

    x = Tensor(np.full((2, 16), 1e30, np.float32)).astype("bfloat16")
    loss = m(x).pow(2).mean()  # overflows bf16 -> inf grads
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    scaler.update()

    for p in m.parameters():
        np.testing.assert_array_equal(
            np.asarray(p._value, np.float32), pre[p.name],
            err_msg=f"param {p.name} changed on an inf step",
        )
    for key, mw in opt._accumulators.get("master_weight", {}).items():
        np.testing.assert_allclose(
            np.asarray(mw), pre[key], rtol=1e-3,
            err_msg=f"master {key} diverged from param on an inf step",
        )
