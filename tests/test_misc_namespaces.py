"""Small public namespaces: version/sysconfig/compat/batch/reader/hub/
callbacks/dataset/tensor/inference.

Reference files are noted per test; these are thin but real surfaces the
reference user relies on.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_version_and_sysconfig():
    assert paddle.version.full_version
    assert paddle.version.cuda() is False
    assert os.path.isdir(paddle.sysconfig.get_include())
    # get_lib points at the native build dir (created on first native use)
    assert paddle.sysconfig.get_lib().endswith("_build")


def test_compat_helpers():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert paddle.compat.floor_division(7, 2) == 3


def test_batch_reader():
    def reader():
        yield from range(7)

    batches = list(paddle.batch(reader, batch_size=3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    batches = list(paddle.batch(reader, batch_size=3, drop_last=True)())
    assert batches == [[0, 1, 2], [3, 4, 5]]


def test_reader_combinators():
    r = paddle.reader

    def nums():
        yield from range(10)

    assert list(r.firstn(nums, 3)()) == [0, 1, 2]
    assert sorted(r.shuffle(nums, 4)()) == list(range(10))
    assert list(r.chain(nums, nums)()) == list(range(10)) * 2
    assert list(r.map_readers(lambda a, b: a + b, nums, nums)()) == [
        2 * i for i in range(10)]
    assert list(r.buffered(nums, 2)()) == list(range(10))
    cached = r.cache(nums)
    assert list(cached()) == list(range(10)) and list(cached()) == list(range(10))
    out = list(r.xmap_readers(lambda v: v * 10, nums, 2, 4, order=True)())
    assert out == [i * 10 for i in range(10)]
    composed = r.compose(nums, nums)
    assert list(composed())[0] == (0, 0)


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny(scale=1):\n"
        "    'build a tiny thing'\n"
        "    return {'scale': scale}\n")
    assert "tiny" in paddle.hub.list(str(tmp_path))
    assert "tiny thing" in paddle.hub.help(str(tmp_path), "tiny")
    assert paddle.hub.load(str(tmp_path), "tiny", scale=3) == {"scale": 3}
    with pytest.raises(RuntimeError, match="offline"):
        paddle.hub.load("user/repo", "x", source="github")


def test_callbacks_namespace_and_reduce_lr():
    import paddle_tpu.nn as nn

    assert paddle.callbacks.EarlyStopping is not None
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                            patience=1, verbose=0)
    cb.set_model(model) if hasattr(cb, "set_model") else setattr(cb, "model", model)
    cb.on_train_begin()
    cb.on_eval_end({"loss": 1.0})  # sets the baseline
    cb.on_eval_end({"loss": 1.0})  # no improvement -> patience hit, reduce
    assert float(opt.get_lr()) == pytest.approx(0.05)
    cb.on_eval_end({"loss": 1.0})  # still flat -> second reduction
    assert float(opt.get_lr()) == pytest.approx(0.025)
    cb.on_eval_end({"loss": 0.1})  # improvement -> lr holds
    assert float(opt.get_lr()) == pytest.approx(0.025)


def test_reduce_lr_cooldown_suppresses_reductions():
    import paddle_tpu.nn as nn

    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                            patience=1, cooldown=2, verbose=0)
    cb.model = model
    cb.on_train_begin()
    cb.on_eval_end({"loss": 1.0})  # baseline
    cb.on_eval_end({"loss": 1.0})  # reduce -> 0.05, cooldown starts
    assert float(opt.get_lr()) == pytest.approx(0.05)
    cb.on_eval_end({"loss": 1.0})  # cooldown tick 1: NO reduction
    cb.on_eval_end({"loss": 1.0})  # cooldown tick 2: NO reduction
    assert float(opt.get_lr()) == pytest.approx(0.05)
    cb.on_eval_end({"loss": 1.0})  # cooldown over -> plateau counts again
    assert float(opt.get_lr()) == pytest.approx(0.025)


def test_dataset_readers():
    sample = next(paddle.dataset.mnist.train()())
    assert sample[0].shape == (784,) and isinstance(sample[1], int)
    x, y = next(paddle.dataset.uci_housing.train()())
    assert x.shape == (13,)
    doc, label = next(paddle.dataset.imdb.train()())
    assert len(doc) > 0 and label in (0, 1)


def test_tensor_namespace():
    import paddle_tpu.tensor as T

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(T.math.add(a, a).numpy(), [2.0, 4.0])
    assert T.concat is not None and T.linalg is not None


def test_inference_predictor_two_inputs(tmp_path):
    """Predictor must expose one handle per saved input (n_inputs from the
    .pdmeta written at save time)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import save

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, a, b):
            return self.fc(a) + self.fc(b)

    paddle.seed(1)
    net = TwoIn()
    a = paddle.to_tensor(np.ones((2, 4), np.float32))
    b = paddle.to_tensor(np.full((2, 4), 2.0, np.float32))
    ref = net(a, b).numpy()
    path = str(tmp_path / "two_in")
    save(net, path, input_spec=[paddle.static.InputSpec([2, 4], "float32"),
                                paddle.static.InputSpec([2, 4], "float32")])
    pred = paddle.inference.create_predictor(paddle.inference.Config(path))
    names = pred.get_input_names()
    assert names == ["input_0", "input_1"]
    pred.get_input_handle("input_0").copy_from_cpu(a.numpy())
    pred.get_input_handle("input_1").copy_from_cpu(b.numpy())
    pred.run()
    np.testing.assert_allclose(
        pred.get_output_handle("output_0").copy_to_cpu(), ref, rtol=1e-5)


def test_inference_predictor_roundtrip(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import save

    paddle.seed(0)
    net = nn.Linear(4, 2)
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    ref = net(x).numpy()
    path = str(tmp_path / "model")
    save(net, path, input_spec=[paddle.static.InputSpec([3, 4], "float32")])

    config = paddle.inference.Config(path)
    predictor = paddle.inference.create_predictor(config)
    names = predictor.get_input_names()
    h = predictor.get_input_handle(names[0])
    h.copy_from_cpu(x.numpy())
    assert predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(out.copy_to_cpu(), ref, rtol=1e-5, atol=1e-6)
