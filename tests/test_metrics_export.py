"""OpenMetrics export (ISSUE 8): text renderer, ``/metrics`` endpoint,
exact histogram ``_count``/``_sum``, and the stdlib scrape round trip.

Contracts under test:
  * counters render as ``counter`` families with the ``_total`` suffix,
    gauges as ``gauge``, histograms as ``summary`` carrying EXACT running
    ``_count``/``_sum`` (acceptance: scraped rates must be correct) plus
    the reservoir p50/p95 as quantile samples;
  * the exposition is parseable by ``tools/metrics_scrape.py`` and ends
    with ``# EOF`` (truncated scrapes fail loudly);
  * ``telemetry.serve_metrics(port=0)`` binds an ephemeral port, serves a
    scrapeable exposition over real HTTP, and tears down cleanly;
  * the endpoint is opt-in and render-on-scrape: nothing changes on the
    instrumented hot paths (the PR 2 zero-overhead tests stay green).
"""
import os
import sys
import urllib.request

import pytest

from paddle_tpu.profiler import telemetry
from paddle_tpu.profiler.export import (
    CONTENT_TYPE,
    MetricsServer,
    openmetrics_name,
    render_openmetrics,
)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import metrics_scrape  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _populate():
    telemetry.enable()
    tm = telemetry.get_telemetry()
    tm.inc("serve.decode_steps", 126)
    tm.inc("serve.tokens_generated", 1024)
    tm.set_gauge("serve.queue_depth", 4)
    tm.set_gauge("step.time_s", 0.125)
    for v in (0.1, 0.2, 0.3, 0.4):
        tm.observe("serve.ttft_s", v)
    return tm


def test_name_sanitization():
    assert openmetrics_name("serve.ttft_s") == "serve_ttft_s"
    assert openmetrics_name("comm.bytes.dp") == "comm_bytes_dp"
    assert openmetrics_name("9lives") == "_9lives"
    assert openmetrics_name("a-b c") == "a_b_c"


def test_render_families_and_exact_count_sum():
    _populate()
    text = render_openmetrics()
    assert text.endswith("# EOF\n")
    assert "# TYPE serve_decode_steps counter" in text
    assert "serve_decode_steps_total 126" in text
    assert "# TYPE serve_queue_depth gauge" in text
    assert "serve_queue_depth 4" in text
    assert "# TYPE serve_ttft_s summary" in text
    # EXACT running count/sum — not reservoir-derived
    assert "serve_ttft_s_count 4" in text
    assert "serve_ttft_s_sum 1\n" in text  # 0.1+0.2+0.3+0.4 == 1.0 exactly
    assert 'serve_ttft_s{quantile="0.5"}' in text
    assert 'serve_ttft_s{quantile="0.95"}' in text


def test_render_includes_phase_histograms():
    telemetry.enable()
    with telemetry.phase_span("dispatch"):
        pass
    text = render_openmetrics()
    assert "# TYPE phase_dispatch summary" in text
    assert "phase_dispatch_count 1" in text


def test_parse_round_trip_preserves_values():
    tm = _populate()
    fams = metrics_scrape.parse_openmetrics(render_openmetrics())
    assert fams["serve_decode_steps"]["type"] == "counter"
    assert metrics_scrape.sample_value(
        fams, "serve_decode_steps", "serve_decode_steps_total") == 126
    assert metrics_scrape.sample_value(fams, "serve_queue_depth") == 4
    st = tm.get("serve.ttft_s")
    assert metrics_scrape.sample_value(
        fams, "serve_ttft_s", "serve_ttft_s_count") == st["count"]
    assert metrics_scrape.sample_value(
        fams, "serve_ttft_s", "serve_ttft_s_sum") == pytest.approx(
            st["sum"], abs=0)
    assert metrics_scrape.sample_value(
        fams, "serve_ttft_s", quantile="0.95") == pytest.approx(0.4)


def test_parser_rejects_truncated_exposition():
    with pytest.raises(ValueError, match="EOF"):
        metrics_scrape.parse_openmetrics("serve_x_total 1\n")
    with pytest.raises(ValueError, match="unparseable"):
        metrics_scrape.parse_openmetrics("!! garbage !!\n# EOF\n")


def test_render_works_with_collection_disabled():
    """The renderer reads whatever the registry holds — it must not
    require the collection flag (an operator scrapes a quiesced process
    too)."""
    tm = _populate()
    telemetry.disable()
    text = render_openmetrics()
    assert "serve_decode_steps_total 126" in text
    assert tm.counters()["serve.decode_steps"] == 126


def test_http_endpoint_scrape_and_close():
    _populate()
    srv = telemetry.serve_metrics(port=0)
    try:
        assert srv.port > 0
        with urllib.request.urlopen(srv.url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            body = resp.read().decode()
        fams = metrics_scrape.parse_openmetrics(body)
        assert metrics_scrape.sample_value(
            fams, "serve_decode_steps", "serve_decode_steps_total") == 126
        # scrapes are render-on-demand: a counter bump between scrapes is
        # visible on the next one
        telemetry.get_telemetry().inc("serve.decode_steps")
        with urllib.request.urlopen(srv.url, timeout=10) as resp:
            body2 = resp.read().decode()
        assert "serve_decode_steps_total 127" in body2
        # non-metrics paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url.replace("/metrics", "/nope"),
                                   timeout=10)
    finally:
        srv.close()
    # closed: the port no longer accepts scrapes
    with pytest.raises(OSError):
        urllib.request.urlopen(srv.url, timeout=2)


def test_metrics_scrape_cli_assertions(tmp_path, capsys):
    _populate()
    p = tmp_path / "dump.txt"
    p.write_text(render_openmetrics())
    assert metrics_scrape.main([str(p),
                                "--assert-family", "serve_ttft_s"]) == 0
    out = capsys.readouterr().out
    assert "serve_ttft_s" in out and "summary" in out
    assert metrics_scrape.main([str(p), "--quiet",
                                "--assert-family", "nonexistent"]) == 1
    err = capsys.readouterr().err
    assert "nonexistent" in err


def test_report_tools_render_serving_sections(tmp_path, capsys):
    """Satellite: tools/telemetry_report.py and tools/mem_report.py grow a
    serving section — serve.* stats no longer land unhumanized in the
    generic counter table."""
    import mem_report
    import telemetry_report

    from paddle_tpu.utils.log_writer import LogWriter

    tm = _populate()
    with LogWriter(str(tmp_path), file_name="serve.jsonl") as w:
        tm.export_scalars(w, step=1)
    path = str(tmp_path / "serve.jsonl")

    assert telemetry_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "serving:" in out
    assert "serve.decode_steps" in out
    assert "serve.ttft_s" in out
    # serve stats moved OUT of the generic counter table
    head = out.split("serving:")[0]
    assert "serve.decode_steps" not in head

    assert mem_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "serving:" in out
    assert "serve.ttft_s" in out and "p95=" in out


def test_telemetry_histograms_in_summary_and_report(capsys):
    """Satellite: observe() histograms surface exact count/sum (plus
    reservoir p50/p95) in summary() and the report() table."""
    tm = _populate()
    s = telemetry.summary()
    h = s["histograms"]["serve.ttft_s"]
    assert h["count"] == 4
    assert h["sum"] == pytest.approx(1.0)
    assert h["p50"] == pytest.approx(0.3)  # nearest-rank over 4 samples
    assert h["p95"] == pytest.approx(0.4)
    # stat() resolves any single statistic (the SLO monitor's accessor)
    assert tm.stat("serve.ttft_s", "count") == 4
    assert tm.stat("serve.ttft_s", "mean") == pytest.approx(0.25)
    assert tm.stat("serve.ttft_s", "p95") == pytest.approx(0.4)
    assert tm.stat("serve.missing", "p95") is None
    with pytest.raises(ValueError):
        tm.stat("serve.ttft_s", "bogus")
    table = telemetry.report()
    capsys.readouterr()
    assert "histograms:" in table
    assert "serve.ttft_s" in table
