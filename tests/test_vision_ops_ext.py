"""vision.ops (roi_align/roi_pool/nms/deform_conv2d) + dlpack interop +
custom-op registration. References: python/paddle/vision/ops.py,
framework/dlpack_tensor.cc, framework/custom_operator.cc."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.vision import ops as vops


def _np(t):
    return np.asarray(t._value)


def test_roi_align_against_torchvision():
    tv = pytest.importorskip("torchvision")
    import torch

    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    boxes = np.array([[1.0, 1.0, 9.0, 9.0], [2.0, 3.0, 14.0, 12.0],
                      [0.0, 0.0, 15.0, 15.0]], np.float32)
    boxes_num = np.array([2, 1], np.int32)

    out = vops.roi_align(Tensor(x), Tensor(boxes), Tensor(boxes_num),
                         output_size=4, spatial_scale=1.0, sampling_ratio=2,
                         aligned=True)

    tv_boxes = [torch.tensor(boxes[:2]), torch.tensor(boxes[2:])]
    ref = tv.ops.roi_align(torch.tensor(x), tv_boxes, output_size=4,
                           spatial_scale=1.0, sampling_ratio=2, aligned=True)
    np.testing.assert_allclose(_np(out), ref.numpy(), atol=1e-4)


def test_roi_align_gradient_flows():
    x = Tensor(np.random.RandomState(1).randn(1, 2, 8, 8).astype(np.float32),
               stop_gradient=False)
    boxes = Tensor(np.array([[1.0, 1.0, 6.0, 6.0]], np.float32))
    out = vops.roi_align(x, boxes, Tensor(np.array([1], np.int32)),
                         output_size=2)
    out.sum().backward()
    assert x.grad is not None and np.abs(_np(x.grad)).sum() > 0


def test_roi_pool_basic():
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 2, 2] = 5.0
    x[0, 0, 5, 6] = 7.0
    out = vops.roi_pool(Tensor(x), Tensor(np.array([[0., 0., 7., 7.]], np.float32)),
                        Tensor(np.array([1], np.int32)), output_size=2)
    o = _np(out)[0, 0]
    assert o[0, 0] == 5.0 and o[1, 1] == 7.0


def test_nms_matches_torchvision():
    tv = pytest.importorskip("torchvision")
    import torch

    rng = np.random.RandomState(2)
    n = 30
    xy = rng.uniform(0, 20, (n, 2)).astype(np.float32)
    wh = rng.uniform(2, 8, (n, 2)).astype(np.float32)
    boxes = np.concatenate([xy, xy + wh], -1)
    scores = rng.uniform(0, 1, n).astype(np.float32)

    kept = _np(vops.nms(Tensor(boxes), 0.4, scores=Tensor(scores)))
    ref = tv.ops.nms(torch.tensor(boxes), torch.tensor(scores), 0.4).numpy()
    np.testing.assert_array_equal(kept, ref)


def test_nms_categories_and_topk():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    cats = np.array([0, 0, 1], np.int64)
    kept = _np(vops.nms(Tensor(boxes), 0.5, scores=Tensor(scores),
                        category_idxs=Tensor(cats), categories=[0, 1]))
    # box1 suppressed by box0 (same cat, IoU>0.5); box2 survives (other cat)
    assert set(kept.tolist()) == {0, 2}
    kept2 = _np(vops.nms(Tensor(boxes), 0.5, scores=Tensor(scores),
                         category_idxs=Tensor(cats), categories=[0, 1],
                         top_k=1))
    assert kept2.tolist() == [0]


def test_deform_conv2d_zero_offset_equals_conv():
    """With zero offsets, deform_conv2d == plain conv2d."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(3)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    w = (rng.randn(4, 2, 3, 3) * 0.2).astype(np.float32)
    off = np.zeros((1, 2 * 9, 4, 4), np.float32)

    out = vops.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
    ref = F.conv2d(Tensor(x), Tensor(w))
    np.testing.assert_allclose(_np(out), _np(ref), atol=1e-4)

    # offsets shift sampling: nonzero offset changes the output
    off2 = np.full_like(off, 0.7)
    out2 = vops.deform_conv2d(Tensor(x), Tensor(off2), Tensor(w))
    assert not np.allclose(_np(out2), _np(out))


def test_dlpack_roundtrip_with_torch():
    import torch

    from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack

    x = Tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    t = torch.from_dlpack(x._value)
    np.testing.assert_allclose(t.numpy(), _np(x))

    back = from_dlpack(torch.arange(4).float())
    np.testing.assert_allclose(_np(back), [0, 1, 2, 3])
    with pytest.raises(TypeError):
        to_dlpack(np.zeros(3))


def test_register_custom_op():
    import jax.numpy as jnp

    from paddle_tpu.utils.cpp_extension import CustomOpError, register_custom_op

    myop = register_custom_op("test_swish3", lambda x: x * jnp.tanh(x))
    x = Tensor(np.array([0.5, -1.0], np.float32), stop_gradient=False)
    y = myop(x)
    y.sum().backward()
    assert x.grad is not None

    # custom backward pair
    def save(x):
        return x * 2.0, x

    def grad(res, g):
        return (g * 2.0,)

    dbl = register_custom_op("test_double3", lambda x: x * 2.0,
                             backward=(save, grad))
    x2 = Tensor(np.ones(3, np.float32), stop_gradient=False)
    dbl(x2).sum().backward()
    np.testing.assert_allclose(_np(x2.grad), 2.0)

    with pytest.raises(CustomOpError):
        register_custom_op("test_swish3", lambda x: x)
