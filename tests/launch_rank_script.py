"""Rank script for the two-process launch test (reference pattern:
``unittests/test_collective_base.py`` rank scripts). Run by
``python -m paddle_tpu.distributed.launch --nproc_per_node 2 --backend gloo``.

Exercises the REAL multi-controller path: jax.distributed.initialize via
init_parallel_env, a cross-process psum, and a data-parallel train step on a
2-process global mesh."""
import json
import os
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.framework.tensor import Tensor

env = dist.init_parallel_env()
rank, world = env.rank, env.world_size
assert world == 2, world

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devs = jax.devices()
assert len(devs) == 2, f"expected 2 global devices, got {devs}"
assert jax.process_count() == 2

mesh = Mesh(np.array(devs), ("dp",))

# 1. cross-process all-reduce parity
local = np.full((1, 4), float(rank + 1), np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, (2, 4)
)
total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
got = float(np.asarray(jax.device_get(total)))
assert got == 12.0, got

# 2. data-parallel train step: per-process batch shard, psum'd grads via the
# global-mesh jit — loss and updated weights must match on both ranks
paddle.seed(0)
lin = paddle.nn.Linear(4, 1)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())

rng = np.random.RandomState(100 + rank)  # different data per rank
x_local = rng.randn(2, 4).astype(np.float32)
x_global = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), x_local, (4, 4)
)

from paddle_tpu.jit.functionalize import CompiledStep


def step(x):
    loss = lin(x).square().mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss


cs = CompiledStep(step, stateful=[lin, opt], donate_state=False)
loss = cs(Tensor(x_global))
loss_val = float(np.asarray(jax.device_get(loss._value)))
w_after = np.asarray(jax.device_get(lin.weight._value)).ravel().tolist()

out_dir = os.environ["LAUNCH_TEST_OUT"]
with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
    json.dump({"rank": rank, "world": world, "psum": got,
               "loss": loss_val, "w": w_after}, f)
print(f"rank {rank} OK", flush=True)

# 3. reference per-rank eager collective semantics (multihost_utils path):
# each process contributes its LOCAL value — NCCL-style, not stacked-global
lr = Tensor(np.full((2,), float(rank + 1), np.float32))
summed = dist.all_reduce(lr)
assert np.allclose(np.asarray(summed._value), 3.0), np.asarray(summed._value)

gathered = []
dist.all_gather(gathered, Tensor(np.full((2,), float(rank), np.float32)))
assert len(gathered) == 2
assert np.allclose(np.asarray(gathered[0]._value), 0.0)
assert np.allclose(np.asarray(gathered[1]._value), 1.0)

b = Tensor(np.full((3,), float(rank * 7 + 1), np.float32))
bc = dist.broadcast(b, src=1)
assert np.allclose(np.asarray(bc._value), 8.0), np.asarray(bc._value)

dist.barrier()
