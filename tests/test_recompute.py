"""Activation-recomputation tests.

Reference: ``fleet/utils/recompute.py`` (RecomputeFunction:207, recompute:350)
and its unit tests (``unittests/test_dygraph_recompute.py``): outputs and
gradients must match the non-recomputed run, RNG state must be preserved,
and the backward must actually save less memory.
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.utils import recompute, recompute_sequential
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.utils import unique_name


def _mlp(depth=4, width=64):
    with unique_name.guard():
        paddle.seed(0)
        layers = []
        for _ in range(depth):
            layers += [nn.Linear(width, width), nn.GELU()]
        return nn.Sequential(*layers)


def test_recompute_output_and_grad_parity():
    m1, m2 = _mlp(), _mlp()
    x_np = np.random.RandomState(0).randn(8, 64).astype(np.float32)

    x1 = Tensor(x_np)
    out1 = m1(x1).pow(2).mean()
    out1.backward()

    x2 = Tensor(x_np)
    out2 = recompute(m2, x2).pow(2).mean()
    out2.backward()

    np.testing.assert_allclose(
        np.asarray(out1._value), np.asarray(out2._value), rtol=1e-6
    )
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        assert p2.grad is not None, "recompute dropped a parameter gradient"
        np.testing.assert_allclose(
            np.asarray(p1.grad), np.asarray(p2.grad), rtol=1e-5, atol=1e-6
        )


def test_recompute_preserves_dropout_rng():
    """The recomputed forward must replay the same dropout mask (reference
    preserve_rng_state=True)."""
    with unique_name.guard():
        paddle.seed(7)
        m = nn.Sequential(nn.Linear(32, 32), nn.Dropout(0.5), nn.Linear(32, 32))
    x = Tensor(np.random.RandomState(1).randn(4, 32).astype(np.float32))

    paddle.seed(123)
    out = recompute(m, x).sum()
    out.backward()
    g1 = {p.name: np.asarray(p.grad).copy() for p in m.parameters()}
    for p in m.parameters():
        p.clear_grad()

    paddle.seed(123)
    out2 = m(x).sum()
    out2.backward()
    np.testing.assert_allclose(
        float(np.asarray(out._value)), float(np.asarray(out2._value)), rtol=1e-6
    )
    for p in m.parameters():
        np.testing.assert_allclose(np.asarray(p.grad), g1[p.name], rtol=1e-5)


def test_recompute_sequential_chunks():
    m = _mlp(depth=6)
    x_np = np.random.RandomState(0).randn(4, 64).astype(np.float32)
    ref = m(Tensor(x_np))
    out = recompute_sequential({"segments": 3}, list(m), Tensor(x_np))
    np.testing.assert_allclose(
        np.asarray(out._value), np.asarray(ref._value), rtol=1e-6
    )


def test_recompute_recomputes_forward_in_backward():
    """The compiled program must actually re-run the forward matmuls inside
    the backward (that is what frees the activations on TPU).  XLA:CPU's
    ``memory_analysis().temp_size_in_bytes`` is insensitive to remat (its
    buffer accounting CSEs across the barrier), so the assertion is on the
    optimized-HLO structure: the recompute build contains one extra forward
    dot per layer."""
    depth, width, batch = 8, 256, 256
    m = _mlp(depth=depth, width=width)
    x_np = np.random.RandomState(0).randn(batch, width).astype(np.float32)

    def dot_count(use_recompute):
        def train(x):
            out = (recompute(m, x) if use_recompute else m(x)).pow(2).mean()
            out.backward()
            grads = [p.grad for p in m.parameters()]
            for p in m.parameters():
                p.clear_grad()
            return grads

        step = CompiledStep(train, stateful=[m], donate_state=False)
        compiled = step.lower(Tensor(x_np)).compile()
        return compiled.as_text().count(" dot(")

    plain = dot_count(False)
    remat = dot_count(True)
    assert remat >= plain + depth - 1, (
        f"recompute did not re-run forward matmuls in backward: "
        f"{remat} vs {plain} (+{depth} layers)"
    )


def test_pipeline_layer_recompute_interval():
    """PipelineLayer honors recompute_interval (was accepted-and-ignored)."""
    from paddle_tpu.distributed.meta_parallel import PipelineLayer

    with unique_name.guard():
        paddle.seed(0)
        descs = [nn.Linear(16, 16) for _ in range(4)]
        pl_plain = PipelineLayer(descs, num_stages=1)
    with unique_name.guard():
        paddle.seed(0)
        descs2 = [nn.Linear(16, 16) for _ in range(4)]
        pl_rc = PipelineLayer(descs2, num_stages=1, recompute_interval=2)

    x_np = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    out_a = pl_plain(Tensor(x_np)).pow(2).mean()
    out_a.backward()
    out_b = pl_rc(Tensor(x_np)).pow(2).mean()
    out_b.backward()
    np.testing.assert_allclose(
        np.asarray(out_a._value), np.asarray(out_b._value), rtol=1e-6
    )
    for pa, pb in zip(pl_plain.parameters(), pl_rc.parameters()):
        np.testing.assert_allclose(
            np.asarray(pa.grad), np.asarray(pb.grad), rtol=1e-5, atol=1e-6
        )
