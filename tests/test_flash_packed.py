"""Seq-major packed flash attention: parity vs the einsum reference and
the layout-swapping kernel (interpret mode, CPU). Reference capability:
``paddle/fluid/operators/fused/fused_attention_op.cu``."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.flash_attention_packed import (
    flash_attention_packed,
    supports,
)

B, S, H, D = 2, 256, 4, 64


def _inputs(dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(ks[0], (B, S, H * D), dtype)
    k = jax.random.normal(ks[1], (B, S, H * D), dtype)
    v = jax.random.normal(ks[2], (B, S, H * D), dtype)
    bias = jax.random.normal(ks[3], (S, S), jnp.float32) * 0.5
    return q, k, v, bias


def _ref(q, k, v, causal=False, bias=None):
    qh = q.reshape(B, S, H, D)
    kh = k.reshape(B, S, H, D)
    vh = v.reshape(B, S, H, D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / np.sqrt(D)
    if bias is not None:
        logits = logits + bias[None, None]
    if causal:
        m = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(m, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vh).reshape(B, S, H * D)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_bias", [False, True])
def test_forward_parity(causal, use_bias):
    q, k, v, bias = _inputs()
    bb = bias if use_bias else None
    got = flash_attention_packed(q, k, v, H, bias=bb, causal=causal,
                                 block_q=128, block_k=128, interpret=True)
    want = _ref(q, k, v, causal, bb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_grad_parity_vs_einsum():
    q, k, v, _ = _inputs()
    co = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)

    def f_packed(q, k, v):
        out = flash_attention_packed(q, k, v, H, causal=True, block_q=128,
                                     block_k=128, bwd_block=128,
                                     interpret=True)
        return jnp.vdot(out, co)

    def f_ref(q, k, v):
        return jnp.vdot(_ref(q, k, v, causal=True), co)

    gp = jax.grad(f_packed, (0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gp, gr):
        err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert err < 1e-4, (name, err)


def test_bwd_block_differs_from_fwd():
    """bwd re-tiles at its own block size (VMEM headroom); gradients must
    not depend on the choice."""
    q, k, v, _ = _inputs()
    co = jax.random.normal(jax.random.key(5), q.shape, jnp.float32)

    def grads(bwd_block):
        def f(q, k, v):
            out = flash_attention_packed(q, k, v, H, causal=True,
                                         block_q=256, block_k=256,
                                         bwd_block=bwd_block, interpret=True)
            return jnp.vdot(out, co)
        return jax.grad(f, (0, 1, 2))(q, k, v)

    for a, b in zip(grads(128), grads(256)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_matches_layout_swapping_kernel():
    q, k, v, _ = _inputs()
    got = flash_attention_packed(q, k, v, H, causal=True, block_q=128,
                                 block_k=128, interpret=True)
    want = flash_attention(
        q.reshape(B, S, H, D), k.reshape(B, S, H, D), v.reshape(B, S, H, D),
        causal=True, block_q=128, block_k=128, interpret=True,
    ).reshape(B, S, H * D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_supports_gate():
    assert supports(1024, 1024, 12, 768)        # d=64: two heads per group
    assert supports(1024, 1024, 6, 768)         # d=128: one head per group
    assert supports(256, 256, 8, 256)           # d=32: four heads per group
    assert not supports(100, 100, 4, 256)       # seq not 128-tileable
    assert not supports(256, 256, 5, 240)       # d=48: no 128-lane grouping
    assert not supports(256, 256, 3, 288)       # d=96: no 128-lane grouping


def test_router_prefers_packed(monkeypatch):
    """F.sdpa routes mask-free large-seq attention through the packed
    kernel (no layout transposes)."""
    import paddle_tpu  # noqa: F401
    from paddle_tpu.nn.functional import attention as A
    from paddle_tpu.ops.pallas import flash_attention_packed as packed_mod

    called = {}
    orig = packed_mod.flash_attention_packed

    def spy(*a, **kw):
        called["hit"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(packed_mod, "flash_attention_packed", spy)
    q = jnp.ones((1, 256, 4, 64), jnp.float32)
    with __import__("paddle_tpu").ops.pallas.interpret_mode():
        A._sdpa_flash(q, q, q, causal=True)
    assert called.get("hit")


def _ref_rect(q, k, v, h, causal):
    """Einsum reference for sq != sk (bottom-right-aligned causal)."""
    b, sq, e = q.shape
    sk = k.shape[1]
    d = e // h
    qh = q.reshape(b, sq, h, d)
    kh = k.reshape(b, sk, h, d)
    vh = v.reshape(b, sk, h, d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / np.sqrt(d)
    if causal:
        m = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(m, logits, -1e30)
        p = jax.nn.softmax(logits, -1)
        # fully-masked rows: softmax of all -1e30 is uniform garbage; the
        # kernel contract is output 0 for those rows
        p = jnp.where(m.any(-1)[None, None, :, None], p, 0.0)
    else:
        p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vh).reshape(b, sq, e)


def test_multi_tile_causal_boundary_inside_tile():
    """Advisor regression: sq > sk causal where the masked-row boundary sits
    INSIDE a q tile (offset=-128, block_q=256) — the multi-tile forward must
    zero fully-masked rows, not emit a spurious uniform softmax."""
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (1, 512, 2 * 64), jnp.float32)
    k = jax.random.normal(ks[1], (1, 384, 2 * 64), jnp.float32)
    v = jax.random.normal(ks[2], (1, 384, 2 * 64), jnp.float32)
    out = flash_attention_packed(q, k, v, 2, causal=True, block_q=256,
                                 block_k=128, interpret=True)
    # offset = -128: rows 0..127 attend nothing (inside tile qi=0)
    np.testing.assert_array_equal(np.asarray(out[0, :128]), 0.0)
    want = _ref_rect(q, k, v, 2, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)


def test_multi_tile_causal_boundary_grads_zero():
    """Advisor regression: the fused backward must give zero dq for
    fully-masked rows and zero spurious dk/dv from them."""
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (1, 512, 2 * 64), jnp.float32)
    k = jax.random.normal(ks[1], (1, 384, 2 * 64), jnp.float32)
    v = jax.random.normal(ks[2], (1, 384, 2 * 64), jnp.float32)
    co = jax.random.normal(jax.random.key(8), q.shape, jnp.float32)

    def f_packed(q, k, v):
        out = flash_attention_packed(q, k, v, 2, causal=True, block_q=256,
                                     block_k=128, bwd_block=256,
                                     interpret=True)
        return jnp.vdot(out, co)

    def f_ref(q, k, v):
        return jnp.vdot(_ref_rect(q, k, v, 2, causal=True), co)

    gp = jax.grad(f_packed, (0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    np.testing.assert_array_equal(np.asarray(gp[0][0, :128]), 0.0)
    for name, a, b in zip("qkv", gp, gr):
        err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert err < 1e-4, (name, err)


def test_bias_fully_masked_rows_multi_tile():
    """Review regression: a shared padding bias can fully mask rows in ANY
    tile (not just causal-boundary ones) — the multi-tile forward and
    fused backward must zero those rows even on interior/non-causal
    paths."""
    ks = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(ks[0], (1, 512, 2 * 64), jnp.float32)
    k = jax.random.normal(ks[1], (1, 512, 2 * 64), jnp.float32)
    v = jax.random.normal(ks[2], (1, 512, 2 * 64), jnp.float32)
    # rows 0..63 are pad queries: every key masked for them
    keep = np.ones((512, 512), bool)
    keep[:64, :] = False
    co = jax.random.normal(jax.random.key(22), q.shape, jnp.float32)

    def f(q, k, v):
        out = flash_attention_packed(q, k, v, 2, bias=jnp.asarray(keep),
                                     causal=False, block_q=256, block_k=128,
                                     bwd_block=256, interpret=True)
        return jnp.vdot(out, co), out

    (_, out), grads = jax.value_and_grad(f, (0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_array_equal(np.asarray(out[0, :64]), 0.0)
    np.testing.assert_array_equal(np.asarray(grads[0][0, :64]), 0.0)
    assert np.abs(np.asarray(out[0, 64:])).max() > 0


def test_single_tile_causal_fully_masked_rows():
    """Review regression: sq > sk causal with one k tile — query rows with
    no visible keys must output 0 (not the mean of v)."""
    q = jnp.ones((1, 256, 2 * 64), jnp.float32)
    k = jax.random.normal(jax.random.key(0), (1, 128, 2 * 64), jnp.float32)
    v = jax.random.normal(jax.random.key(1), (1, 128, 2 * 64), jnp.float32)
    out = flash_attention_packed(q, k, v, 2, causal=True, block_q=128,
                                 block_k=128, interpret=True)
    # offset = sk - sq = -128: rows 0..127 attend nothing -> zeros
    np.testing.assert_array_equal(np.asarray(out[0, :128]), 0.0)
    assert np.abs(np.asarray(out[0, 128:])).max() > 0
