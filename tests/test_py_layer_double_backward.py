"""PyLayer (user-defined autograd op) + dygraph double backward
(create_graph=True). Reference: python/paddle/autograd/py_layer.py,
GeneralGrad in paddle/fluid/eager/backward.cc:38."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer
from paddle_tpu.framework.tensor import Tensor


class _Scale(PyLayer):
    @staticmethod
    def forward(ctx, x, alpha):
        ctx.save_for_backward(x)
        ctx.alpha = alpha
        return x * alpha

    @staticmethod
    def backward(ctx, dy):
        (x,) = ctx.saved_tensor()
        return dy * ctx.alpha


class _TanhTwice(PyLayer):
    """Two tensor inputs, two outputs."""

    @staticmethod
    def forward(ctx, a, b):
        ya, yb = paddle.tanh(a), paddle.tanh(b)
        ctx.save_for_backward(ya, yb)
        return ya, yb

    @staticmethod
    def backward(ctx, dya, dyb):
        ya, yb = ctx.saved_tensor()
        return dya * (1 - ya * ya), dyb * (1 - yb * yb)


def test_pylayer_roundtrip_simple():
    x = Tensor(np.array([1.0, -2.0, 3.0], np.float32), stop_gradient=False)
    y = _Scale.apply(x, 2.5)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), 2.5, atol=1e-6)


def test_pylayer_matches_builtin_grad():
    xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    x1 = Tensor(xv, stop_gradient=False)
    a1, b1 = _TanhTwice.apply(x1 * 2.0, x1 + 1.0)
    (a1.sum() + (b1 * b1).sum()).backward()

    x2 = Tensor(xv, stop_gradient=False)
    a2, b2 = paddle.tanh(x2 * 2.0), paddle.tanh(x2 + 1.0)
    (a2.sum() + (b2 * b2).sum()).backward()
    np.testing.assert_allclose(np.asarray(x1.grad._value),
                               np.asarray(x2.grad._value), atol=1e-5)


def test_pylayer_none_grad_and_non_tensor_args():
    class PickFirst(PyLayer):
        @staticmethod
        def forward(ctx, a, b, k):
            return a * k + b.detach()

        @staticmethod
        def backward(ctx, dy):
            return dy * 3.0, None  # no grad for b

    a = Tensor(np.ones((2,), np.float32), stop_gradient=False)
    b = Tensor(np.ones((2,), np.float32), stop_gradient=False)
    out = PickFirst.apply(a, b, 3.0)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(a.grad._value), 3.0)  # user backward: dy*3
    assert b.grad is None


def test_pylayer_in_jitted_step():
    from paddle_tpu.jit.functionalize import CompiledStep

    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())

    def step(x):
        y = _Scale.apply(lin(x), 2.0)
        loss = (y * y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    cs = CompiledStep(step, stateful=[lin, opt])
    x = Tensor(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    l0 = float(cs(x)._value)
    l1 = float(cs(x)._value)
    assert l1 < l0  # training moves the loss


def test_double_backward_scalar_chain():
    # y = x^3 -> dy/dx = 3x^2 -> d2y/dx2 = 6x
    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(np.asarray(g._value), [12.0], rtol=1e-5)
    assert g.stop_gradient is False
    (g2,) = paddle.grad(g, x)
    np.testing.assert_allclose(np.asarray(g2._value), [12.0], rtol=1e-5)


def test_gradient_penalty_training():
    """WGAN-GP-style: penalty = (||d critic/d x|| - 1)^2 trains through the
    second-order path."""
    paddle.seed(0)
    lin1 = paddle.nn.Linear(3, 8)
    lin2 = paddle.nn.Linear(8, 1)
    params = lin1.parameters() + lin2.parameters()
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)

    rng = np.random.RandomState(0)
    xv = rng.randn(16, 3).astype(np.float32)

    def penalty_value():
        x = Tensor(xv, stop_gradient=False)
        score = lin2(paddle.tanh(lin1(x))).sum()
        (gx,) = paddle.grad(score, x, create_graph=True)
        norm = (gx * gx).sum(axis=1).sqrt()
        return ((norm - 1.0) ** 2).mean()

    p0 = float(penalty_value()._value)
    for _ in range(20):
        pen = penalty_value()
        pen.backward()
        opt.step()
        opt.clear_grad()
    p1 = float(penalty_value()._value)
    assert p1 < p0, f"gradient penalty did not decrease: {p0} -> {p1}"
    # parameters actually received second-order gradients
    assert all(np.isfinite(np.asarray(p._value)).all() for p in params)


def test_double_backward_through_pylayer():
    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 2.0 * x

    x = Tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = Square.apply(x)
    (g,) = paddle.grad(y, x, create_graph=True)      # 2x = 6
    np.testing.assert_allclose(np.asarray(g._value), [6.0], rtol=1e-6)
    (g2,) = paddle.grad(g, x)                         # 2
    np.testing.assert_allclose(np.asarray(g2._value), [2.0], rtol=1e-6)


def test_grad_matches_incubate_autograd():
    """VERDICT weak#10: the tape grad and the functional jax grad must agree."""
    import paddle_tpu.incubate.autograd as iag

    xv = np.random.RandomState(2).randn(5).astype(np.float32)

    def f(x):
        return (paddle.tanh(x) * x).sum()

    x1 = Tensor(xv, stop_gradient=False)
    (g_tape,) = paddle.grad(f(x1), x1)
    g_fn = iag.grad(f, Tensor(xv))
    np.testing.assert_allclose(np.asarray(g_tape._value),
                               np.asarray(g_fn._value), atol=1e-5)
