"""Round-4 API audit, second sweep: static legacy surface, sequence/CRF
ops, text datasets + Viterbi, vision models/transforms/ops, incubate
segment/graph ops, fleet role makers, utils/device/jit shims."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.utils import unique_name

rng = np.random.RandomState(0)


def t(x):
    return Tensor(np.asarray(x))


# -- viterbi / CRF -----------------------------------------------------------

def test_viterbi_decode_matches_brute_force():
    B, L, N = 2, 4, 3
    pot = rng.randn(B, L, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lens = np.array([4, 3])
    scores, paths = paddle.text.viterbi_decode(
        t(pot), t(trans), t(lens), include_bos_eos_tag=False)
    for b in range(B):
        best, bestp = -1e9, None
        for p in itertools.product(range(N), repeat=int(lens[b])):
            s = pot[b, 0, p[0]] + sum(
                trans[p[i - 1], p[i]] + pot[b, i, p[i]]
                for i in range(1, len(p)))
            if s > best:
                best, bestp = s, p
        assert abs(best - scores.numpy()[b]) < 1e-4
        assert list(paths.numpy()[b][:int(lens[b])]) == list(bestp)


def test_viterbi_decoder_class_and_crf_decoding():
    B, L, N = 2, 5, 4
    pot = rng.randn(B, L, N + 2).astype(np.float32)
    trans = rng.randn(N + 2, N + 2).astype(np.float32)
    lens = np.array([5, 4])
    dec = paddle.text.ViterbiDecoder(t(trans))
    scores, paths = dec(t(pot), t(lens))
    assert paths.shape == [B, L]
    assert (paths.numpy() < N).all()  # BOS/EOS never emitted
    with unique_name.guard():
        path2 = static.nn.crf_decoding(t(pot), length=t(lens),
                                       transition=t(trans))
    np.testing.assert_array_equal(path2.numpy(), paths.numpy())


# -- static legacy surface ---------------------------------------------------

def test_static_legacy_layers_eager():
    with unique_name.guard():
        paddle.seed(0)
        img = t(rng.randn(2, 3, 8, 8).astype(np.float32))
        y = static.nn.conv2d(img, 4, 3, padding=1, act="relu")
        assert list(y.shape) == [2, 4, 8, 8]
        z = static.nn.batch_norm(y)
        assert list(z.shape) == [2, 4, 8, 8]
        e = static.nn.embedding(t(rng.randint(0, 10, (2, 5))), (10, 6))
        assert list(e.shape) == [2, 5, 6]
        n = static.nn.layer_norm(t(rng.randn(3, 7).astype(np.float32)))
        assert list(n.shape) == [3, 7]
        w = t(rng.randn(6, 4).astype(np.float32))
        sn = static.nn.spectral_norm(w, power_iters=20)
        s = np.linalg.svd(sn.numpy(), compute_uv=False)[0]
        assert abs(s - 1.0) < 1e-3


def test_static_nce_and_case():
    with unique_name.guard():
        paddle.seed(0)
        x = t(rng.randn(6, 8).astype(np.float32))
        y = t(rng.randint(0, 20, (6,)))
        loss = static.nn.nce(x, y, 20, num_neg_samples=3)
        assert list(loss.shape) == [6, 1]
        assert np.isfinite(loss.numpy()).all()

    out = static.nn.case(
        [(t(np.array(False)), lambda: t(np.array(1.0))),
         (t(np.array(True)), lambda: t(np.array(2.0)))],
        default=lambda: t(np.array(3.0)))
    assert float(out.numpy()) == 2.0


def test_static_sequence_ops_dense_contract():
    x = t(rng.randn(2, 5, 3).astype(np.float32))
    lens = t(np.array([5, 3]))
    pooled = static.nn.sequence_pool(x, "average", length=lens)
    want = x.numpy()[1, :3].mean(axis=0)
    np.testing.assert_allclose(pooled.numpy()[1], want, rtol=1e-5)
    last = static.nn.sequence_last_step(x, lens)
    np.testing.assert_allclose(last.numpy()[1], x.numpy()[1, 2])
    rev = static.nn.sequence_reverse(x, length=lens)
    np.testing.assert_allclose(rev.numpy()[1, :3], x.numpy()[1, 2::-1])
    np.testing.assert_allclose(rev.numpy()[1, 3:], x.numpy()[1, 3:])
    sm = static.nn.sequence_softmax(x, length=lens)
    np.testing.assert_allclose(sm.numpy()[1, :, 0].sum(), 1.0, rtol=1e-5)
    assert abs(sm.numpy()[1, 3:, 0].sum()) < 1e-6


def test_static_rnn_runs():
    with unique_name.guard():
        paddle.seed(0)
        seq = t(rng.randn(4, 2, 8).astype(np.float32))  # [T, B, F]
        rnn = static.StaticRNN() if hasattr(static, "StaticRNN") \
            else static.nn.StaticRNN()
        xin = rnn.step_input(seq)
        h = rnn.memory(init=t(np.zeros((2, 8), np.float32)))
        lin = paddle.nn.Linear(16, 8)

        def step(tstep):
            import paddle_tpu.ops as ops

            nh = paddle.tanh(lin(ops.concat([xin.value(), h._slot["cur"]],
                                            axis=-1)))
            rnn.update_memory(h, nh)
            rnn.step_output(nh)

        out = rnn.run(step)
    assert list(out.shape) == [4, 2, 8]


def test_static_compat_metrics_ema_state():
    logits = t(rng.randn(8, 5).astype(np.float32))
    label = t(rng.randint(0, 5, (8, 1)))
    acc = static.accuracy(logits, label, k=5)
    assert float(acc.numpy()) == 1.0
    scores = t(np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]],
                        np.float32))
    y = t(np.array([[0], [1], [1], [0]]))
    a = static.auc(scores, y)
    assert float(a.numpy()) == 1.0  # perfectly ranked

    with unique_name.guard():
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 2)
        main = static.Program()
        with static.program_guard(main):
            xv = static.data("x", [2, 4], "float32")
            out = lin(xv)
        ema = static.ExponentialMovingAverage(0.5)
        w0 = np.asarray(lin.weight._value).copy()
        ema.update(lin.parameters())       # shadow = w0
        lin.weight._value = lin.weight._value + 1.0
        ema.update(lin.parameters())       # shadow = w0 + 0.5
        with ema.apply():
            applied = np.asarray(lin.weight._value)
        after = np.asarray(lin.weight._value)
        np.testing.assert_allclose(after, w0 + 1.0)
        np.testing.assert_allclose(applied, w0 + 0.5, rtol=1e-5)

        state = {p.name: np.asarray(p._value) * 0.0
                 for p in main.all_parameters()}
        assert static.set_program_state(main, state) >= 1
        assert np.allclose(np.asarray(lin.weight._value), 0.0)


def test_static_places_and_guards(tmp_path):
    assert len(static.cpu_places(2)) == 2
    assert static.cuda_places([0])
    with static.device_guard("cpu"):
        pass
    with pytest.raises(ValueError):
        static.device_guard("fpga").__enter__()
    ps = static.ParallelExecutor()
    assert ps is not None
    v = static.create_global_var([2, 2], 1.5, "float32")
    assert np.allclose(v.numpy(), 1.5)
    with unique_name.guard():
        p = static.create_parameter([3, 3], "float32")
        assert list(p.shape) == [3, 3]


# -- text / incubate ---------------------------------------------------------

def test_text_datasets_shapes():
    for cls in (paddle.text.Conll05st, paddle.text.Imikolov,
                paddle.text.Movielens, paddle.text.WMT14, paddle.text.WMT16):
        ds = cls()
        assert len(ds) > 0
        item = ds[0]
        assert isinstance(item, tuple)


def test_incubate_segment_and_graph_ops():
    inc = paddle.incubate
    d = t(np.arange(12, dtype=np.float32).reshape(6, 2))
    ids = t(np.array([0, 0, 1, 1, 1, 2]))
    np.testing.assert_allclose(inc.segment_sum(d, ids).numpy()[0], [2, 4])
    np.testing.assert_allclose(inc.segment_mean(d, ids).numpy()[1], [6, 7])
    np.testing.assert_allclose(inc.segment_max(d, ids).numpy()[2], [10, 11])
    np.testing.assert_allclose(inc.segment_min(d, ids).numpy()[1], [4, 5])

    x = t(np.eye(3, dtype=np.float32))
    out = inc.graph_send_recv(x, t(np.array([0, 1, 2, 0])),
                              t(np.array([1, 2, 0, 2])), "sum")
    np.testing.assert_allclose(out.numpy()[2], [1, 1, 0])

    src, dst, nodes = inc.graph_reindex(
        t(np.array([5, 9])), t(np.array([9, 7, 5, 3])),
        t(np.array([2, 2])))
    assert nodes.numpy().tolist() == [5, 9, 7, 3]
    assert dst.numpy().tolist() == [0, 0, 1, 1]

    # CSC graph: node 0 <- {1, 2}, node 1 <- {0}, node 2 <- {}
    row = t(np.array([1, 2, 0]))
    colptr = t(np.array([0, 2, 3, 3]))
    neigh, cnt = inc.graph_sample_neighbors(row, colptr,
                                            t(np.array([0, 2])),
                                            sample_size=-1)
    assert cnt.numpy().tolist() == [2, 0]
    assert sorted(neigh.numpy().tolist()) == [1, 2]

    # advisor regression: duplicate centers must map dst through the
    # first-seen order table, not positional arange
    src2, dst2, nodes2 = inc.graph_reindex(
        t(np.array([5, 5, 9])), t(np.array([9, 7, 5, 3])),
        t(np.array([1, 1, 2])))
    assert nodes2.numpy().tolist() == [5, 9, 7, 3]
    assert dst2.numpy().tolist() == [0, 0, 1, 1]

    sm = inc.softmax_mask_fuse_upper_triangle(
        t(np.zeros((1, 1, 4, 4), np.float32)))
    np.testing.assert_allclose(sm.numpy()[0, 0, 0], [1, 0, 0, 0])
    assert float(inc.identity_loss(t(np.array([2.0, 4.0])),
                                   "mean").numpy()) == 3.0
    # advisor regression: integer reduction codes are 0=sum, 1=mean, 2=none
    assert float(inc.identity_loss(t(np.array([2.0, 4.0])), 0).numpy()) == 6.0
    assert float(inc.identity_loss(t(np.array([2.0, 4.0])), 1).numpy()) == 3.0
    assert inc.identity_loss(t(np.array([2.0, 4.0])),
                             2).numpy().tolist() == [2.0, 4.0]


# -- fleet role makers / misc ------------------------------------------------

def test_fleet_role_maker_and_util(monkeypatch):
    from paddle_tpu.distributed import fleet

    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    rm = fleet.PaddleCloudRoleMaker(is_collective=True)
    assert rm.worker_index() == 1 and rm.worker_num() == 4
    util = fleet.UtilBase()
    shard = util.get_file_shard([f"f{i}" for i in range(10)])
    assert shard == ["f3", "f4", "f5"]  # rank 1 of 4 over 10 files

    gen = _Gen()
    rows = gen.run_from_memory(["a b", "c"])
    assert rows == ["words 2 a b", "words 1 c"]


class _Gen:
    pass


from paddle_tpu.distributed.fleet import MultiSlotStringDataGenerator  # noqa: E402


class _Gen(MultiSlotStringDataGenerator):  # noqa: F811
    def generate_sample(self, line):
        def gen():
            yield [("words", line.split())]

        return gen


# -- vision ------------------------------------------------------------------

def test_vision_new_models_forward():
    from paddle_tpu.vision import models as M

    x = t(rng.randn(1, 3, 64, 64).astype(np.float32))
    with unique_name.guard():
        paddle.seed(0)
        m = M.shufflenet_v2_x0_25(num_classes=7)
        m.eval()
        assert list(m(x).shape) == [1, 7]
        g = M.googlenet(num_classes=7)
        g.eval()
        out, a1, a2 = g(t(rng.randn(1, 3, 96, 96).astype(np.float32)))
        assert list(out.shape) == [1, 7] and list(a1.shape) == [1, 7]
        r = M.resnext101_32x4d(num_classes=7)
        assert r is not None  # construction exercises the grouped blocks


def test_vision_functional_transforms():
    from paddle_tpu.vision import transforms as T

    img = (rng.rand(12, 16, 3) * 255).astype(np.uint8)
    assert T.hflip(img).shape == img.shape
    np.testing.assert_array_equal(T.hflip(T.hflip(img)), img)
    assert T.center_crop(img, 8).shape == (8, 8, 3)
    assert T.crop(img, 2, 3, 4, 5).shape == (4, 5, 3)
    assert T.pad(img, 2).shape == (16, 20, 3)
    b = T.adjust_brightness(img, 2.0)
    assert b.mean() >= img.mean()
    gray = T.to_grayscale(img)
    assert gray.shape == (12, 16, 1)
    rot = T.rotate(img, 90)
    assert rot.shape == img.shape
    aff = T.affine(img, 0, (0, 0), 1.0, 0.0)
    np.testing.assert_array_equal(aff, img)  # identity affine
    ident = T.perspective(img, [[0, 0], [15, 0], [15, 11], [0, 11]],
                          [[0, 0], [15, 0], [15, 11], [0, 11]])
    np.testing.assert_array_equal(ident, img)
    er = T.erase(img, 2, 2, 4, 4, 0)
    assert (np.asarray(er)[2:6, 2:6] == 0).all()
    hue = T.adjust_hue(img, 0.0)
    np.testing.assert_allclose(hue.astype(int), img.astype(int), atol=2)


def test_vision_ops_additions(tmp_path):
    from paddle_tpu.vision import ops as V

    x = t(rng.randn(1, 8, 16, 16).astype(np.float32))
    boxes = t(np.array([[0., 0., 8., 8.]], np.float32))
    bn = t(np.array([1], np.int32))
    assert list(V.RoIAlign(4)(x, boxes, bn).shape) == [1, 8, 4, 4]
    assert list(V.RoIPool(4)(x, boxes, bn).shape) == [1, 8, 4, 4]
    assert list(V.PSRoIPool(2)(x, boxes, bn).shape) == [1, 2, 2, 2]

    feat = t(rng.randn(2, 3 * 85, 4, 4).astype(np.float32))
    img = t(np.array([[128, 128], [128, 128]], np.int32))
    b, s = V.yolo_box(feat, img, [10, 13, 16, 30, 33, 23], 80, 0.01, 32)
    assert list(b.shape) == [2, 48, 4] and list(s.shape) == [2, 48, 80]
    bx = b.numpy()
    assert (bx >= 0).all() and (bx <= 127).all()  # clipped to image

    gtb = t((rng.rand(2, 5, 4) * 0.5 + 0.2).astype(np.float32))
    gtl = t(rng.randint(0, 80, (2, 5)))
    loss = V.yolo_loss(feat, gtb, gtl, [10, 13, 16, 30, 33, 23], [0, 1, 2],
                       80, 0.7, 32)
    assert list(loss.shape) == [2] and np.isfinite(loss.numpy()).all()

    from PIL import Image
    import io

    img_np = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img_np).save(buf, format="JPEG")
    p = str(tmp_path / "t.jpg")
    with open(p, "wb") as f:
        f.write(buf.getvalue())
    raw = V.read_file(p)
    dec = V.decode_jpeg(raw)
    assert list(dec.shape) == [3, 8, 8]


def test_jit_traced_layer(tmp_path):
    with unique_name.guard():
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 2)
        x = t(rng.randn(2, 4).astype(np.float32))
        outs, traced = paddle.jit.TracedLayer.trace(lin, [x])
        assert list(outs.shape) == [2, 2]
        path = str(tmp_path / "traced")
        traced.save_inference_model(path)
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(np.asarray(loaded(x)._value),
                                   outs.numpy(), rtol=1e-5)
    paddle.jit.set_code_level(50)
    paddle.jit.set_verbosity(3)


def test_utils_helpers():
    paddle.utils.require_version("0.0.1")
    with pytest.raises(Exception):
        paddle.utils.require_version("999.0.0")
    assert paddle.utils.try_import("json") is not None
    with pytest.raises(ImportError):
        paddle.utils.try_import("definitely_not_a_module_xyz")

    calls = []

    @paddle.utils.deprecated(update_to="new_fn", since="2.0")
    def old_fn():
        calls.append(1)
        return 7

    with pytest.warns(DeprecationWarning):
        assert old_fn() == 7


# -- review-fix regressions --------------------------------------------------

def test_require_version_accepts_current_exact():
    from paddle_tpu.version import full_version

    paddle.utils.require_version(full_version)  # exact pin must pass


def test_data_norm_scale_shift_and_detached_stats():
    with unique_name.guard():
        x = t(rng.randn(8, 4).astype(np.float32))
        x.stop_gradient = False
        y = static.nn.data_norm(x, enable_scale_and_shift=True)
        assert list(y.shape) == [8, 4]
        y.sum().backward()
        assert np.isfinite(np.asarray(x.grad._value)).all()
    np.testing.assert_allclose(y.numpy().mean(0), 0.0, atol=1e-5)


def test_multi_box_head_locs_align_with_priors():
    with unique_name.guard():
        paddle.seed(0)
        feats = [t(rng.randn(1, 8, 4, 4).astype(np.float32)),
                 t(rng.randn(1, 8, 2, 2).astype(np.float32))]
        image = t(rng.randn(1, 3, 64, 64).astype(np.float32))
        locs, confs, boxes, variances = static.nn.multi_box_head(
            feats, image, base_size=64, num_classes=3,
            aspect_ratios=[[1.0, 2.0], [1.0, 2.0]], min_ratio=20,
            max_ratio=90, flip=True)
    # the row counts of predictions and priors MUST agree (review fix:
    # aspect ratio 1.0 was double-counted in the conv width)
    assert locs.shape[1] == boxes.shape[0] == variances.shape[0]
    assert confs.shape[1] == boxes.shape[0]


def test_yolo_loss_respects_ignore_thresh():
    feat_np = rng.randn(1, 3 * 15, 4, 4).astype(np.float32)
    gtb = t(np.array([[[0.5, 0.5, 0.4, 0.4]]], np.float32))
    gtl = t(np.array([[2]]))
    from paddle_tpu.vision import ops as V

    # permissive threshold ignores more negatives => loss can only shrink
    strict = float(V.yolo_loss(t(feat_np), gtb, gtl,
                               [10, 13, 16, 30, 33, 23], [0, 1, 2], 10,
                               0.99, 32).numpy()[0])
    loose = float(V.yolo_loss(t(feat_np), gtb, gtl,
                              [10, 13, 16, 30, 33, 23], [0, 1, 2], 10,
                              0.0, 32).numpy()[0])
    assert loose <= strict
