"""ONNX artifact production (round-5 VERDICT missing #4): the static
Program -> ONNX emitter writes real ModelProto files for the vision-zoo
op set, round-tripped through the in-tree protobuf reader.
Reference: python/paddle/onnx/export.py (paddle2onnx)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.save_load import InputSpec
from paddle_tpu.onnx import export, load_structure
from paddle_tpu.utils import unique_name


def test_lenet_onnx_structure(tmp_path):
    from paddle_tpu.vision.models import LeNet

    with unique_name.guard():
        paddle.seed(0)
        model = LeNet(num_classes=10)
    path = export(model, str(tmp_path / "lenet"),
                  input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    s = load_structure(path)
    assert s["ir_version"] == 8 and s["opset"] == 13
    ops = [n["op_type"] for n in s["nodes"]]
    assert ops.count("Conv") == 2
    assert ops.count("MaxPool") == 2
    assert ops.count("Gemm") == 3
    assert "Flatten" in ops and "Relu" in ops
    assert s["inputs"] == ["input_0"] and len(s["outputs"]) == 1
    # the graph is connected: every node input is a graph input, an
    # initializer, or a prior node's output
    known = set(s["inputs"]) | set(s["initializers"])
    for n in s["nodes"]:
        for i in n["inputs"]:
            assert i in known, (n["op_type"], i)
        known |= set(n["outputs"])
    assert s["outputs"][0] in known


def test_lenet_onnx_weights_roundtrip(tmp_path):
    """Initializer payloads are the exact fp32 parameter values (checked
    through the wire-format reader, not the writer's own dicts)."""
    from paddle_tpu.vision.models import LeNet

    with unique_name.guard():
        paddle.seed(1)
        model = LeNet(num_classes=10)
    path = export(model, str(tmp_path / "lenet_w"),
                  input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    s = load_structure(path)
    conv1_w = np.asarray(model.features[0].weight._value)
    gemm_ws = [a for a in s["initializers"].values()
               if a.shape == tuple(model.fc[0].weight.shape)]
    conv_ws = [a for a in s["initializers"].values()
               if a.shape == conv1_w.shape]
    assert any(np.allclose(a, conv1_w) for a in conv_ws)
    fc1_w = np.asarray(model.fc[0].weight._value)
    assert any(np.allclose(a, fc1_w) for a in gemm_ws)


def test_resnet18_onnx_structure(tmp_path):
    from paddle_tpu.vision.models import resnet18

    with unique_name.guard():
        paddle.seed(2)
        model = resnet18(num_classes=10)
    path = export(model, str(tmp_path / "r18"),
                  input_spec=[InputSpec([None, 3, 32, 32], "float32")])
    s = load_structure(path)
    ops = [n["op_type"] for n in s["nodes"]]
    assert ops.count("Conv") == 20
    assert ops.count("BatchNormalization") == 20
    assert ops.count("Add") == 8            # residual joins
    assert ops.count("GlobalAveragePool") == 1
    assert ops.count("Gemm") == 1
    # BatchNormalization input order is (x, scale, B, mean, var): scale is
    # all-ones at init, running var is all-ones too, but mean is zeros —
    # check slot 3 maps to the zeros initializer
    bn = next(n for n in s["nodes"] if n["op_type"] == "BatchNormalization")
    mean_init = s["initializers"][bn["inputs"][3]]
    assert np.allclose(mean_init, 0.0)
    var_init = s["initializers"][bn["inputs"][4]]
    assert np.allclose(var_init, 1.0)


def test_unmapped_op_raises_with_name(tmp_path):
    class Odd(paddle.nn.Layer):
        def forward(self, x):
            return paddle.erf(x)

    with pytest.raises(NotImplementedError, match="erf"):
        export(Odd(), str(tmp_path / "odd"),
               input_spec=[InputSpec([None, 4], "float32")])


def test_export_requires_input_spec(tmp_path):
    with pytest.raises(ValueError, match="input_spec"):
        export(paddle.nn.Linear(2, 2), str(tmp_path / "x"))


def test_string_padding_raises_clearly(tmp_path):
    class SamePad(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.c = paddle.nn.Conv2D(1, 2, 3, padding="same")

        def forward(self, x):
            return self.c(x)

    with pytest.raises(NotImplementedError, match="padding"):
        export(SamePad(), str(tmp_path / "sp"),
               input_spec=[InputSpec([None, 1, 8, 8], "float32")])


def test_unsupported_opset_raises(tmp_path):
    with pytest.raises(ValueError, match="opset"):
        export(paddle.nn.Linear(2, 2), str(tmp_path / "o9"),
               input_spec=[InputSpec([None, 2], "float32")],
               opset_version=9)


def test_flatten_start2_and_3d_linear_and_inclusive_pool(tmp_path):
    """Review regressions: general flatten emits a batch-polymorphic
    Reshape; >2-D linear emits MatMul+Add (Gemm is rank-2 only);
    exclusive=False avg pool carries count_include_pad=1."""
    import paddle_tpu.nn.functional as F

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(6, 5)

        def forward(self, x):                 # x: [b, 2, 3, 6]
            y = self.lin(x)                   # 4-D linear -> MatMul+Add
            y = paddle.flatten(y, start_axis=2)   # [b, 2, 15] -> Reshape
            return y

    with unique_name.guard():
        paddle.seed(9)
        m = M()
    path = export(m, str(tmp_path / "gen"),
                  input_spec=[InputSpec([None, 2, 3, 6], "float32")])
    s = load_structure(path)
    ops = [n["op_type"] for n in s["nodes"]]
    assert "MatMul" in ops and "Add" in ops and "Gemm" not in ops
    assert "Reshape" in ops and "Flatten" not in ops
    reshape = next(n for n in s["nodes"] if n["op_type"] == "Reshape")
    tgt = s["initializers"][reshape["inputs"][1]]
    assert tgt.tolist() == [-1, 2, 15]

    class P2(paddle.nn.Layer):
        def forward(self, x):
            return F.avg_pool2d(x, 2, stride=2, padding=1, exclusive=False)

    path2 = export(P2(), str(tmp_path / "pool"),
                   input_spec=[InputSpec([None, 2, 8, 8], "float32")])
    s2 = load_structure(path2)
    assert [n["op_type"] for n in s2["nodes"]] == ["AveragePool"]

    class P0(paddle.nn.Layer):
        def forward(self, x):
            return paddle.flatten(x, start_axis=0)

    with pytest.raises(NotImplementedError, match="batch"):
        export(P0(), str(tmp_path / "f0"),
               input_spec=[InputSpec([None, 4], "float32")])


def test_int32_initializer_roundtrips_as_int32(tmp_path):
    """int32 initializers must emit ONNX elem type 6 with <i4 raw data and
    parse back as int32 (previously silently upcast to INT64)."""
    class M(paddle.nn.Layer):
        def forward(self, x):
            return paddle.reshape(x, shape=[-1, 6])

    with unique_name.guard():
        m = M()
    path = export(m, str(tmp_path / "r32"),
                  input_spec=[InputSpec([None, 2, 3], "float32")])
    s = load_structure(path)
    reshape = next(n for n in s["nodes"] if n["op_type"] == "Reshape")
    shape_init = s["initializers"][reshape["inputs"][1]]
    assert shape_init.dtype == np.int64  # reshape targets stay int64

    # direct codec check for the int32 lane
    from paddle_tpu.onnx import _proto as P
    from paddle_tpu.onnx._export import _tensor

    raw = _tensor("idx", np.asarray([1, 2, 3], np.int32))
    t = P.parse(raw)
    assert t[2][0] == 6                      # TensorProto elem type INT32
    assert t[9][0] == np.asarray([1, 2, 3], "<i4").tobytes()
    back = np.frombuffer(t[9][0], "<i4")
    assert back.dtype == np.int32 and back.tolist() == [1, 2, 3]
