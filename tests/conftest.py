"""Test harness config.

Per SURVEY.md §4: tests run on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``) so every collective/parallelism
strategy is exercised without TPU hardware; numeric checks pin matmul
precision to HIGHEST (TPU default bf16 matmuls would break finite-difference
gradient comparisons)."""
import os

# hard override: the environment presets JAX_PLATFORMS=axon (TPU tunnel) and
# its sitecustomize imports jax at interpreter start, so env vars are too
# late — switch platform via jax.config before any backend use. Unit tests
# must run on the virtual 8-device CPU mesh regardless of hardware.
# PADDLE_TPU_HW_TESTS=1 opts out, keeping the real TPU backend for the
# hardware-only tests (in-kernel PRNG dropout etc.) that skip on CPU.
_HW = os.environ.get("PADDLE_TPU_HW_TESTS") == "1"
if not _HW:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax

if not _HW:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# normalize the jax surface (jax.shard_map et al.) before test modules run
# their own `from jax import shard_map` imports — conftest executes first
from paddle_tpu.framework.jax_compat import ensure_jax_compat

ensure_jax_compat()

# Persistent compilation cache: the eager path compiles one executable per
# (op, shape) — cache them across tests and across pytest runs. The dir is
# keyed by the host CPU's feature set: this box's pool mixes machine types,
# and XLA:CPU AOT executables cached by a host with (e.g.) prefer-no-scatter
# SIGABRT when loaded on one without it (seen as cpu_aot_loader "machine
# type doesn't match" errors followed by a fatal Abort mid-suite).
#
# KNOWN HAZARD (observed 2026-08, reproduces on the untouched seed commit):
# on the current pool host even a SAME-host cache round-trip of the
# test_models_bert_vision executables is broken — a cold run populates the
# cache and passes, the next (warm) run dies mid-file (a python-level
# failure in the fused-MLM test followed by SIGSEGV/SIGABRT, crash stack in
# copy.deepcopy or CompiledStep dispatch). Until the runtime is fixed, a
# crashed/warm suite is recovered by `rm -rf /tmp/jax_pt_cache_*` — tier-1
# runs green from a cold cache.
import hashlib

try:
    _cpuinfo = open("/proc/cpuinfo").read()
    _lines = _cpuinfo.splitlines()
    _flags_line = next((l for l in _lines if l.startswith("flags")), "")
    # include the model line too: pool machines with IDENTICAL cpuinfo
    # flags can still differ in XLA-derived target features
    # (prefer-no-scatter/-gather), and a key collision SIGABRTs mid-suite
    # when an AOT executable from the other machine type loads
    _model_line = next((l for l in _lines if l.startswith("model name")), "")
    # the visible core count sways XLA:CPU target tuning (prefer-no-scatter
    # et al.) even on identical silicon — key on it too
    _cpu_key = hashlib.sha1(
        (_flags_line + _model_line + f"n{os.cpu_count()}").encode()
    ).hexdigest()[:12]
except OSError:
    _cpu_key = "generic"
jax.config.update("jax_compilation_cache_dir", f"/tmp/jax_pt_cache_{_cpu_key}")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
