"""Native (C++) runtime components: TCPStore + host tracer.

Reference: ``distributed/store/tcp_store.cc`` (rendezvous KV + barriers) and
``platform/profiler/host_tracer.cc`` (RecordEvent sink). Both are compiled
from ``paddle_tpu/core/native/*.cc`` with g++ and bound via ctypes.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from paddle_tpu.core import TCPStore, load_native


pytestmark = pytest.mark.skipif(
    load_native() is None, reason="native toolchain unavailable")


def test_store_set_get_add():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10)
    try:
        master.set("k", b"v1")
        assert master.get("k") == b"v1"
        master.set("k", "v2")            # str values accepted
        assert master.get("k") == b"v2"
        assert master.add("ctr", 3) == 3
        assert master.add("ctr", -1) == 2
        master.wait(["k"])               # existing key returns immediately
    finally:
        master.close()


def test_store_get_blocks_until_set():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10)
    client = TCPStore("127.0.0.1", master.port, is_master=False, timeout=10)
    try:
        got = {}

        def getter():
            got["v"] = client.get("late-key", timeout=5)

        t = threading.Thread(target=getter)
        t.start()
        master.set("late-key", b"payload")
        t.join(5)
        assert got.get("v") == b"payload"
    finally:
        client.close()
        master.close()


def test_store_timeout():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10)
    try:
        with pytest.raises(Exception, match="timeout"):
            master.get("never-set", timeout=0.2)
    finally:
        master.close()


def test_store_large_value():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10)
    try:
        big = os.urandom(300_000)  # > the 64 KiB first-try buffer
        master.set("big", big)
        assert master.get("big") == big
    finally:
        master.close()


_WORKER = r"""
import sys
from paddle_tpu.core import TCPStore

rank, port = int(sys.argv[1]), int(sys.argv[2])
store = TCPStore("127.0.0.1", port, is_master=False, world_size=2, timeout=30)
store.set(f"rank{rank}/endpoint", f"10.0.0.{rank}:8{rank}00")
peer = 1 - rank
val = store.get(f"rank{peer}/endpoint").decode()
assert val == f"10.0.0.{peer}:8{peer}00", val
store.barrier("ready", world_size=2)
n = store.add("done", 1)
print(f"rank{rank} OK peer={val} done={n}")
store.close()
"""


def test_store_two_process_rendezvous(tmp_path):
    """The reference's test_tcp_store pattern: real processes exchange
    endpoints through the store and pass a barrier."""
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=30)
    try:
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        env = dict(os.environ, PYTHONPATH=os.getcwd())
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(r), str(master.port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
                text=True)
            for r in range(2)
        ]
        outs = [p.communicate(timeout=120)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs
        assert "rank0 OK peer=10.0.0.1:8100" in outs[0]
        assert "rank1 OK peer=10.0.0.0:8000" in outs[1]
        assert master.get("done")  # counter exists
    finally:
        master.close()


def test_native_host_tracer_feeds_profiler(tmp_path):
    import paddle_tpu.profiler as profiler
    from paddle_tpu.profiler.profiler import _native_state

    trace_path = str(tmp_path / "trace.json")
    done = {}

    def on_ready(prof):
        prof.export(trace_path)
        done["ok"] = True

    with profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                           on_trace_ready=on_ready) as p:
        assert _native_state["active"], "native tracer should be the sink"
        with profiler.RecordEvent("native_span"):
            np.dot(np.ones((64, 64)), np.ones((64, 64)))
        with profiler.RecordEvent("other_span", "Operator"):
            pass
        p.step()
    assert done.get("ok")
    with open(trace_path) as f:
        data = json.load(f)
    names = {e["name"] for e in data["traceEvents"]}
    assert "native_span" in names and "other_span" in names
    cats = {e["name"]: e["cat"] for e in data["traceEvents"]}
    assert cats["other_span"] == "Operator"  # event type survives the dump
