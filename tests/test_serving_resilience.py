"""Serving-tier resilience (ISSUE 10): deadlines, admission control /
load shedding, OOM-safe degraded decode, drain accounting and the
deterministic chaos inject points.

Contracts under test:
  * every request, on every path, ends with EXACTLY ONE terminal
    ``finish_reason`` from ``serving.FINISH_REASONS``;
  * an injected OOM mid-decode evicts exactly the largest-footprint
    victim and the SURVIVORS' token streams are identical to a clean run
    (slot isolation survives the degraded tick);
  * deadline / queue-wait expiry evicts with ``timeout`` and hands the
    freed slot to the next queued request in the same tick;
  * a full bounded queue (and the cost-aware admission policy, and an
    injected ``serve.admit`` fault) sheds at submit with the counter;
  * ``drain()``/``shutdown()`` terminate ALL outstanding work with
    ``drained`` — nothing disappears silently;
  * readers of the retired ``serve.requests_in_flight``/``queue_depth``
    gauges stay absent-safe (PR 8 NOTE: retired == absent, not 0);
  * ``fault.inject`` rejects unknown points exactly like unknown kinds,
    and the ``stall`` kind sleeps instead of raising.

Everything is deterministic: ``retry_sleep`` is stubbed, faults are armed
at fixed hit counts, and the OOM victim choice is a (footprint, slot) max.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fault import inject
from paddle_tpu.fault.retry import TransientError
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler import telemetry, tracing
from paddle_tpu.serving import (
    FINISH_REASONS,
    CostAwareAdmission,
    GenerationEngine,
    Request,
    Scheduler,
)
from paddle_tpu.utils import unique_name


def _gpt(seed=3, max_pos=64):
    with unique_name.guard():
        paddle.seed(seed)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=max_pos, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    return model


@pytest.fixture(scope="module")
def eng():
    """One warmed 2-slot engine shared by the module: the resilience
    paths never compare against an eager reference, so sharing compiled
    executables (and the persistent cache) across tests is safe and keeps
    the suite fast. Prefill fully resets a slot on admit, so cache state
    left by one test cannot leak into the next."""
    model = _gpt()
    e = GenerationEngine(model, max_batch=2, max_len=64,
                         prefill_buckets=(8, 16))
    e.prefill(0, [1] * 7)
    e.prefill(0, [1] * 12)
    e.decode_once(np.zeros(2, np.int32))
    return e


@pytest.fixture(autouse=True)
def _clean_faults():
    inject.disarm_all()
    yield
    inject.disarm_all()


def _sched(eng, **kw):
    kw.setdefault("retry_sleep", lambda s: None)  # tests never sleep
    return Scheduler(eng, **kw)


def _reqs(n, seed=5, max_new=6, vocab=97):
    rng = np.random.RandomState(seed)
    return [Request(prompt=rng.randint(0, vocab,
                                       int(rng.randint(3, 14))).tolist(),
                    max_new_tokens=max_new) for _ in range(n)]


def _assert_full_accounting(sched, submitted):
    assert len(sched.finished) == len(submitted)
    assert len({r.rid for r in sched.finished}) == len(submitted)
    for r in submitted:
        assert r.finished, f"rid {r.rid} never reached a terminal state"
        assert r.finish_reason in FINISH_REASONS, r.finish_reason


# ---------------------------------------------------------------------------
# OOM-safe degraded decode
# ---------------------------------------------------------------------------
def test_oom_mid_decode_evicts_victim_survivors_match_clean(eng):
    prompts = [r.prompt for r in _reqs(4, seed=8)]
    # clean reference streams
    clean = _sched(eng)
    clean_reqs = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    for r in clean_reqs:
        clean.submit(r)
    clean.run()

    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng)
        reqs = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
        for r in reqs:
            sched.submit(r)
        inject.arm("oom", "serve.decode", at=2)
        fin = sched.run()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()

    _assert_full_accounting(sched, reqs)
    victims = [r for r in fin if r.finish_reason == "oom_evicted"]
    assert len(victims) == 1
    # deterministic victim: largest (prompt + generated) footprint among
    # the actives at the faulted tick, highest slot on ties
    assert counters["serve.oom_evictions"] == 1
    assert counters["serve.degraded_steps"] == 1
    # survivors stream EXACTLY the clean tokens — the degraded tick is
    # invisible to the slots that kept their cache
    survivors = [r for r in reqs if r.finish_reason in ("eos", "length")]
    assert survivors, "OOM eviction took out every request"
    for r, ref in zip(reqs, clean_reqs):
        if r.finish_reason in ("eos", "length"):
            assert r.tokens == ref.tokens, f"rid {r.rid} diverged"


def test_oom_during_prefill_evicts_active_victim_then_admits(eng):
    sched = _sched(eng)
    first, second = _reqs(2, seed=9)
    sched.submit(first)
    sched.step()  # first is active
    assert first.slot is not None
    inject.arm("oom", "serve.prefill", at=1)
    sched.submit(second)
    sched.run()
    _assert_full_accounting(sched, [first, second])
    # the only active request was the only possible victim; the freed HBM
    # let the retried prefill succeed and second finished normally
    assert first.finish_reason == "oom_evicted"
    assert second.finish_reason == "length"
    assert len(second.tokens) == second.max_new_tokens


# ---------------------------------------------------------------------------
# deadlines and queue-wait budgets
# ---------------------------------------------------------------------------
def test_deadline_expiry_evicts_with_timeout_and_frees_slot(eng):
    sched = _sched(eng)
    hog_a, hog_b, waiter = _reqs(3, seed=10, max_new=8)
    sched.submit(hog_a)
    sched.submit(hog_b)
    sched.step()  # both slots taken
    sched.submit(waiter)
    sched.step()
    assert waiter.slot is None  # still queued: no free slot
    # the first hog's total-latency budget expires mid-serve
    hog_a.deadline_s = 0.0
    sched.step()
    assert hog_a.finish_reason == "timeout"
    assert hog_a.tokens, "an admitted request keeps its partial tokens"
    # the freed slot went to the waiter IN THE SAME TICK (expire runs
    # before admit)
    assert waiter.slot == hog_a.slot
    sched.run()
    _assert_full_accounting(sched, [hog_a, hog_b, waiter])
    assert waiter.finish_reason == "length"


def test_queue_wait_budget_times_out_without_ever_taking_a_slot(eng):
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng)
        hogs = _reqs(2, seed=11, max_new=4)
        for r in hogs:
            sched.submit(r)
        sched.step()
        impatient = Request(prompt=[1, 2, 3], max_new_tokens=4,
                            max_queue_s=0.0)
        sched.submit(impatient)
        sched.step()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert impatient.finish_reason == "timeout"
    assert impatient.slot is None and impatient.tokens == []
    assert counters["serve.timeouts"] == 1
    assert (sched._step_idx - 1, "timeout", impatient.rid, None) \
        in sched.events


# ---------------------------------------------------------------------------
# admission control + load shedding
# ---------------------------------------------------------------------------
def test_full_queue_sheds_at_submit_with_counter(eng):
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng, max_queue=2)
        reqs = _reqs(4, seed=12)
        out = [sched.submit(r) for r in reqs]
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert [r.finish_reason for r in out] == [None, None, "shed", "shed"]
    assert out[2] is reqs[2]  # the caller gets its own request back
    assert counters["serve.shed"] == 2
    assert counters["serve.submitted"] == 4
    shed_events = [e for e in sched.events if e[1] == "shed"]
    assert len(shed_events) == 2
    # shed requests are already terminal — the run serves the queued two
    sched.run()
    _assert_full_accounting(sched, reqs)


def test_cost_aware_admission_sheds_on_backlog(eng):
    # cap below two requests' worth: the second submit must shed
    policy = CostAwareAdmission(max_backlog_tokens=20)
    sched = _sched(eng, admission=policy)
    a = Request(prompt=[1] * 6, max_new_tokens=6)   # bucket 8 + 6 = 14
    b = Request(prompt=[1] * 6, max_new_tokens=6)
    sched.submit(a)
    sched.submit(b)
    assert a.finish_reason is None and b.finish_reason == "shed"
    # active requests count their REMAINING budget toward the backlog
    sched.run()
    assert a.finish_reason == "length"
    c = Request(prompt=[1] * 6, max_new_tokens=6)
    sched.submit(c)
    assert c.finish_reason is None  # backlog drained: admitted again
    sched.run()


def test_injected_admit_fault_sheds_deterministically(eng):
    inject.arm("error", "serve.admit", at=2)
    sched = _sched(eng)
    reqs = _reqs(3, seed=13, max_new=3)
    out = [sched.submit(r) for r in reqs]
    assert [r.finish_reason for r in out] == [None, "shed", None]
    sched.run()
    _assert_full_accounting(sched, reqs)


# ---------------------------------------------------------------------------
# transient prefill faults: retry then terminal error
# ---------------------------------------------------------------------------
def test_prefill_transient_fault_retries_and_stream_is_unperturbed(eng):
    ref = _sched(eng)
    ref_req = Request(prompt=[7, 8, 9, 10], max_new_tokens=5)
    ref.submit(ref_req)
    ref.run()

    inject.arm("error", "serve.prefill", at=1)
    sched = _sched(eng)
    req = Request(prompt=[7, 8, 9, 10], max_new_tokens=5)
    sched.submit(req)
    sched.run()
    assert req.finish_reason == "length"
    assert req.tokens == ref_req.tokens  # the retry is invisible


def test_prefill_faults_past_retry_budget_fail_terminally(eng):
    # three at=1 entries: check() consumes one per hit (it breaks after a
    # fire, so later entries don't see that hit) — every attempt of the
    # default tries=3 budget faults, the 4th check (healthy) runs clean
    for _ in range(3):
        inject.arm("error", "serve.prefill", at=1)
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng)
        doomed, healthy = _reqs(2, seed=14, max_new=3)
        sched.submit(doomed)
        sched.submit(healthy)
        sched.run()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    _assert_full_accounting(sched, [doomed, healthy])
    assert doomed.finish_reason == "error"
    assert doomed.slot is None and doomed.tokens == []
    assert counters["serve.errors"] == 1
    # the slot the failed prefill borrowed went back to the pool
    assert healthy.finish_reason == "length"
    assert ("error", doomed.rid) in [(e[1], e[2]) for e in sched.events]


# ---------------------------------------------------------------------------
# drain / shutdown accounting
# ---------------------------------------------------------------------------
def test_drain_accounts_for_queued_and_active_requests(eng):
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng)
        reqs = _reqs(4, seed=15, max_new=8)
        for r in reqs:
            sched.submit(r)
        sched.step()  # two active (slots), two still queued
        fin = sched.drain()
        tm = telemetry.get_telemetry()
        counters, gauges = tm.counters(), tm.gauges()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert fin is sched.finished
    _assert_full_accounting(sched, reqs)
    assert all(r.finish_reason == "drained" for r in reqs)
    actives = [r for r in reqs if r.slot is not None]
    assert actives and all(r.tokens for r in actives)  # partials kept
    queued = [r for r in reqs if r.slot is None]
    assert queued and all(not r.tokens for r in queued)
    assert counters["serve.drained"] == 4
    # drain retires the lifecycle gauges (PR 8 stale-gauge contract)
    assert "serve.requests_in_flight" not in gauges
    assert "serve.queue_depth" not in gauges


def test_shutdown_drains_midflight_and_is_idempotent(eng):
    sched = _sched(eng)
    reqs = _reqs(3, seed=16, max_new=8)
    for r in reqs:
        sched.submit(r)
    sched.step()
    sched.shutdown()
    _assert_full_accounting(sched, reqs)
    assert all(r.finish_reason == "drained" for r in reqs)
    sched.shutdown()  # second shutdown: no double accounting
    assert len(sched.finished) == 3


def test_mixed_chaos_everything_reaches_exactly_one_terminal_state(eng):
    inject.arm("error", "serve.prefill", at=2)
    inject.arm("oom", "serve.decode", at=4)
    sched = _sched(eng, max_queue=3)
    submitted = [sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=4,
                                      deadline_s=0.0))]
    for r in _reqs(6, seed=17, max_new=4):
        submitted.append(sched.submit(r))
    sched.run()
    sched.shutdown()
    _assert_full_accounting(sched, submitted)
    reasons = {r.finish_reason for r in submitted}
    assert "shed" in reasons and "timeout" in reasons


# ---------------------------------------------------------------------------
# retired-gauge reader safety (satellite regression)
# ---------------------------------------------------------------------------
def test_retired_gauge_readers_are_absent_safe():
    """PR 8 NOTE: after drain the serve gauges are ABSENT, not 0 — every
    reader must .get() with a default. Covers the SLO value fallback and
    the stdlib report tools."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report

    from paddle_tpu.profiler.slo import SERVING_SLOS, SLOSpec

    telemetry.reset()
    telemetry.enable()
    try:
        tm = telemetry.get_telemetry()
        tm.inc("serve.shed", 2)
        tm.inc("serve.decode_steps", 5)
        # no serve gauges at all — the post-drain registry shape
        assert "serve.queue_depth" not in tm.gauges()
        # a gauge-named spec falls through to the counters-read-as-0 path
        spec = SLOSpec.parse("serve.queue_depth < 16")
        ok, value = spec.evaluate(tm)
        assert ok is True and value == 0.0
        # the shipped serving SLOs never reference the retirable gauges
        for text in SERVING_SLOS:
            s = SLOSpec.parse(text)
            assert s.metric not in ("serve.requests_in_flight",
                                    "serve.queue_depth"), text
        # report tools render a gauge-free serve block without KeyError
        table = telemetry_report.build_table(
            {}, {}, {"serve.shed": 2.0, "serve.decode_steps": 5.0}, {}, {})
        assert "serve.shed" in table
        # bench_serve's reader idiom: absent gauge reads as the default
        assert tm.gauges().get("serve.requests_in_flight", 0.0) == 0.0
    finally:
        telemetry.disable()
        telemetry.reset()


# ---------------------------------------------------------------------------
# fault.inject: serve points, unknown-point error, stall kind
# ---------------------------------------------------------------------------
def test_unknown_point_raises_same_error_as_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        inject.arm("meteor", "serve.decode")
    with pytest.raises(ValueError, match="unknown fault point"):
        inject.arm("error", "serve.decoed")  # typo must fail loudly
    for point in ("serve.admit", "serve.prefill", "serve.decode",
                  "serve.evict", "serve.draft", "serve.verify"):
        assert point in inject.POINTS
        inject.arm("error", point, at=99)  # all of them arm cleanly
    inject.disarm_all()


def test_stall_kind_sleeps_then_returns(monkeypatch):
    monkeypatch.setenv(inject.STALL_ENV_VAR, "0.02")
    inject.arm("stall", "serve.decode", at=1)
    t0 = time.perf_counter()
    assert inject.check("serve.decode") == "stall"
    assert time.perf_counter() - t0 >= 0.02
    assert inject.check("serve.decode") is None  # fires once


def test_evict_fault_does_not_lose_the_request(eng):
    inject.arm("error", "serve.evict", at=1)
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(eng)
        req = Request(prompt=[4, 5, 6], max_new_tokens=3)
        sched.submit(req)
        sched.run()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert req.finish_reason == "length"  # eviction completed regardless
    assert req in sched.finished
    assert counters["serve.evict_faults"] == 1


# ---------------------------------------------------------------------------
# trace event spans for abnormal terminations
# ---------------------------------------------------------------------------
def test_shed_and_timeout_record_trace_event_spans(eng):
    tracing.reset()
    tracing.enable()
    try:
        sched = _sched(eng, max_queue=1)
        kept = Request(prompt=[1, 2, 3], max_new_tokens=2,
                       max_queue_s=0.0)
        sched.submit(kept)     # queued, will time out waiting
        shed = sched.submit(Request(prompt=[4, 5, 6], max_new_tokens=2))
        sched.step()
        spans = tracing.get_tracer().spans()
    finally:
        tracing.disable()
        tracing.reset()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    assert shed.finish_reason == "shed"
    assert kept.finish_reason == "timeout"
    # event spans are queryable by NAME and parent under the request root
    (shed_ev,) = by_name["shed"]
    assert shed_ev.attrs["rid"] == shed.rid
    assert shed_ev.trace_id == shed.trace_id
    (timeout_ev,) = by_name["timeout"]
    assert timeout_ev.attrs["rid"] == kept.rid
    # root spans closed with the terminal reason
    roots = {s.attrs.get("rid"): s for s in by_name["request"]}
    assert roots[shed.rid].attrs["finish_reason"] == "shed"
    assert roots[kept.rid].attrs["finish_reason"] == "timeout"
    assert all(s.end_ns is not None for s in by_name["request"])


# ---------------------------------------------------------------------------
# ISSUE 13: chunked prefill + speculative decoding under faults
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def spec_eng():
    """A 2-slot engine with speculation AND chunked prefill armed, every
    executable warmed — the resilience paths below fault the new surfaces
    (mid-chunk expiry, between-chunk OOM, draft/verify faults)."""
    model = _gpt(seed=4)
    e = GenerationEngine(model, max_batch=2, max_len=64,
                         prefill_buckets=(8, 16), spec_k=4,
                         prefill_chunk=4)
    e.prefill(0, [1] * 7)
    e.prefill(0, [1] * 12)
    e.decode_once(np.zeros(2, np.int32))
    off, tok = 0, None
    while tok is None:  # two-chunk warm of the chunk step
        tok = e.prefill_chunk_step(0, [1] * 5, off)
        off += 4
    e.verify_once(np.zeros((2, 5), np.int32))  # lengths unchanged
    return e


def test_mid_chunk_deadline_expiry_is_exactly_one_timeout(spec_eng):
    sched = _sched(spec_eng)
    req = Request(prompt=list(range(1, 13)), max_new_tokens=4,
                  deadline_s=60.0)
    sched.submit(req)
    sched.step()  # admitted into the chunked path, ONE chunk advanced
    assert req.slot is not None and not req.finished
    assert req.prefill_off == 4  # mid-prefill: 1 of 3 chunks done
    req.deadline_s = 1e-9  # already elapsed: next tick must expire it
    sched.step()
    _assert_full_accounting(sched, [req])
    assert req.finish_reason == "timeout"
    assert not req.tokens  # died between chunks: no token, no double-count
    # the freed slot and the engine survive: a fresh request runs clean
    nxt = Request(prompt=list(range(1, 13)), max_new_tokens=4)
    sched.submit(nxt)
    sched.run()
    assert nxt.finish_reason == "length"
    assert len(nxt.tokens) == 4


def test_oom_between_chunks_evicts_decoder_not_the_prefiller(spec_eng):
    prompt = list(range(20, 31))  # 11 tokens -> chunks of 4, 4, 3
    clean = Request(prompt=list(prompt), max_new_tokens=8)
    solo = _sched(spec_eng)
    solo.submit(clean)
    solo.run()

    sched = _sched(spec_eng)
    hog = Request(prompt=[3, 5, 7], max_new_tokens=12)
    sched.submit(hog)
    sched.step()  # hog active and decoding
    # armed AFTER hog's one-shot prefill, so hit 1 is the newcomer's
    # first chunk: the OOM lands mid-chunked-prefill, and the victim must
    # be the DECODING neighbor (the requester is excluded — evicting it
    # would orphan the retry)
    inject.arm("oom", "serve.prefill", at=1)
    telemetry.reset()
    telemetry.enable()
    try:
        req = Request(prompt=list(prompt), max_new_tokens=8)
        sched.submit(req)
        sched.run()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    _assert_full_accounting(sched, [hog, req])
    assert hog.finish_reason == "oom_evicted"
    assert counters["serve.oom_evictions"] == 1
    # the interrupted-then-retried prefiller still streams EXACTLY what a
    # clean solo run of the same prompt produced
    assert req.finish_reason == "length"
    assert req.tokens == clean.tokens


def test_draft_fault_decodes_plain_and_stream_is_byte_identical(spec_eng):
    # cyclic prompts guarantee the n-gram proposer WOULD draft; the
    # injected fault drops every proposal for one tick and the scheduler
    # must decode plain — output identical to the unfaulted run
    prompts = [[1, 2, 3] * 4, [4, 5] * 5]
    refs = [Request(prompt=list(p), max_new_tokens=10) for p in prompts]
    ref_sched = _sched(spec_eng)
    for r in refs:
        ref_sched.submit(r)
    ref_sched.run()

    inject.arm("error", "serve.draft", at=2)
    sched = _sched(spec_eng)
    reqs = [Request(prompt=list(p), max_new_tokens=10) for p in prompts]
    for r in reqs:
        sched.submit(r)
    sched.run()
    _assert_full_accounting(sched, reqs)
    for r, ref in zip(reqs, refs):
        assert r.tokens == ref.tokens
        assert r.finish_reason == "length"


def test_verify_fault_falls_back_to_plain_tick_with_counter(spec_eng):
    prompts = [[6, 7, 8] * 4, [9, 1] * 5]
    refs = [Request(prompt=list(p), max_new_tokens=10) for p in prompts]
    ref_sched = _sched(spec_eng, speculative=False)  # plain-greedy truth
    for r in refs:
        ref_sched.submit(r)
    ref_sched.run()

    inject.arm("error", "serve.verify", at=1)
    telemetry.reset()
    telemetry.enable()
    try:
        sched = _sched(spec_eng)
        reqs = [Request(prompt=list(p), max_new_tokens=10) for p in prompts]
        for r in reqs:
            sched.submit(r)
        sched.run()
        counters = telemetry.get_telemetry().counters()
    finally:
        telemetry.disable()
        telemetry.reset()
    _assert_full_accounting(sched, reqs)
    # the faulted tick degraded (counted) and later ticks speculated again
    assert counters["serve.spec_fallback_ticks"] == 1
    assert counters.get("serve.spec_ticks", 0) > 0
    for r, ref in zip(reqs, refs):
        assert r.tokens == ref.tokens
