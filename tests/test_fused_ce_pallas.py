"""Pallas flash-CE kernels (ops/pallas/fused_ce.py): parity with the XLA
scan path for loss and dh/dw/db gradients, including token/vocab padding
and ignore_index. Reference capability:
``paddle/phi/kernels/gpu/cross_entropy_kernel.cu``."""
import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.ops.fused as fused
from paddle_tpu.ops import pallas

N, H, V = 300, 128, 1000  # odd N, non-multiple V -> exercises padding


@pytest.fixture()
def data():
    rngs = jax.random.split(jax.random.key(0), 4)
    h = jax.random.normal(rngs[0], (N, H), jnp.float32)
    w = jax.random.normal(rngs[1], (V, H), jnp.float32) * 0.05
    b = jax.random.normal(rngs[2], (V,), jnp.float32) * 0.1
    y = jax.random.randint(rngs[3], (N,), 0, V)
    y = y.at[5].set(-100).at[17].set(-100)
    return h, w, b, y


def _run(h, w, b, y, use_pallas, use_bias):
    def f(h, w, b):
        bb = b if use_bias else jnp.zeros((), jnp.float32)
        losses = fused._flce(h, w, bb, y, -100, 0)
        return losses.sum() / 298.0

    ctx = pallas.interpret_mode() if use_pallas else contextlib.nullcontext()
    fused._FORCE_PALLAS = use_pallas
    try:
        with ctx:
            loss = float(f(h, w, b))
            grads = jax.grad(f, (0, 1, 2))(h, w, b)
        return loss, grads
    finally:
        fused._FORCE_PALLAS = None


@pytest.mark.parametrize("use_bias", [True, False])
def test_pallas_ce_matches_scan(data, use_bias):
    h, w, b, y = data
    l0, g0 = _run(h, w, b, y, False, use_bias)
    l1, g1 = _run(h, w, b, y, True, use_bias)
    assert abs(l0 - l1) < 1e-5
    for name, a, c in zip(("dh", "dw", "db"), g0, g1):
        err = float(jnp.abs(a - c).max() / (jnp.abs(a).max() + 1e-12))
        assert err < 1e-4, (name, err)


def test_pallas_ce_block_aligned_shapes():
    """Shapes already multiples of the blocks skip the padding paths."""
    rngs = jax.random.split(jax.random.key(1), 3)
    h = jax.random.normal(rngs[0], (256, 128), jnp.float32)
    w = jax.random.normal(rngs[1], (512, 128), jnp.float32) * 0.05
    y = jax.random.randint(rngs[2], (256,), 0, 512)
    l0, g0 = _run(h, w, jnp.zeros((512,)), y, False, False)
    l1, g1 = _run(h, w, jnp.zeros((512,)), y, True, False)
    assert abs(l0 - l1) < 1e-5
    np.testing.assert_allclose(np.asarray(g0[0]), np.asarray(g1[0]),
                               rtol=2e-5, atol=1e-7)


def test_gate_defaults():
    """Hardware default stays the scan unless FLAGS_enable_flash_ce; the
    interpret mode defaults to the kernels (keeps them tested)."""
    import paddle_tpu  # noqa: F401  (registers flags)

    with pallas.interpret_mode():
        assert fused._use_pallas(16384, 50304, 768)
    assert not fused._use_pallas(16384, 50304, 77)  # odd hidden -> scan
