"""dy2static AST transformation: Python control flow → lax under to_static.

Reference behavior model: dygraph_to_static transformers
(``program_translator.py:991``, ``ifelse_transformer.py``,
``loop_transformer.py``) — tensor-dependent if/while/for must produce the
same values compiled as eager, with gradients intact.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import convert_to_static


def t(x, **kw):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32), **kw)


# -- pure-transform unit checks (eager semantics preserved) -----------------


def test_concrete_control_flow_unchanged():
    def fn(x, flag):
        if flag:                      # plain python bool: python branch
            y = x + 1
        else:
            y = x - 1
        acc = 0
        for i in range(3):            # concrete range: python loop
            acc = acc + i
        return y * 1.0, acc

    conv = convert_to_static(fn)
    y, acc = conv(t([2.0]), True)
    assert float(y.numpy()[0]) == 3.0 and acc == 3
    y, _ = conv(fn=None) if False else conv(t([2.0]), False)
    assert float(y.numpy()[0]) == 1.0


def test_eager_tensor_if_still_branches():
    def fn(x):
        if x.sum() > 0:               # concrete tensor: python truth value
            return x * 2
        return x * -1

    conv = convert_to_static(fn)
    # `return` inside the if → transform bails; eager semantics preserved
    assert float(conv(t([1.0])).numpy()[0]) == 2.0
    assert float(conv(t([-1.0])).numpy()[0]) == 1.0


def test_if_assign_transformed_eager():
    def fn(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x * -1
        return y

    conv = convert_to_static(fn)
    assert conv is not fn  # transform actually fired
    assert float(conv(t([3.0])).numpy()[0]) == 6.0
    assert float(conv(t([-3.0])).numpy()[0]) == 3.0


# -- compiled (traced) parity ----------------------------------------------


def test_to_static_if_parity():
    @to_static
    def fn(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 5.0
        return y + 1.0

    for v in ([1.0, 2.0], [-1.0, -2.0]):
        out = fn(t(v)).numpy()
        ref = (np.asarray(v) * 2 + 1) if sum(v) > 0 else (np.asarray(v) - 4)
        np.testing.assert_allclose(out, ref.astype(np.float32), rtol=1e-6)


def test_to_static_elif_chain():
    @to_static
    def fn(x):
        s = x.sum()
        if s > 10.0:
            y = x * 3.0
        elif s > 0.0:
            y = x * 2.0
        else:
            y = x * 0.0
        return y

    np.testing.assert_allclose(fn(t([20.0])).numpy(), [60.0], rtol=1e-6)
    np.testing.assert_allclose(fn(t([3.0])).numpy(), [6.0], rtol=1e-6)
    np.testing.assert_allclose(fn(t([-3.0])).numpy(), [0.0], rtol=1e-6)


def test_to_static_while_parity():
    @to_static
    def fn(x):
        # data-dependent trip count: double until the sum crosses 100
        while x.sum() < 100.0:
            x = x * 2.0
        return x

    out = fn(t([3.0])).numpy()
    ref = 3.0
    while ref < 100.0:
        ref *= 2
    np.testing.assert_allclose(out, [ref], rtol=1e-6)


def test_to_static_for_range_tensor_bound():
    @to_static
    def fn(x, n):
        acc = paddle.zeros_like(x)
        for i in range(n):
            acc = acc + x * (i.astype("float32") + 1.0)
        return acc

    n = paddle.to_tensor(np.int32(4))
    np.testing.assert_allclose(fn(t([1.0]), n).numpy(), [10.0], rtol=1e-6)


def test_to_static_bool_ops_in_test():
    @to_static
    def fn(x):
        if (x.sum() > 0.0) and (x.max() < 10.0):
            y = x + 100.0
        else:
            y = x - 100.0
        return y

    np.testing.assert_allclose(fn(t([1.0])).numpy(), [101.0], rtol=1e-6)
    np.testing.assert_allclose(fn(t([50.0])).numpy(), [-50.0], rtol=1e-6)
    np.testing.assert_allclose(fn(t([-1.0])).numpy(), [-101.0], rtol=1e-6)


def test_to_static_nested_if_in_while():
    @to_static
    def fn(x):
        k = paddle.to_tensor(np.float32(0.0))
        while k.sum() < 5.0:
            if x.sum() > 0.0:
                x = x + 1.0
            else:
                x = x - 1.0
            k = k + 1.0
        return x

    np.testing.assert_allclose(fn(t([0.5])).numpy(), [5.5], rtol=1e-6)
    np.testing.assert_allclose(fn(t([-0.5])).numpy(), [-5.5], rtol=1e-6)


def test_gradient_through_transformed_if():
    def fn(x):
        if x.sum() > 0:
            y = x * 3.0
        else:
            y = x * 7.0
        return y.sum()

    conv = convert_to_static(fn)
    x = t([2.0], stop_gradient=False)
    loss = conv(x)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0], rtol=1e-6)
    x2 = t([-2.0], stop_gradient=False)
    conv(x2).backward()
    np.testing.assert_allclose(x2.grad.numpy(), [7.0], rtol=1e-6)


def test_layer_forward_to_static_control_flow():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0.0:
                out = h * 2.0
            else:
                out = h * 0.5
            return out

    net = Net()
    x = t(np.random.RandomState(0).randn(2, 4))
    eager = net(x).numpy()
    net_s = to_static(net)
    np.testing.assert_allclose(net_s(x).numpy(), eager, rtol=1e-5, atol=1e-5)


def test_undefined_in_one_branch_raises_under_trace():
    def fn(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            z = x * 3.0  # noqa: F841 — y undefined on this path
        return x

    conv = convert_to_static(fn)
    sfn = to_static(fn)
    # eager is fine (python branch taken)
    conv(t([1.0]))
    # under trace both branches lower; y mismatch must raise clearly
    with pytest.raises(Exception, match="(?i)branch|assigned"):
        sfn(t([1.0]))


def test_enable_to_static_toggle():
    import paddle_tpu.jit as jit

    def fn(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    try:
        jit.enable_to_static(False)
        assert convert_to_static(fn) is fn
    finally:
        jit.enable_to_static(True)
    assert convert_to_static(fn) is not fn


def test_for_loop_target_survives_loop():
    """Python binds the loop variable to its final value after the loop."""
    def fn(x):
        s = x
        for i in range(3):
            s = s + i
        return s + i  # noqa: B023 — this is the python idiom under test

    conv = convert_to_static(fn)
    assert float(conv(t(0.0)).numpy()) == 0 + 0 + 1 + 2 + 2

    @to_static
    def fn2(x, n):
        s = paddle.zeros_like(x)
        for i in range(n):
            s = s + x
        return s + i.astype("float32")

    n = paddle.to_tensor(np.int32(3))
    np.testing.assert_allclose(fn2(t([2.0]), n).numpy(), [8.0], rtol=1e-6)


def test_closure_cells_stay_live():
    """The converted function shares the original closure cells: rebinding
    an enclosing variable after conversion is visible (and recursive
    decorated functions resolve their own not-yet-filled cell)."""
    k = 1.0

    def fn(x):
        if x.sum() > 0:
            y = x + k
        else:
            y = x - k
        return y

    conv = convert_to_static(fn)
    assert float(conv(t([1.0])).numpy()[0]) == 2.0
    k = 100.0  # noqa: F841 — rebinding must be seen by the converted fn
    assert float(conv(t([1.0])).numpy()[0]) == 101.0

    # recursive decorated function: own cell empty at decoration time
    def outer():
        @to_static
        def walk(v, depth):
            if depth > 0:
                out = walk(v * 2.0, depth - 1)
            else:
                out = v
            return out

        return walk

    w = outer()
    np.testing.assert_allclose(w(t([1.0]), 3).numpy(), [8.0], rtol=1e-6)


def test_wrapping_decorator_preserved():
    """A functools.wraps decorator between to_static and the def must keep
    its behavior — conversion bails rather than silently dropping it."""
    import functools

    def times10(f):
        @functools.wraps(f)
        def inner(*a, **k):
            return f(*a, **k) * 10.0

        return inner

    @times10
    def fn(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    assert convert_to_static(fn) is fn  # bail-out, not silent strip
    # eager semantics keep the decorator
    np.testing.assert_allclose(fn(t([1.0])).numpy(), [20.0], rtol=1e-6)
    # compiling the wrapped fn with tensor control flow now raises jax's
    # concretization error (the documented fallback) instead of silently
    # returning 2.0 with the decorator dropped
    with pytest.raises(Exception, match="(?i)trace|concret"):
        to_static(fn)(t([1.0]))

    @times10
    def plain(x):
        return x + 1.0

    # wrapped fns without tensor control flow still compile, decorator intact
    np.testing.assert_allclose(to_static(plain)(t([1.0])).numpy(), [20.0],
                               rtol=1e-6)


def test_static_program_recording_with_dy2static():
    """Transformed control flow must also record into a static Program."""
    import paddle_tpu.static as static

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")

            def body(x):
                if x.sum() > 0:
                    y = x * 2.0
                else:
                    y = x * -1.0
                return y

            y = convert_to_static(body)(x)
            exe = static.Executor()
            exe.run(startup)
            (out,) = exe.run(main, feed={"x": np.array([1.0, 2.0], np.float32)},
                             fetch_list=[y])
            np.testing.assert_allclose(out, [2.0, 4.0], rtol=1e-6)
            (out,) = exe.run(main, feed={"x": np.array([-1.0, -2.0], np.float32)},
                             fetch_list=[y])
            np.testing.assert_allclose(out, [1.0, 2.0], rtol=1e-6)
    finally:
        paddle.disable_static()
