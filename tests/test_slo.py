"""SLO specs + multi-window burn-rate monitoring (ISSUE 8).

Contracts under test:
  * spec grammar: bare gauges/counters, histogram percentile/mean stats,
    counter rates, per-spec objectives, parse errors on junk;
  * absent counters read as 0 (``fault.giveups == 0`` holds on a clean
    process) while absent histograms produce NO sample (no false pages);
  * burn-rate alerting: fires only when EVERY window exceeds its
    threshold, dedupes while firing, re-arms after recovery — all under
    an injected clock, no sleeping;
  * sinks: JSONL + callback, and a broken sink cannot break the check;
  * wiring: ``Scheduler(slo=)`` samples mid-serve, ``TelemetryLogger
    (slo=)`` samples per log_freq and prints the SLO table at train end.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.callbacks import TelemetryLogger
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.nn import CrossEntropyLoss
from paddle_tpu.profiler import telemetry
from paddle_tpu.profiler.slo import (
    JsonlAlertSink,
    SLOMonitor,
    SLOSpec,
    log_alert_sink,
)
from paddle_tpu.serving import GenerationEngine, Request, Scheduler
from paddle_tpu.utils import unique_name


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# spec parsing + evaluation
# ---------------------------------------------------------------------------
def test_spec_parse_forms():
    s = SLOSpec.parse("serve.latency_s p95 < 0.5")
    assert (s.metric, s.stat, s.op, s.threshold) \
        == ("serve.latency_s", "p95", "<", 0.5)
    assert s.objective is None
    s = SLOSpec.parse("fault.giveups == 0")
    assert (s.metric, s.stat, s.op, s.threshold) \
        == ("fault.giveups", None, "==", 0.0)
    s = SLOSpec.parse("serve.decode_steps rate > 1.5 @ 0.999")
    assert (s.stat, s.objective) == ("rate", 0.999)
    s = SLOSpec.parse("phase.data_wait mean <= 0.01")
    assert (s.metric, s.stat) == ("phase.data_wait", "mean")


@pytest.mark.parametrize("bad", [
    "no operator here", "metric !! 3", "m < notanumber",
    "m p95 < 0.5 @ 7", "", "m bogus < 1",
])
def test_spec_parse_errors(bad):
    with pytest.raises(ValueError):
        SLOSpec.parse(bad)


def test_spec_evaluation_against_registry():
    tm = telemetry.get_telemetry()
    tm.set_gauge("serve.queue_depth", 3)
    tm.inc("serve.evicted", 12)
    for v in (0.1, 0.4):
        tm.observe("serve.latency_s", v)

    ok, v = SLOSpec.parse("serve.queue_depth < 16").evaluate(tm)
    assert (ok, v) == (True, 3.0)
    ok, v = SLOSpec.parse("serve.evicted >= 12").evaluate(tm)
    assert (ok, v) == (True, 12.0)
    ok, v = SLOSpec.parse("serve.latency_s p95 < 0.2").evaluate(tm)
    assert (ok, v) == (False, 0.4)
    # absent counter reads 0 (clean-process semantics)
    ok, v = SLOSpec.parse("fault.giveups == 0").evaluate(tm)
    assert (ok, v) == (True, 0.0)
    # absent histogram: no sample, not a page
    ok, v = SLOSpec.parse("serve.ttft_s p95 < 1").evaluate(tm)
    assert (ok, v) == (None, None)


def test_counter_rate_stat():
    tm = telemetry.get_telemetry()
    spec = SLOSpec.parse("serve.tokens_generated rate > 10")
    state = {}
    assert spec.value(tm, rate_state=state, now=0.0) is None  # first read
    tm.inc("serve.tokens_generated", 50)
    assert spec.value(tm, rate_state=state, now=2.0) == pytest.approx(25.0)
    tm.inc("serve.tokens_generated", 5)
    assert spec.value(tm, rate_state=state, now=3.0) == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# burn-rate monitor
# ---------------------------------------------------------------------------
def _monitor(specs, windows=((10.0, 5.0), (60.0, 2.0)), objective=0.9,
             sinks=()):
    return SLOMonitor(specs, objective=objective, windows=windows,
                      sinks=list(sinks), clock=lambda: 0.0)


def test_alert_fires_only_when_all_windows_burn():
    tm = telemetry.get_telemetry()
    tm.set_gauge("serve.queue_depth", 100)  # violates from the start
    alerts = []
    mon = _monitor(["serve.queue_depth < 16"], sinks=[alerts.append])
    # budget = 0.1, constant violation → burn 10x in both windows once
    # enough samples exist; single alert, deduped while firing
    for t in range(20):
        mon.check(now=float(t))
    assert len(alerts) == 1
    a = alerts[0]
    assert a["spec"] == "serve.queue_depth < 16"
    assert a["value"] == 100.0
    assert all(w["burn_rate"] >= w["max_burn"] for w in a["windows"])
    assert mon.status()[0]["firing"]

    # recovery: the gauge drops, the short window clears first, monitor
    # re-arms, a later sustained violation pages AGAIN
    tm.set_gauge("serve.queue_depth", 2)
    for t in range(20, 120):
        mon.check(now=float(t))
    assert not mon.status()[0]["firing"]
    tm.set_gauge("serve.queue_depth", 200)
    for t in range(120, 240):
        mon.check(now=float(t))
    assert len(alerts) == 2


def test_short_blip_does_not_page():
    """One violating sample inside an otherwise-clean stream must not
    fire: the long window keeps its burn under threshold."""
    tm = telemetry.get_telemetry()
    alerts = []
    mon = SLOMonitor(["serve.queue_depth < 16"], objective=0.5,
                     windows=((5.0, 1.5), (60.0, 1.5)),
                     sinks=[alerts.append], clock=lambda: 0.0)
    tm.set_gauge("serve.queue_depth", 1)
    for t in range(60):
        if t == 30:
            tm.set_gauge("serve.queue_depth", 99)  # one-tick blip
        mon.check(now=float(t))
        if t == 30:
            tm.set_gauge("serve.queue_depth", 1)
    assert alerts == []


def test_jsonl_sink_and_sink_isolation(tmp_path):
    tm = telemetry.get_telemetry()
    tm.set_gauge("serve.queue_depth", 50)
    path = tmp_path / "alerts.jsonl"

    def broken_sink(alert):
        raise RuntimeError("sink down")

    mon = _monitor(["serve.queue_depth < 16"],
                   sinks=[broken_sink, JsonlAlertSink(str(path))])
    with pytest.warns(RuntimeWarning, match="sink.*failed"):
        for t in range(10):
            mon.check(now=float(t))
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(rows) == 1 and rows[0]["value"] == 50.0


def test_log_sink_warns():
    tm = telemetry.get_telemetry()
    tm.set_gauge("serve.queue_depth", 50)
    mon = _monitor(["serve.queue_depth < 16"], sinks=[log_alert_sink])
    with pytest.warns(RuntimeWarning, match="SLO burn"):
        for t in range(10):
            mon.check(now=float(t))


def test_report_table(capsys):
    tm = telemetry.get_telemetry()
    tm.set_gauge("serve.queue_depth", 2)
    mon = _monitor(["serve.queue_depth < 16", "fault.giveups == 0"])
    for t in range(5):
        mon.check(now=float(t))
    table = mon.report()
    capsys.readouterr()
    assert "serve.queue_depth < 16" in table
    assert "fault.giveups == 0" in table
    assert "100.0%" in table  # fully compliant
    assert "FIRING" not in table


# ---------------------------------------------------------------------------
# wiring: scheduler + TelemetryLogger
# ---------------------------------------------------------------------------
def test_scheduler_checks_slo_inline():
    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=64, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    eng = GenerationEngine(model, max_batch=2, max_len=64,
                           prefill_buckets=(8,))
    alerts = []
    # impossible objective so the run itself pages: latency p95 < 0 with
    # single-sample windows
    mon = SLOMonitor(["serve.latency_s p95 < 0"], objective=0.9,
                     windows=((3600.0, 1.0),), sinks=[alerts.append])
    sched = Scheduler(eng, slo=mon, slo_check_every=1)
    rng = np.random.RandomState(0)
    for _ in range(3):
        sched.submit(Request(prompt=rng.randint(0, 97, 4).tolist(),
                             max_new_tokens=3))
    sched.run()
    assert mon.checks >= sched.decode_steps  # sampled every tick + drain
    assert len(alerts) == 1
    assert alerts[0]["metric"] == "serve.latency_s"


def test_telemetry_logger_slo_wiring(capsys):
    class _DS:
        def __init__(self, n=48):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype(np.float32)
            self.y = (self.x.sum(1) > 0).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    model.prepare(opt, CrossEntropyLoss())
    alerts = []
    mon = SLOMonitor(["phase.dispatch p95 < 0", "fault.giveups == 0"],
                     objective=0.9, windows=((3600.0, 1.0),),
                     sinks=[alerts.append])
    cb = TelemetryLogger(log_freq=1, print_report=True, slo=mon)
    model.fit(_DS(), batch_size=16, epochs=1, verbose=0, callbacks=[cb])
    assert cb.slo_monitor is mon
    assert mon.checks >= 3  # one per batch at log_freq=1, plus train end
    assert alerts and alerts[0]["metric"] == "phase.dispatch"
    out = capsys.readouterr().out
    assert "phase.dispatch p95 < 0" in out  # SLO table printed at end
    assert "fault.giveups == 0" in out
    assert "FIRING" in out


def test_telemetry_logger_slo_from_strings():
    """Spec strings build a monitor lazily at train begin."""
    cb = TelemetryLogger(print_report=False, slo=["fault.giveups == 0"])
    assert cb.slo_monitor is None
    cb.on_train_begin()
    assert isinstance(cb.slo_monitor, SLOMonitor)
    assert cb.slo_monitor.specs[0].metric == "fault.giveups"
    cb.on_train_end()
