"""paddle.fft + paddle.signal vs numpy references, gradients, static mode.

Reference: ``python/paddle/fft.py`` (norm conventions, full c2c/r2c/c2r
surface) and ``python/paddle/signal.py`` (frame/overlap_add/stft/istft with
NOLA reconstruction).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(x, **kw):
    return paddle.to_tensor(np.asarray(x), **kw)


rng = np.random.RandomState(0)


@pytest.mark.parametrize("norm", ["backward", "forward", "ortho"])
def test_fft_ifft_roundtrip_and_norms(norm):
    x = rng.randn(4, 16).astype(np.float32)
    out = paddle.fft.fft(t(x), norm=norm).numpy()
    ref = np.fft.fft(x, norm=norm)
    np.testing.assert_allclose(out, ref.astype(np.complex64), rtol=1e-4,
                               atol=1e-4)
    back = paddle.fft.ifft(t(out), norm=norm).numpy()
    np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-4)


def test_rfft_irfft_hfft_family():
    x = rng.randn(8, 32).astype(np.float32)
    r = paddle.fft.rfft(t(x)).numpy()
    np.testing.assert_allclose(r, np.fft.rfft(x).astype(np.complex64),
                               rtol=1e-4, atol=1e-4)
    back = paddle.fft.irfft(t(r)).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)
    sym = np.fft.ihfft(x)  # hermitian input for hfft
    h = paddle.fft.hfft(t(sym.astype(np.complex64))).numpy()
    np.testing.assert_allclose(h, np.fft.hfft(sym), rtol=1e-3, atol=1e-3)
    ih = paddle.fft.ihfft(t(x)).numpy()
    np.testing.assert_allclose(ih, np.fft.ihfft(x).astype(np.complex64),
                               rtol=1e-4, atol=1e-4)


def test_fft2_fftn_shift_freq():
    x = rng.randn(3, 8, 8).astype(np.float32)
    np.testing.assert_allclose(paddle.fft.fft2(t(x)).numpy(),
                               np.fft.fft2(x).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(paddle.fft.fftn(t(x)).numpy(),
                               np.fft.fftn(x).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(paddle.fft.rfft2(t(x)).numpy(),
                               np.fft.rfft2(x).astype(np.complex64),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(paddle.fft.fftfreq(16, d=0.5).numpy(),
                               np.fft.fftfreq(16, d=0.5).astype(np.float32),
                               rtol=1e-6)
    np.testing.assert_allclose(paddle.fft.rfftfreq(16).numpy(),
                               np.fft.rfftfreq(16).astype(np.float32),
                               rtol=1e-6)
    np.testing.assert_allclose(paddle.fft.fftshift(t(x)).numpy(),
                               np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.fft.ifftshift(t(x)).numpy(),
                               np.fft.ifftshift(x), rtol=1e-6)


def test_fft_gradient_flows():
    x = t(rng.randn(8).astype(np.float32), stop_gradient=False)
    y = paddle.fft.rfft(x)
    # |Y|^2 sum: real scalar of a complex intermediate
    mag = (y.real() ** 2 + y.imag() ** 2).sum() if hasattr(y, "real") else None
    if mag is None:
        pytest.skip("complex component accessors unavailable")
    mag.backward()
    assert x.grad is not None
    # Parseval: d/dx sum|rfft(x)|^2 ~ 2*n*x for full-spectrum; just finite
    assert np.isfinite(x.grad.numpy()).all()


def test_frame_overlap_add_inverse():
    x = rng.randn(160).astype(np.float32)
    f = paddle.signal.frame(t(x), frame_length=32, hop_length=32)
    assert list(f.shape) == [32, 5]
    # non-overlapping: overlap_add inverts exactly
    back = paddle.signal.overlap_add(f, hop_length=32).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-6)
    # batched, axis=-1
    xb = rng.randn(2, 100).astype(np.float32)
    fb = paddle.signal.frame(t(xb), 20, 10)
    assert list(fb.shape) == [2, 20, 9]


def test_stft_matches_manual_dft():
    x = rng.randn(256).astype(np.float32)
    n_fft, hop = 64, 16
    win = np.hanning(n_fft).astype(np.float32)
    spec = paddle.signal.stft(t(x), n_fft, hop_length=hop,
                              window=t(win), center=False).numpy()
    n_frames = 1 + (256 - n_fft) // hop
    assert spec.shape == (n_fft // 2 + 1, n_frames)
    ref = np.stack(
        [np.fft.rfft(x[i * hop:i * hop + n_fft] * win)
         for i in range(n_frames)], axis=-1)
    np.testing.assert_allclose(spec, ref.astype(np.complex64), rtol=1e-3,
                               atol=1e-3)


def test_stft_window_padding_odd_win_length():
    """win_length one less than n_fft must center-pad the window (the
    `(n_fft-w)//2 == 0` case) and win_length > n_fft must raise."""
    x = rng.randn(256).astype(np.float32)
    win = np.hanning(63).astype(np.float32)
    spec = paddle.signal.stft(t(x), 64, hop_length=16, win_length=63,
                              window=t(win), center=False)
    assert spec.shape[0] == 33  # n_fft//2 + 1 — padded window applied cleanly
    back = paddle.signal.istft(spec, 64, hop_length=16, win_length=63,
                               window=t(win), center=False)
    assert np.isfinite(back.numpy()).all()
    with pytest.raises(ValueError, match="win_length"):
        paddle.signal.stft(t(x), 64, win_length=65)
    with pytest.raises(ValueError, match="win_length"):
        paddle.signal.istft(spec, 64, win_length=65)


def test_stft_istft_roundtrip():
    x = rng.randn(512).astype(np.float32)
    n_fft, hop = 128, 32
    win = np.hanning(n_fft).astype(np.float32)
    spec = paddle.signal.stft(t(x), n_fft, hop_length=hop, window=t(win),
                              center=True)
    back = paddle.signal.istft(spec, n_fft, hop_length=hop, window=t(win),
                               center=True, length=512).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)
