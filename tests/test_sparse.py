"""paddle.sparse: COO/CSR creation, matmul/add/multiply/relu, dense
round-trips. Reference: phi/core/sparse_*_tensor.h, kernels/sparse/,
python/paddle/incubate/sparse/."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.framework.tensor import Tensor


def test_coo_roundtrip_and_accessors():
    indices = [[0, 1, 2], [1, 2, 0]]
    values = [1.0, 2.0, 3.0]
    s = sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
    assert s.is_sparse() and s.is_sparse_coo()
    assert s.shape == [3, 3] and s.nnz() == 3
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)
    np.testing.assert_allclose(np.asarray(s.values()._value), values)
    np.testing.assert_allclose(np.asarray(s.indices()._value), indices)


def test_csr_roundtrip():
    s = sparse.sparse_csr_tensor([0, 1, 2, 3], [1, 2, 0], [1.0, 2.0, 3.0],
                                 shape=[3, 3])
    assert s.is_sparse_csr()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(s.to_dense().numpy(), expect)
    np.testing.assert_allclose(np.asarray(s.crows()._value), [0, 1, 2, 3])


def test_sparse_dense_matmul():
    rng = np.random.RandomState(0)
    dense = rng.randn(4, 4).astype(np.float32)
    dense[dense < 0.3] = 0.0
    idx = np.nonzero(dense)
    s = sparse.sparse_coo_tensor(np.stack(idx), dense[idx], shape=dense.shape)
    y = rng.randn(4, 5).astype(np.float32)
    out = sparse.matmul(s, Tensor(y))
    np.testing.assert_allclose(np.asarray(out._value), dense @ y, atol=1e-5)


def test_add_multiply_relu():
    a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [-1.0, 2.0], shape=[2, 2])
    b = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [5.0, -7.0], shape=[2, 2])
    s = sparse.add(a, b)
    np.testing.assert_allclose(s.to_dense().numpy(), [[4, 0], [0, -5]])
    r = sparse.relu(a)
    np.testing.assert_allclose(r.to_dense().numpy(), [[0, 0], [0, 2]])
    d = Tensor(np.full((2, 2), 3.0, np.float32))
    m = sparse.multiply(a, d)
    np.testing.assert_allclose(m.to_dense().numpy(), [[-3, 0], [0, 6]])
    assert sparse.is_same_shape(a, b)


def test_review_fixes_predicates_csr_add_scalar_multiply():
    dense = Tensor(np.ones((2, 2), np.float32))
    assert not dense.is_sparse() and not dense.is_sparse_coo()
    a = sparse.sparse_coo_tensor([[0], [0]], [1.0], shape=[2, 2])
    assert a.is_sparse_coo() and not a.is_sparse_csr()

    c1 = sparse.sparse_csr_tensor([0, 1, 1], [0], [1.0], shape=[2, 2])
    c2 = sparse.sparse_csr_tensor([0, 0, 1], [1], [2.0], shape=[2, 2])
    s = sparse.add(c1, c2)
    assert s.is_sparse_csr()
    np.testing.assert_allclose(s.to_dense().numpy(), [[1, 0], [0, 2]])

    m = sparse.multiply(a, 2.0)
    np.testing.assert_allclose(m.to_dense().numpy(), [[2, 0], [0, 0]])
    row = Tensor(np.array([3.0, 4.0], np.float32))
    m2 = sparse.multiply(a, row)
    np.testing.assert_allclose(m2.to_dense().numpy(), [[3, 0], [0, 0]])

    with pytest.raises(ValueError, match="explicit shape"):
        sparse.sparse_coo_tensor(np.zeros((2, 0)), np.zeros((0,)))
