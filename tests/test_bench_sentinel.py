"""Bench-history regression sentinel (ISSUE 8): noise-aware baseline
comparison over the checked-in BENCH/SERVE/MULTICHIP round series.

Contracts under test (incl. the acceptance criterion):
  * the REAL repo history passes clean;
  * an artificial 20% tokens/sec regression appended to the BENCH_r01..r05
    history IS flagged, and the ``--smoke`` CI gate verifies both at once;
  * direction-awareness: latency regresses UP, throughput DOWN,
    improvements never flag; contract metrics (decode compile count,
    dryrun ok) flag on ANY change;
  * noise-awareness: a jittery history widens tolerance (within the cap),
    a flat history is held tight;
  * ranked output (worst regression first) and exit codes.

Stdlib-only module under test — imported straight from tools/.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import bench_sentinel  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _series(values, direction="higher", metric="tokens_per_sec",
            name="bench"):
    return {name: [(i + 1, {metric: (v, direction)})
                   for i, v in enumerate(values)]}


def _regressions(findings):
    return [f for f in findings if f["status"] == "REGRESSION"]


# ---------------------------------------------------------------------------
# comparison engine
# ---------------------------------------------------------------------------
def test_flat_history_passes():
    f = bench_sentinel.compare(_series([100.0, 101.0, 99.5, 100.2]))
    assert _regressions(f) == []


def test_twenty_percent_drop_flagged_and_ranked():
    series = _series([100.0, 101.0, 99.5, 80.0])
    series["bench"][-1][1]["mfu"] = (0.5, "higher")  # fine metric rides along
    series["bench"][0][1]["mfu"] = (0.5, "higher")
    series["bench"][1][1]["mfu"] = (0.51, "higher")
    f = bench_sentinel.compare(series)
    regs = _regressions(f)
    assert len(regs) == 1
    assert regs[0]["metric"] == "tokens_per_sec"
    assert regs[0]["delta"] == pytest.approx(-0.2, abs=0.01)
    # ranked: the regression sorts first
    assert f[0]["status"] == "REGRESSION"


def test_improvement_never_flags():
    f = bench_sentinel.compare(_series([100.0, 110.0, 130.0, 160.0]))
    assert _regressions(f) == []


def test_lower_better_direction():
    # latency creeping UP is the regression
    f = bench_sentinel.compare(_series([1.0, 1.02, 0.98, 1.5],
                                       direction="lower",
                                       metric="p95_latency_s"))
    regs = _regressions(f)
    assert len(regs) == 1 and regs[0]["metric"] == "p95_latency_s"
    # latency going DOWN is an improvement
    f = bench_sentinel.compare(_series([1.0, 1.02, 0.98, 0.5],
                                       direction="lower",
                                       metric="p95_latency_s"))
    assert _regressions(f) == []


def test_zero_baseline_lower_better_flags_any_appearance():
    # lint findings / giveups held at 0 historically: ANY appearance flags
    f = bench_sentinel.compare(_series([0.0, 0.0, 0.0, 1.0],
                                       direction="lower",
                                       metric="shape_churn_findings"))
    assert len(_regressions(f)) == 1


def test_contract_metric_flags_any_change():
    # decode must compile exactly once — 1 → 2 is a regression even
    # though 2 is "within 8%+" of nothing
    f = bench_sentinel.compare(_series([1.0, 1.0, 1.0, 2.0],
                                       direction="equal",
                                       metric="decode_compiles"))
    assert len(_regressions(f)) == 1


def test_noise_awareness_widens_tolerance():
    # jittery history (robust cv ≈ 10.4% > the 8% floor): a 9% dip below
    # the median baseline sits inside the widened tolerance → no flag
    jittery = [100.0, 115.0, 87.0, 113.0, 96.9]
    f = bench_sentinel.compare(_series(jittery), window=4, noise_k=1.0)
    assert _regressions(f) == []
    # the SAME 9%-below-baseline dip on a flat history (cv ≈ 0, tolerance
    # floored at 8%) → flagged
    flat = [100.0, 100.5, 99.8, 100.2, 91.1]
    f = bench_sentinel.compare(_series(flat), window=4, noise_k=1.0)
    assert len(_regressions(f)) == 1


def test_step_change_ratchets_baseline():
    # a 60% jump (beyond tolerance → confirmed step-change, not jitter)
    # becomes the new bar: sliding back toward the pre-jump level must
    # flag even though the trailing MEDIAN still sits at the old level
    f = bench_sentinel.compare(_series([100.0, 100.0, 101.0, 160.0, 120.0]))
    regs = _regressions(f)
    assert len(regs) == 1
    assert regs[0]["baseline"] == pytest.approx(160.0)
    # holding the new level is clean
    f = bench_sentinel.compare(_series([100.0, 100.0, 101.0, 160.0, 158.0]))
    assert _regressions(f) == []
    # lower-is-better mirrors: latency halves, then creeps back up
    f = bench_sentinel.compare(_series([10.0, 10.1, 9.9, 5.0, 8.0],
                                       direction="lower",
                                       metric="p95_latency_s"))
    regs = _regressions(f)
    assert len(regs) == 1
    assert regs[0]["baseline"] == pytest.approx(5.0)
    # a within-tolerance wiggle does NOT ratchet (median still rules —
    # see test_noise_awareness_widens_tolerance for the jitter case)
    f = bench_sentinel.compare(_series([100.0, 101.0, 99.5, 100.2]))
    assert f[0]["baseline"] == pytest.approx(100.0)


def test_single_round_series_skipped():
    f = bench_sentinel.compare(_series([42.0]))
    assert f[0]["status"] == "no-history"
    assert _regressions(f) == []


# ---------------------------------------------------------------------------
# real repo history (acceptance criterion)
# ---------------------------------------------------------------------------
def test_real_history_loads_and_passes_clean():
    series = bench_sentinel.load_series(REPO_ROOT)
    assert "bench" in series and len(series["bench"]) >= 4
    assert "multichip" in series and "serve" in series
    f = bench_sentinel.compare(series)
    assert _regressions(f) == [], bench_sentinel.build_table(f)


def test_real_history_flags_injected_20pct_drop():
    # the serve series carries the live tokens_per_sec history — the bench
    # series' tokens_per_sec ended at r05 (r14 onward is CPU-measured and
    # deliberately omits parsed.value; see BENCH_r14.json's note)
    series = bench_sentinel.load_series(REPO_ROOT)
    injected = bench_sentinel.inject_round(series, "serve",
                                           "tokens_per_sec", 0.8)
    f = bench_sentinel.compare(injected)
    regs = _regressions(f)
    assert any(r["series"] == "serve" and r["metric"] == "tokens_per_sec"
               for r in regs), bench_sentinel.build_table(f, verbose=True)
    # the untouched metrics still pass
    assert all(r["metric"] == "tokens_per_sec" for r in regs)


def test_multichip_ok_flip_flags():
    series = bench_sentinel.load_series(REPO_ROOT)
    rounds = series["multichip"]
    last_round, last = rounds[-1]
    flipped = dict(last)
    flipped["dryrun_ok"] = (0.0, "equal")
    series = dict(series)
    series["multichip"] = rounds + [(last_round + 1, flipped)]
    f = bench_sentinel.compare(series)
    assert any(r["metric"] == "dryrun_ok" for r in _regressions(f))


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------
def test_cli_clean_and_smoke(tmp_path, capsys):
    assert bench_sentinel.main(["--root", REPO_ROOT]) == 0
    assert bench_sentinel.main(["--root", REPO_ROOT, "--smoke"]) == 0
    out = capsys.readouterr().out
    assert "SMOKE OK" in out


def test_cli_inject_fails_and_dumps_json(tmp_path, capsys):
    out_json = tmp_path / "findings.json"
    rc = bench_sentinel.main([
        "--root", REPO_ROOT,
        "--inject", "serve:tokens_per_sec=0.8",
        "--json", str(out_json)])
    assert rc == 1
    table = capsys.readouterr().out
    assert "REGRESSION" in table and "tokens_per_sec" in table
    findings = json.loads(out_json.read_text())
    assert any(f["status"] == "REGRESSION" for f in findings)


def test_cli_no_history_exit_2(tmp_path):
    assert bench_sentinel.main(["--root", str(tmp_path)]) == 2


def test_cli_bad_inject_spec():
    with pytest.raises(ValueError, match="bad --inject"):
        bench_sentinel.main(["--root", REPO_ROOT, "--inject", "nonsense"])
