"""auto_parallel: ProcessMesh, shard_tensor annotation -> GSPMD placement,
Engine fit/evaluate/predict parity. Reference:
python/paddle/distributed/auto_parallel/{process_mesh,interface,engine}.py"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ProcessMesh, shard_tensor
from paddle_tpu.distributed.auto_parallel import Engine
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io import Dataset
from paddle_tpu.utils import unique_name


def test_process_mesh_basics():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4]
    assert pm.ndim == 2
    assert pm.processes == list(range(8))
    assert pm.dim_names == ["x", "y"]
    jm = pm.jax_mesh
    assert jm.axis_names == ("x", "y")
    with pytest.raises(ValueError):
        ProcessMesh([[0, 1]], dim_names=["a", "b", "c"])


def test_shard_tensor_places_by_dims_mapping():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    x = Tensor(np.random.RandomState(0).randn(8, 12).astype(np.float32))
    sx = shard_tensor(x, {"process_mesh": pm, "dims_mapping": [0, 1]})
    sh = sx._value.sharding
    # dim0 split over x (2), dim1 over y (4): per-shard (4, 3)
    assert sx._value.addressable_shards[0].data.shape == (4, 3)
    np.testing.assert_allclose(np.asarray(sx._value), np.asarray(x._value))

    # context-mesh form + replicate
    with pm:
        r = shard_tensor(x, {"dims_mapping": [-1, -1]})
    assert r._value.addressable_shards[0].data.shape == (8, 12)


def test_shard_tensor_gradient_passthrough():
    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    x = Tensor(np.random.RandomState(1).randn(8, 4).astype(np.float32),
               stop_gradient=False)
    y = shard_tensor(x, {"process_mesh": pm, "dims_mapping": [0, -1]})
    (y * y).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               2 * np.asarray(x._value), atol=1e-6)


class _Toy(Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n, 1)).astype(np.float32)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp():
    with unique_name.guard():
        paddle.seed(0)
        return paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                    paddle.nn.Tanh(),
                                    paddle.nn.Linear(16, 1))


def test_engine_fit_eval_predict():
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    engine = Engine(model=net, loss=paddle.nn.MSELoss(), optimizer=opt)
    hist = engine.fit(_Toy(64), batch_size=16, epochs=6)
    losses = hist["loss"]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    ev = engine.evaluate(_Toy(32, seed=1), batch_size=16)
    assert np.isfinite(ev["loss"])
    preds = engine.predict(_Toy(32, seed=1), batch_size=16)
    assert sum(p.shape[0] for p in preds) == 32


def test_engine_matches_single_device_training():
    """8-device dp Engine == single-device loop, same data order."""
    ds = _Toy(32)

    def run_plain():
        net = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        losses = []
        for i in range(0, 32, 16):
            xb = Tensor(ds.x[i:i + 16])
            yb = Tensor(ds.y[i:i + 16])
            loss = paddle.nn.MSELoss()(net(xb), yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._value)))
        return losses

    def run_engine():
        from paddle_tpu.io import DataLoader

        net = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        engine = Engine(model=net, loss=paddle.nn.MSELoss(), optimizer=opt)
        loader = DataLoader(ds, batch_size=16, shuffle=False)
        return engine.fit(loader, epochs=1)["loss"]

    np.testing.assert_allclose(run_engine(), run_plain(), rtol=2e-5)


def test_engine_save_load(tmp_path):
    net = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    engine = Engine(model=net, loss=paddle.nn.MSELoss(), optimizer=opt)
    engine.fit(_Toy(32), batch_size=16, epochs=1)
    engine.save(str(tmp_path / "ap"))

    net2 = _mlp()
    engine2 = Engine(model=net2, loss=paddle.nn.MSELoss())
    engine2.load(str(tmp_path / "ap"), load_optimizer=False)
    x = np.ones((4, 8), np.float32)
    a = engine.predict([ (x[i], np.zeros(1, np.float32)) for i in range(4)], batch_size=4)
    b = engine2.predict([ (x[i], np.zeros(1, np.float32)) for i in range(4)], batch_size=4)
    np.testing.assert_allclose(a[0], b[0], atol=1e-6)


def test_shard_tensor_name_and_none_specs():
    """paddle shard_spec convention: axis names / None entries."""
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    x = Tensor(np.random.RandomState(5).randn(8, 12).astype(np.float32))
    a = shard_tensor(x, process_mesh=pm, shard_spec=["x", None])
    assert a._value.addressable_shards[0].data.shape == (4, 12)
    b = shard_tensor(x, {"process_mesh": pm, "dims_mapping": [None, "y"]})
    assert b._value.addressable_shards[0].data.shape == (8, 3)
    with pytest.raises(ValueError, match="unknown mesh dim"):
        shard_tensor(x, process_mesh=pm, shard_spec=["zz", None])


# -- round-4 additions: annotated 2-D training, reshard, strategy -----------

def _annotated_mlp(pm):
    from paddle_tpu.distributed.auto_parallel import shard_tensor

    with unique_name.guard():
        paddle.seed(0)
        net = paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
            paddle.nn.Linear(32, 16),
        )
    # megatron-style 2-D annotation: fc1 column-split over mp, fc2 row-split
    shard_tensor(net[0].weight, process_mesh=pm, shard_spec=[None, "mp"])
    shard_tensor(net[0].bias, process_mesh=pm, shard_spec=["mp"])
    shard_tensor(net[2].weight, process_mesh=pm, shard_spec=["mp", None])
    return net


class _Rand(Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(3)
        self.x = rng.randn(n, 16).astype(np.float32)
        self.y = rng.randn(n, 16).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_engine_2d_annotated_mlp_trains_with_realized_shardings():
    """Round-3 VERDICT missing #3: annotations beyond batch-dim0 must be
    honored end-to-end — the dp x mp MLP trains and the params KEEP the
    annotated placements after optimizer steps."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    pm = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    net = _annotated_mlp(pm)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    eng = Engine(model=net, loss=lambda o, y: (o - y).pow(2).mean(),
                 optimizer=opt, process_mesh=pm)
    hist = eng.fit(_Rand(), batch_size=8, epochs=3)["loss"]
    assert hist[-1] < hist[0]
    specs = {id(net[0].weight): P(None, "mp"), id(net[0].bias): P("mp"),
             id(net[2].weight): P("mp", None)}
    checked = 0
    for p in net.parameters():
        want = specs.get(id(p))
        if want is None:
            continue
        sh = p._value.sharding
        assert isinstance(sh, NamedSharding), (p.name, sh)
        assert sh.is_equivalent_to(
            NamedSharding(pm.jax_mesh, want), p._value.ndim), (p.name, sh)
        checked += 1
    assert checked == 3


def test_reshard_roundtrip_between_meshes():
    from paddle_tpu.distributed.auto_parallel import reshard

    pm_a = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    pm_b = ProcessMesh(np.arange(4), dim_names=["z"])  # different device set
    x = Tensor(np.random.RandomState(7).randn(8, 12).astype(np.float32))
    a = reshard(x, process_mesh=pm_a, shard_spec=["x", "y"])
    assert a._value.addressable_shards[0].data.shape == (4, 3)
    b = reshard(a, process_mesh=pm_b, shard_spec=["z", None])
    assert b._value.addressable_shards[0].data.shape == (2, 12)
    assert len({s.device for s in b._value.addressable_shards}) == 4
    back = reshard(b, process_mesh=pm_a, shard_spec=[None, None])
    np.testing.assert_allclose(np.asarray(back._value),
                               np.asarray(x._value))


def test_engine_consumes_strategy_amp_merge_sharding():
    """strategy is no longer accepted-and-ignored: sharding places ZeRO
    state over dp, gradient_merge accumulates k micro-steps, amp wraps the
    step; training stays correct."""
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.sharding = True
    strat.sharding_configs = {"stage": 2}
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    strat.amp = True

    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    with unique_name.guard():
        paddle.seed(1)
        net = paddle.nn.Sequential(paddle.nn.Linear(16, 32),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(32, 16))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    eng = Engine(model=net, loss=lambda o, y: (o - y).pow(2).mean(),
                 optimizer=opt, strategy=strat, process_mesh=pm)
    hist = eng.fit(_Rand(), batch_size=8, epochs=3)["loss"]
    assert hist[-1] < hist[0]
    # ZeRO stage: accumulators sharded over dp
    inner = opt._inner_opt if hasattr(opt, "_inner_opt") else opt
    sharded = 0
    for store in eng._optimizer._accumulators.values():
        for acc in store.values():
            if getattr(acc, "ndim", 0) >= 1 and acc.size >= 8:
                assert (acc.addressable_shards[0].data.nbytes
                        == acc.nbytes // 8), acc.shape
                sharded += 1
    assert sharded >= 4


def test_engine_cluster_bounds_devices():
    class FakeCluster:
        device_count = 4

    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    with pytest.raises(ValueError, match="devices are available"):
        Engine(model=paddle.nn.Linear(4, 4), cluster=FakeCluster(),
               process_mesh=pm)
