"""Per-tick RNG for pipelined dropout + static-mode per-run dropout.
VERDICT item 8 + ADVICE medium (static dropout baked as constant).
Reference: fleet/meta_parallel/parallel_layers/random.py RNGStatesTracker."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.models import GPTConfig
from paddle_tpu.utils import unique_name


def _init_fleet(pp=2):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 1
    strategy.hybrid_configs["mp_degree"] = 1
    strategy.hybrid_configs["pp_degree"] = pp
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _build_piped(cfg, hcg, micro):
    from paddle_tpu.distributed.meta_parallel import build_pipelined_gpt

    return build_pipelined_gpt(cfg, hcg, num_microbatches=micro)


def test_pipelined_dropout_trains_and_varies():
    """dropout>0 no longer raises; identical microbatch contents produce
    different losses across steps (fresh masks), and eval mode is
    deterministic."""
    hcg = _init_fleet(pp=2)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, hidden_dropout=0.5,
                    attention_dropout=0.0)
    with unique_name.guard():
        paddle.seed(0)
        piped = _build_piped(cfg, hcg, micro=2)

    ids = Tensor(np.random.RandomState(0).randint(0, 64, (4, 16)).astype(np.int64))

    piped.train()
    l1 = float(np.asarray(piped.loss(ids, ids)._value))
    l2 = float(np.asarray(piped.loss(ids, ids)._value))
    assert np.isfinite(l1) and np.isfinite(l2)
    assert l1 != l2, "train-mode dropout produced identical losses across steps"

    piped.eval()
    e1 = float(np.asarray(piped.loss(ids, ids)._value))
    e2 = float(np.asarray(piped.loss(ids, ids)._value))
    assert e1 == e2, "eval mode must be deterministic"


def test_pipelined_dropout_masks_differ_across_microbatches():
    """Two microbatches with IDENTICAL content must get different masks."""
    hcg = _init_fleet(pp=2)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, hidden_dropout=0.5,
                    attention_dropout=0.0)
    with unique_name.guard():
        paddle.seed(0)
        piped = _build_piped(cfg, hcg, micro=2)
    piped.train()

    row = np.random.RandomState(1).randint(0, 64, (1, 16)).astype(np.int64)
    ids = Tensor(np.repeat(row, 4, axis=0))  # 4 identical rows, 2 microbatches
    out = piped(ids)  # [batch, seq, vocab] logits (no labels)
    a = np.asarray(out._value)
    # microbatch 0 = rows 0..1, microbatch 1 = rows 2..3; identical inputs,
    # different dropout ticks -> different activations
    assert not np.allclose(a[0], a[2]), "identical microbatches got identical masks"


def test_pipelined_dropout_eval_matches_single_device():
    """Eval-mode (dropout off) parity with the plain model is preserved."""
    from paddle_tpu.models import GPTForCausalLM
    from tests.test_pipeline_schedule import _copy_gpt_into_pipeline

    hcg = _init_fleet(pp=2)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, hidden_dropout=0.3,
                    attention_dropout=0.0)
    with unique_name.guard():
        paddle.seed(0)
        ref = GPTForCausalLM(cfg)
    with unique_name.guard():
        paddle.seed(1)
        piped = _build_piped(cfg, hcg, micro=2)
    _copy_gpt_into_pipeline(ref, piped, pp=2, per=1)

    ids = Tensor(np.random.RandomState(2).randint(0, 64, (4, 16)).astype(np.int64))
    ref.eval()
    piped.eval()
    l_ref = float(np.asarray(ref.loss(ids, ids)._value))
    l_pp = float(np.asarray(piped.loss(ids, ids)._value))
    np.testing.assert_allclose(l_pp, l_ref, rtol=2e-5)


def test_static_dropout_fresh_per_run():
    """ADVICE medium: static programs must draw fresh dropout masks per
    Executor.run (the mask is an in-graph op on the threaded RNG key, not a
    recorded constant)."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [8, 32], "float32")
            y = F.dropout(x, p=0.5, training=True)
            out_name = y.name
        exe = paddle.static.Executor()
        exe.run(startup)
        xv = np.ones((8, 32), np.float32)
        (r1,) = exe.run(main, feed={"x": xv}, fetch_list=[out_name])
        (r2,) = exe.run(main, feed={"x": xv}, fetch_list=[out_name])
        assert not np.allclose(r1, r2), "static dropout replayed an identical mask"
        # scale check: surviving entries are upscaled by 1/(1-p)
        kept = r1[r1 != 0]
        np.testing.assert_allclose(kept, 2.0, rtol=1e-6)
        # determinism under paddle.seed
        paddle.seed(7)
        (a1,) = exe.run(main, feed={"x": xv}, fetch_list=[out_name])
        paddle.seed(7)
        (a2,) = exe.run(main, feed={"x": xv}, fetch_list=[out_name])
        np.testing.assert_allclose(a1, a2)
    finally:
        paddle.disable_static()


def test_static_dropout_grad_consistent_with_forward():
    """The backward replay must see the SAME mask as the forward (both read
    the same per-run key)."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [4, 8], "float32")
            x.stop_gradient = False
            lin = paddle.nn.Linear(8, 8)
            h = lin(x)
            d = F.dropout(h, p=0.5, training=True)
            loss = (d * d).sum()
            pairs = paddle.static.append_backward(loss)
        w_name = lin.weight.name
        exe = paddle.static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        out, g = exe.run(main, feed={"x": xv},
                         fetch_list=[d.name, f"{w_name}@GRAD"])
        # d(loss)/dw = x^T @ (2*d*mask*scale); where out==0 the grad
        # contribution must vanish -> check grad is finite and nonzero
        assert np.isfinite(g).all() and (g != 0).any()
    finally:
        paddle.disable_static()


def test_static_clone_for_test_disables_dropout():
    """Program.clone(for_test=True) must run dropout as identity (reference
    clone(for_test=True) semantics)."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [4, 16], "float32")
            d = F.dropout(x, p=0.5, training=True)
        eval_prog = main.clone(for_test=True)
        exe = paddle.static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        (r1,) = exe.run(eval_prog, feed={"x": xv}, fetch_list=[d.name])
        (r2,) = exe.run(eval_prog, feed={"x": xv}, fetch_list=[d.name])
        np.testing.assert_allclose(r1, xv, atol=1e-7)  # identity, no mask
        np.testing.assert_allclose(r1, r2)
        # the train program still masks
        (t1,) = exe.run(main, feed={"x": xv}, fetch_list=[d.name])
        assert (t1 == 0).any()
    finally:
        paddle.disable_static()


def test_static_clone_for_test_downscale_mode():
    """downscale_in_infer dropout must become x*(1-p) at eval, not identity."""
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [4, 16], "float32")
            d = F.dropout(x, p=0.5, training=True, mode="downscale_in_infer")
        eval_prog = main.clone(for_test=True)
        exe = paddle.static.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        (r,) = exe.run(eval_prog, feed={"x": xv}, fetch_list=[d.name])
        np.testing.assert_allclose(r, 0.5 * xv, atol=1e-7)
    finally:
        paddle.disable_static()
