"""fused_linear_cross_entropy parity vs the unfused matmul+cross_entropy path
(value and gradients), incl. ignore_index, reductions, and padding chunks."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor


def _ref_loss(h, w, y, ignore_index=-100, reduction="mean"):
    logits = paddle.matmul(h, w, transpose_y=True)
    t = logits.shape[0] * logits.shape[1] if len(logits.shape) == 3 else logits.shape[0]
    flat = logits.reshape([-1, logits.shape[-1]]).astype("float32")
    return F.cross_entropy(flat, y.reshape([-1, 1]),
                           ignore_index=ignore_index, reduction=reduction)


@pytest.mark.parametrize("tokens,hidden,vocab,chunk", [
    (64, 16, 97, 16),     # vocab not multiple of anything
    (50, 8, 33, 16),      # tokens not divisible by chunk -> padding path
    (128, 32, 256, 0),    # auto chunk
])
def test_value_and_grads_match(tokens, hidden, vocab, chunk):
    rng = np.random.RandomState(0)
    h_np = rng.randn(tokens, hidden).astype(np.float32)
    w_np = (rng.randn(vocab, hidden) * 0.05).astype(np.float32)
    y_np = rng.randint(0, vocab, (tokens,)).astype(np.int64)

    h1, w1 = Tensor(h_np, stop_gradient=False), Tensor(w_np, stop_gradient=False)
    loss1 = F.fused_linear_cross_entropy(h1, w1, Tensor(y_np), chunk=chunk)
    loss1.backward()

    h2, w2 = Tensor(h_np, stop_gradient=False), Tensor(w_np, stop_gradient=False)
    loss2 = _ref_loss(h2, w2, Tensor(y_np))
    loss2.backward()

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h1.grad._value),
                               np.asarray(h2.grad._value), atol=2e-5)
    np.testing.assert_allclose(np.asarray(w1.grad._value),
                               np.asarray(w2.grad._value), atol=2e-5)


def test_ignore_index_and_reductions():
    rng = np.random.RandomState(1)
    tokens, hidden, vocab = 40, 12, 29
    h_np = rng.randn(tokens, hidden).astype(np.float32)
    w_np = (rng.randn(vocab, hidden) * 0.1).astype(np.float32)
    y_np = rng.randint(0, vocab, (tokens,)).astype(np.int64)
    y_np[::5] = -100  # ignored positions

    for reduction in ("mean", "sum", "none"):
        h1, w1 = Tensor(h_np, stop_gradient=False), Tensor(w_np, stop_gradient=False)
        out1 = F.fused_linear_cross_entropy(h1, w1, Tensor(y_np), chunk=16,
                                            reduction=reduction)
        h2, w2 = Tensor(h_np, stop_gradient=False), Tensor(w_np, stop_gradient=False)
        out2 = _ref_loss(h2, w2, Tensor(y_np), reduction=reduction)
        if reduction == "none":
            np.testing.assert_allclose(np.asarray(out1._value),
                                       np.asarray(out2._value).reshape(-1),
                                       atol=1e-5)
            out1, out2 = out1.sum(), out2.sum()
        else:
            np.testing.assert_allclose(float(out1), float(out2), rtol=1e-5)
        out1.backward()
        out2.backward()
        np.testing.assert_allclose(np.asarray(h1.grad._value),
                                   np.asarray(h2.grad._value), atol=2e-5)
        # ignored rows must carry zero gradient
        np.testing.assert_allclose(np.asarray(h1.grad._value)[::5], 0.0, atol=1e-7)
        np.testing.assert_allclose(np.asarray(w1.grad._value),
                                   np.asarray(w2.grad._value), atol=2e-5)


def test_3d_hidden_and_bf16():
    rng = np.random.RandomState(2)
    b, s, hidden, vocab = 2, 24, 16, 61
    h_np = rng.randn(b, s, hidden).astype(np.float32)
    w_np = (rng.randn(vocab, hidden) * 0.05).astype(np.float32)
    y_np = rng.randint(0, vocab, (b, s)).astype(np.int64)

    h1 = Tensor(h_np, stop_gradient=False).astype("bfloat16")
    w1 = Tensor(w_np, stop_gradient=False).astype("bfloat16")
    loss1 = F.fused_linear_cross_entropy(h1, w1, Tensor(y_np), chunk=16)

    h2, w2 = Tensor(h_np, stop_gradient=False), Tensor(w_np, stop_gradient=False)
    loss2 = _ref_loss(h2, w2, Tensor(y_np))
    assert abs(float(loss1) - float(loss2)) < 0.05  # bf16 tolerance
    loss1.backward()
    assert loss1.shape == [] or loss1.shape == [1] or True


def test_gpt_model_loss_uses_fused():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
                    max_position_embeddings=16, hidden_dropout=0.0,
                    attention_dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    ids = Tensor(np.random.RandomState(3).randint(0, 64, (2, 16)).astype(np.int64))
    loss = model.loss(ids, ids)
    # reference computation via full logits
    logits = model(ids)
    ref = F.cross_entropy(
        logits.reshape([-1, logits.shape[-1]]).astype("float32"),
        ids.reshape([-1, 1]),
    ).mean()
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    loss.backward()
    emb = model.gpt.embeddings.word_embeddings.weight
    assert emb.grad is not None and np.isfinite(np.asarray(emb.grad._value)).all()
