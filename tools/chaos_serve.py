#!/usr/bin/env python
"""Deterministic chaos harness for the serving tier (ISSUE 10 acceptance).

Floods a :class:`~paddle_tpu.serving.Scheduler` while injecting faults
through ``paddle_tpu.fault.inject`` and asserts the resilience contract:

* **full accounting** — every submitted request reaches EXACTLY ONE
  terminal ``finish_reason`` (``eos|length|timeout|shed|oom_evicted|
  error|drained``), and the ``serve.*`` telemetry counters agree with the
  per-request records;
* **no scheduler crash** — the injected OOM (``serve.decode``), transient
  prefill error (``serve.prefill``), draft fault (``serve.draft``),
  mid-verify faults (``serve.verify`` error + stall) and stall are
  absorbed by the degraded-decode / retry / plain-tick-fallback paths;
* **survivor parity** — the chaos pass serves with speculative decoding
  and chunked prefill ON while the clean reference runs the PLAIN greedy
  path (``Scheduler(speculative=False)``); every request that still
  finished normally (``eos``/``length``) must have produced the SAME
  token stream, token for token. That is the ISSUE-13 acceptance squared:
  spec output is byte-identical to greedy even while drafts drop,
  verifies fault mid-flight and neighbors get evicted around it;
* **overload pages** — an :class:`~paddle_tpu.profiler.slo.SLOMonitor`
  over the shipped ``SERVING_SLOS`` (driven on a synthetic clock, so burn
  windows are deterministic) must fire on the shed burst;
* **recovery** — after ``disarm_all()``, steady-state tokens/sec is back
  within 10% of the pre-chaos clean measurement (median of ``--reps``
  each).

The whole run is deterministic: seeded prompts, faults armed at fixed hit
counts, `retry_sleep` stubbed out, a deterministic largest-footprint OOM
victim, and submission order fixed — re-running produces the same event
log and the same survivor set.

Usage::

    python tools/chaos_serve.py --smoke       # CI gate (tiny CPU config)
    python tools/chaos_serve.py --json        # machine-readable result

``tools/bench_serve.py --chaos`` embeds this harness's verdict as the
``chaos_ok`` contract metric in the SERVE_r*.json artifact (direction
``equal`` in ``tools/bench_sentinel.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MAX_NEW = 8
CONCURRENCY = 4
MAX_QUEUE = 4
BUCKETS = (8, 16)
MAX_LEN = 64
SPEC_K = 4
PREFILL_CHUNK = 4


def build_engines(seed=0):
    """Tiny CPU GPT + TWO identically warmed engines over the same model:
    the chaos subject and a never-faulted CONTROL. The recovery check
    compares the two in interleaved passes, so slow host drift (thermal,
    another process) cancels instead of masquerading as a regression.
    Every executable — per-bucket prefill, decode, chunked prefill,
    speculative verify — is warmed up front; chaos must measure the
    steady state, not compiles."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_position_embeddings=MAX_LEN,
                    hidden_dropout=0.0, attention_dropout=0.0)
    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    engines = []
    for _ in range(2):
        eng = GenerationEngine(model, max_batch=CONCURRENCY,
                               max_len=MAX_LEN, prefill_buckets=BUCKETS,
                               spec_k=SPEC_K, prefill_chunk=PREFILL_CHUNK)
        for b in BUCKETS:
            eng.prefill(0, [1] * (b - 1))
        eng.decode_once(np.zeros(CONCURRENCY, np.int32))
        off, tok = 0, None
        warm = [1] * (PREFILL_CHUNK + 1)  # exactly two chunks
        while tok is None:
            tok = eng.prefill_chunk_step(0, warm, off)
            off += PREFILL_CHUNK
        # a verify does not advance lengths, so warming leaves no state
        eng.verify_once(np.zeros((CONCURRENCY, SPEC_K + 1), np.int32))
        engines.append(eng)
    return cfg, engines[0], engines[1]


def make_prompts(cfg, n, seed):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size,
                        int(rng.randint(4, BUCKETS[-1] // 2))).tolist()
            for _ in range(n)]


def _new_requests(prompts):
    from paddle_tpu.serving import Request

    return [Request(prompt=list(p), max_new_tokens=MAX_NEW) for p in prompts]


def run_clean(eng, prompts):
    """Reference pass: serve every prompt cleanly through the PLAIN
    greedy path (speculation forced off), return idx → tokens. The chaos
    pass then serves with speculation ON, so survivor parity doubles as
    the spec-vs-greedy byte-identity check under faults."""
    from paddle_tpu.serving import Scheduler

    sched = Scheduler(eng, speculative=False)
    reqs = _new_requests(prompts)
    for r in reqs:
        sched.submit(r)
    sched.run()
    return {i: list(r.tokens) for i, r in enumerate(reqs)}


def _tps_pass(eng, prompts):
    """One full serving pass → tokens/sec. Decodes 4× the chaos token
    budget so a pass is long enough (hundreds of decode ticks) for the
    10% recovery bar to sit above per-pass timing noise."""
    from paddle_tpu.serving import Request, Scheduler

    sched = Scheduler(eng)
    reqs = [Request(prompt=list(p), max_new_tokens=4 * MAX_NEW)
            for p in prompts]
    t0 = time.perf_counter()
    for r in reqs:
        sched.submit(r)
    fin = sched.run()
    wall = time.perf_counter() - t0
    return sum(len(r.tokens) for r in fin) / wall


def measure_pair(eng_a, eng_b, prompts, reps=3):
    """Best-of-``reps`` tokens/sec for two engines, passes INTERLEAVED
    (b, a, b, a, ...) so both sides sample the same host conditions.
    Best-of, not mean/median: host noise (GC, CPU frequency, another
    process) only ever SLOWS a pass, so the fastest pass is the cleanest
    steady-state estimate."""
    a_vals, b_vals = [], []
    for _ in range(reps):
        b_vals.append(_tps_pass(eng_b, prompts))
        a_vals.append(_tps_pass(eng_a, prompts))
    return max(a_vals), max(b_vals)


def run_chaos(seed=0, reps=3):
    """Clean → chaos → recovery. Returns a result dict with ``ok`` and the
    list of contract ``problems`` (empty on a green run)."""
    from paddle_tpu.fault import inject
    from paddle_tpu.profiler import telemetry, tracing
    from paddle_tpu.profiler.slo import SERVING_SLOS, SLOMonitor
    from paddle_tpu.serving import FINISH_REASONS, Request, Scheduler

    cfg, eng, control = build_engines(seed)
    prompts = make_prompts(cfg, 24, seed)

    # -- clean reference streams (survivor-parity baseline) ------------------
    clean_streams = run_clean(eng, prompts)

    problems = []
    counters = {}
    alerts = []
    reason_counts = {}
    survivors = 0
    try:
        # -- chaos pass ------------------------------------------------------
        inject.disarm_all()
        telemetry.reset()
        telemetry.enable(recompile_warn_threshold=len(BUCKETS) + 2)
        tracing.reset()
        tracing.enable()
        # synthetic clock (+1 s per check): SLO burn windows deterministic
        clk = {"now": 0.0}

        def clock():
            clk["now"] += 1.0
            return clk["now"]

        monitor = SLOMonitor(SERVING_SLOS, clock=clock,
                             sinks=[alerts.append])
        sched = Scheduler(eng, slo=monitor, slo_check_every=1,
                          max_queue=MAX_QUEUE,
                          retry_sleep=lambda s: None)
        # armed faults (fixed hit counts — fully replayable): a transient
        # prefill error the retry must absorb, a draft fault and two
        # mid-verify errors that must each fall back to a plain tick,
        # an OOM on one of those plain ticks (the third serve.decode hit)
        # that must evict exactly one victim, and a mid-verify stall (a
        # slow tick, not a dead one). With speculation healthy the
        # scheduler never decodes plain, so serve.decode hits are created
        # BY the draft/verify faults — the fallback chain under test.
        inject.arm("error", "serve.prefill", at=2)
        inject.arm("error", "serve.draft", at=2)
        inject.arm("error", "serve.verify", at=3)
        inject.arm("error", "serve.verify", at=5)
        inject.arm("oom", "serve.decode", at=3)
        inject.arm("stall", "serve.verify", at=7)

        chaos_reqs = _new_requests(prompts)
        # two requests with an already-expired deadline: deterministic
        # queue-wait timeouts at the first tick
        doomed = [Request(prompt=list(prompts[0]), max_new_tokens=MAX_NEW,
                          deadline_s=0.0) for _ in range(2)]
        submitted = list(doomed)
        for r in doomed:
            sched.submit(r)
        # flood in waves: each wave overflows the bounded queue (sheds burn
        # the serve.shed SLO between monitor checks), then the scheduler
        # ticks a few times before the next wave lands
        for lo in range(0, len(chaos_reqs), 8):
            for r in chaos_reqs[lo:lo + 8]:
                submitted.append(sched.submit(r))
            sched.step()
            sched.step()
        sched.run()
        sched.shutdown()
        inject.disarm_all()

        # -- contract checks -------------------------------------------------
        # exactly one terminal reason per submitted request
        fin = sched.finished
        if len(fin) != len(submitted):
            problems.append(f"accounting: {len(submitted)} submitted but "
                            f"{len(fin)} finished")
        if len({r.rid for r in fin}) != len(fin):
            problems.append("accounting: a request finished more than once")
        for r in submitted:
            if not r.finished or r.finish_reason not in FINISH_REASONS:
                problems.append(f"rid {r.rid}: no terminal finish_reason "
                                f"(got {r.finish_reason!r})")
                break
        for r in fin:
            reason_counts[r.finish_reason] = \
                reason_counts.get(r.finish_reason, 0) + 1
        # the injected faults must actually have produced their reasons
        for want in ("shed", "timeout", "oom_evicted"):
            if not reason_counts.get(want):
                problems.append(f"chaos produced no {want!r} termination")
        # telemetry counters must agree with the per-request records
        counters = {k: v for k, v in
                    telemetry.get_telemetry().counters().items()
                    if k.startswith("serve.")}
        for reason, counter in (("shed", "serve.shed"),
                                ("timeout", "serve.timeouts"),
                                ("oom_evicted", "serve.oom_evictions"),
                                ("drained", "serve.drained")):
            want = reason_counts.get(reason, 0)
            got = int(counters.get(counter, 0))
            if got != want:
                problems.append(f"{counter}={got} but {want} request(s) "
                                f"finished {reason!r}")
        if not counters.get("serve.degraded_steps"):
            problems.append("injected decode OOM did not count a "
                            "degraded step")
        # the speculative surface must have been exercised AND survived:
        # spec ticks ran, and both injected verify faults degraded to
        # plain ticks instead of killing the scheduler
        if not counters.get("serve.spec_ticks"):
            problems.append("chaos pass ran no speculative ticks")
        if int(counters.get("serve.spec_fallback_ticks", 0)) < 2:
            problems.append(
                f"expected both injected verify faults to force plain-"
                f"tick fallbacks, got serve.spec_fallback_ticks="
                f"{counters.get('serve.spec_fallback_ticks', 0)}")
        if not counters.get("serve.prefill_chunks"):
            problems.append("chaos pass never took the chunked-prefill "
                            "path")
        # abnormal terminations must be queryable as trace event spans
        span_names = {s.name for s in tracing.get_tracer().spans()}
        for want in ("shed", "timeout", "oom_evicted"):
            if want in reason_counts and want not in span_names:
                problems.append(f"no {want!r} trace event span recorded")
        # overload must page: the shed burst burns the serve.shed SLO
        if not any(a["metric"] == "serve.shed" for a in alerts):
            problems.append("SLO monitor never fired on the shed burst "
                            f"({len(alerts)} alert(s) total)")
        # survivor parity: normal finishers match the clean run exactly
        for i, r in enumerate(chaos_reqs):
            if r.finish_reason in ("eos", "length"):
                survivors += 1
                if r.tokens != clean_streams[i]:
                    problems.append(
                        f"survivor rid {r.rid} diverged from the clean "
                        f"run: {r.tokens[:4]}... vs "
                        f"{clean_streams[i][:4]}...")
        if survivors == 0:
            problems.append("chaos left no surviving request to check "
                            "parity against")
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        problems.append(f"scheduler crashed under chaos: {type(e).__name__}: "
                        f"{e}")
    finally:
        inject.disarm_all()
        telemetry.disable()
        tracing.disable()

    # -- recovery: post-chaos steady state within 10% of the clean control —
    # interleaved passes against the never-faulted engine, measured under
    # identical host conditions
    recovery_tps, clean_tps = measure_pair(eng, control, prompts, reps=reps)
    if recovery_tps < 0.9 * clean_tps:
        problems.append(f"post-chaos throughput {recovery_tps:.1f} tok/s "
                        f"recovered to less than 90% of the clean control "
                        f"{clean_tps:.1f} tok/s")

    return {
        "ok": not problems,
        "problems": problems,
        "submitted": 26,
        "finish_reasons": reason_counts,
        "survivors": survivors,
        "slo_alerts": len(alerts),
        "clean_tokens_per_sec": round(clean_tps, 2),
        "recovery_tokens_per_sec": round(recovery_tps, 2),
        "counters": {k: v for k, v in sorted(counters.items())},
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate (same deterministic run; nonzero exit on "
                         "any contract violation)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--reps", type=int, default=3,
                    help="throughput samples per median (clean + recovery)")
    ap.add_argument("--json", action="store_true",
                    help="print the result as one JSON object")
    args = ap.parse_args(argv)

    result = run_chaos(seed=args.seed, reps=args.reps)
    if args.json:
        print(json.dumps(result))
    else:
        status = "OK" if result["ok"] else "FAILED"
        print(f"chaos_serve {status}: {result['submitted']} submitted, "
              f"reasons {result['finish_reasons']}, "
              f"{result['survivors']} survivor(s) token-exact, "
              f"{result['slo_alerts']} SLO alert(s), clean "
              f"{result['clean_tokens_per_sec']} tok/s → recovery "
              f"{result['recovery_tokens_per_sec']} tok/s")
        for p in result["problems"]:
            print(f"  problem: {p}", file=sys.stderr)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
