#!/usr/bin/env python
"""Shard-lint the MULTICHIP zoo configs (static analysis only — nothing
executes on a device unless ``--measure`` is given).

For each config this builds a dryrun-shaped multichip train step (dp×mp
Megatron-style TP, dp×mp×sep ring attention, sharding×pp pipeline ticks,
MoE expert-parallel all_to_all), abstractly propagates shardings over its
jaxpr under the config's mesh (``paddle_tpu.analysis.shard_lint`` — no XLA
invocation), prints the findings table plus the predicted per-axis
collective bytes, and (with ``--jsonl``) emits one JSON object per finding.
``--format sarif`` instead writes a SARIF 2.1.0 document to stdout for CI
annotations.

``--measure`` additionally compiles each config that this host's backend
supports (dp-mp, moe, dp-zero on XLA:CPU) through ``profiler.devprof``
and prints
the predicted-vs-HLO-measured crosscheck rows
(``analysis.crosscheck_comm`` — the accuracy loop; within 10%, exact for
explicit shard_map collectives).

``--fixture mismatched-constraint`` re-builds every config with a
deliberately wrong ``with_sharding_constraint`` injected after the first
TP matmul: the regression fixture for ``spmd-implicit-resharding`` — the
run must exit 1 (``tools/run_tests.sh`` gates exactly this).

Exit status: 1 when any finding at/above ``--fail-on`` severity survived
(default ``error``).

Usage:
    JAX_PLATFORMS=cpu python tools/shard_lint.py
        [--models dp-mp dp-mp-sep sharding-pp moe dp-zero] [--jsonl PATH]
        [--format table|sarif] [--fixture mismatched-constraint]
        [--measure] [--fail-on error|warning|never]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# the zoo meshes need 8 virtual devices; flags must land before jax
# initializes its backend (same forcing as tests/conftest.py)
if os.environ.get("PADDLE_TPU_HW_TESTS") != "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mismatch(y_value, mesh, axis):
    """The injected defect: constrain a TP-sharded activation to a sharding
    that moves the model-parallel axis onto the batch dim — the propagated
    sharding disagrees and GSPMD must reshard every step."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        y_value, NamedSharding(mesh, P(axis, None)))


def build_dp_mp(fixture=None):
    """Megatron-style TP MLP under a dp×mp mesh: column-split l1, row-split
    l2 (partial sums → mp all-reduce), batch sharded over dp, SGD update.
    The canonical GSPMD config: every collective is partitioner-inserted
    (fwd mp psum + bwd dp gradient all-reduces) and the propagation must
    price them within 10% of the compiled HLO."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.utils import unique_name

    mesh = build_mesh({"dp": 2, "mp": 2})
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(32, 64)
        l2 = paddle.nn.Linear(64, 32)
    put = jax.device_put
    l1.weight._value = put(l1.weight._value, NamedSharding(mesh, P(None, "mp")))
    l1.bias._value = put(l1.bias._value, NamedSharding(mesh, P("mp")))
    l2.weight._value = put(l2.weight._value, NamedSharding(mesh, P("mp", None)))
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(l1.parameters()) + list(l2.parameters()))

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        if fixture == "mismatched-constraint":
            h._value = _mismatch(h._value, mesh, "mp")
        out = l2(h)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "dp_mp_train_step"
    step = CompiledStep(train_step, stateful=[l1, l2, opt],
                        donate_state=True)
    rng = np.random.RandomState(0)
    x = Tensor(put(jnp.asarray(rng.randn(16, 32), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    y = Tensor(put(jnp.asarray(rng.randn(16, 32), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    return step, (x, y), mesh, True  # measurable on XLA:CPU


def build_dp_mp_sep(fixture=None):
    """dp×mp×sep: ring attention — shard_map manual over the sep axis
    rotating KV blocks with ppermute (exact ring-model bytes), dp sharding
    the batch and a TP-sharded projection around it. Static-only on
    XLA:CPU (the partial-manual region needs PartitionId — real TPUs
    partition it fine)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.utils import unique_name

    mesh = build_mesh({"dp": 2, "mp": 2, "sep": 2})
    sep = 2
    with unique_name.guard():
        paddle.seed(0)
        proj = paddle.nn.Linear(16, 16)
    proj.weight._value = jax.device_put(
        proj.weight._value, NamedSharding(mesh, P(None, "mp")))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=proj.parameters())

    def ring(qv, kv, vv):
        def inner(q, k, v):
            # per-rank sequence block; rotate KV around the sep ring
            def tick(carry, _):
                k_blk, v_blk, acc = carry
                acc = acc + jnp.einsum("bqd,bkd->bqk", q, k_blk) @ v_blk
                k_blk = lax.ppermute(k_blk, "sep", [(0, 1), (1, 0)])
                v_blk = lax.ppermute(v_blk, "sep", [(0, 1), (1, 0)])
                return (k_blk, v_blk, acc), 0.0

            acc0 = jnp.zeros_like(q)
            (_, _, acc), _ = lax.scan(tick, (k, v, acc0),
                                      jnp.arange(sep))
            return acc / q.shape[1]

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P("dp", "sep"), P("dp", "sep"), P("dp", "sep")),
            out_specs=P("dp", "sep"), check_vma=False)(qv, kv, vv)

    from paddle_tpu.ops.dispatch import apply_op

    def train_step(x, y):
        h = proj(x)
        if fixture == "mismatched-constraint":
            h._value = _mismatch(h._value, mesh, "mp")
        attn = apply_op("ring_attn", ring, (h, h, h), {})
        loss = ((attn - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "dp_mp_sep_train_step"
    step = CompiledStep(train_step, stateful=[proj, opt], donate_state=True)
    rng = np.random.RandomState(1)
    mk = lambda: Tensor(jax.device_put(  # noqa: E731
        jnp.asarray(rng.randn(4, 8, 16), jnp.float32),
        NamedSharding(mesh, P("dp", "sep", None))))
    return step, (mk(), mk()), mesh, False


def build_sharding_pp(fixture=None):
    """sharding×pp: the pipeline's tick structure — microbatch activations
    rotated stage-to-stage with ppermute inside a scan over the schedule
    (T = M + pp − 1 ticks), stage weights sharded over pp, optimizer
    state ZeRO-sharded over the data axis. Static-only on XLA:CPU
    (PartitionId, as above)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.framework.tensor import Parameter, Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.ops.dispatch import apply_op

    pp, M, d = 2, 4, 16
    mesh = build_mesh({"sharding": 4, "pp": pp})
    rng = np.random.RandomState(2)
    holder = paddle.nn.Layer()
    w = Parameter(jax.device_put(
        jnp.asarray(rng.randn(pp, d, d) * 0.2, jnp.float32),
        NamedSharding(mesh, P("pp"))))
    holder.add_parameter("stages", w)
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w])

    def pipe(xv, wv):
        def inner(h_m, ws):
            s = lax.axis_index("pp")
            T = M + pp - 1

            def tick(buf, t):
                x0 = jnp.take(h_m, jnp.clip(t, 0, M - 1), axis=0)
                x_in = jnp.where(s == 0, x0, buf)
                y = jnp.tanh(x_in @ ws[0])
                nxt = lax.ppermute(y, "pp",
                                   [(i, (i + 1) % pp) for i in range(pp)])
                return nxt, y

            _, ys = lax.scan(tick, jnp.zeros_like(h_m[0]), jnp.arange(T))
            outs = ys[pp - 1:]
            mask = (s == pp - 1).astype(outs.dtype)
            return lax.psum(outs * mask, "pp")

        return jax.shard_map(
            inner, mesh=mesh, in_specs=(P(None, "sharding"), P("pp")),
            out_specs=P(None, "sharding"), check_vma=False)(xv, wv)

    def train_step(x):
        out = apply_op("pipe_ticks", pipe, (x, w), {})
        loss = ((out - 1.0) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "sharding_pp_train_step"
    step = CompiledStep(train_step, stateful=[holder, opt],
                        donate_state=True)
    x = Tensor(jax.device_put(
        jnp.asarray(rng.randn(M, 8, d), jnp.float32),
        NamedSharding(mesh, P(None, "sharding", None))))
    return step, (x,), mesh, False


def build_moe(fixture=None):
    """MoE expert parallelism: stacked expert weights sharded over the ep
    axis, token exchange as the explicit shard_map all_to_all pair
    (dispatch + combine, the reference ``global_scatter``/``global_gather``
    comm pattern) — every collective is explicit, so the prediction is
    EXACT against the compiled HLO."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.framework.tensor import Parameter, Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.ops.dispatch import apply_op

    ep, d, cap = 8, 16, 4
    mesh = build_mesh({"ep": ep})
    rng = np.random.RandomState(0)
    holder = paddle.nn.Layer()
    w = Parameter(jax.device_put(
        jnp.asarray(rng.randn(ep, d, d) * 0.1, jnp.float32),
        NamedSharding(mesh, P("ep"))))
    holder.add_parameter("experts", w)
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w])

    def moe(xv, wv):
        def inner(xs, ws):
            # xs [E, cap, d] rows grouped by destination expert
            recv = lax.all_to_all(xs, "ep", split_axis=0, concat_axis=1,
                                  tiled=True)
            h = jax.nn.relu(jnp.einsum("ecd,df->ecf", recv, ws[0]))
            return lax.all_to_all(h, "ep", split_axis=1, concat_axis=0,
                                  tiled=True)

        return jax.shard_map(inner, mesh=mesh, in_specs=(P("ep"), P("ep")),
                             out_specs=P("ep"), check_vma=False)(xv, wv)

    def train_step(x):
        if fixture == "mismatched-constraint":
            x = paddle.framework.tensor.Tensor(
                _mismatch(x._value, mesh, None))
        out = apply_op("moe_sm", moe, (x, w), {})
        loss = ((out - 1.0) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "moe_train_step"
    step = CompiledStep(train_step, stateful=[holder, opt],
                        donate_state=True)
    x = Tensor(jax.device_put(
        jnp.asarray(rng.randn(ep * ep, cap, d), jnp.float32),
        NamedSharding(mesh, P("ep"))))
    return step, (x,), mesh, True  # measurable on XLA:CPU


def build_dp_zero(fixture=None):
    """Pure-dp ZeRO sharded weight update (distributed/sharding/zero.py):
    grads constrained to each param's 1/dp shard at the optimizer, AdamW
    moments + step on the shard, params constrained back to replicated —
    the partitioner materializes the reduce-scatter/all-gather pair (on
    XLA:CPU: all-reduce + fused local slice, priced identically). The
    ``spmd-replicated-optimizer-state`` rule must stay quiet here, and the
    predicted dp bytes must match the compiled HLO within 10%."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.sharding import ShardedOptimizer
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.utils import unique_name

    mesh = build_mesh({"dp": 8})
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(64, 256)
        l2 = paddle.nn.Linear(256, 64)
    rep = NamedSharding(mesh, P())
    for lyr in (l1, l2):
        for p in lyr.parameters():
            p._value = jax.device_put(p._value, rep)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2,
        parameters=list(l1.parameters()) + list(l2.parameters()))
    opt = ShardedOptimizer(opt, axis="dp", mesh=mesh)

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        if fixture == "mismatched-constraint":
            h._value = _mismatch(h._value, mesh, "dp")
        out = l2(h)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "dp_zero_train_step"
    step = CompiledStep(train_step, stateful=[l1, l2, opt._inner_opt],
                        donate_state=True)
    rng = np.random.RandomState(3)
    put = jax.device_put
    x = Tensor(put(jnp.asarray(rng.randn(32, 64), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    y = Tensor(put(jnp.asarray(rng.randn(32, 64), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    return step, (x, y), mesh, True  # measurable on XLA:CPU


ZOO = {
    "dp-mp": build_dp_mp,
    "dp-mp-sep": build_dp_mp_sep,
    "sharding-pp": build_sharding_pp,
    "moe": build_moe,
    "dp-zero": build_dp_zero,
}


def lint_zoo(models, fixture=None, measure=False, out=sys.stdout,
             fusion=True):
    """Returns ``[(name, LintReport, ShardingAnalysis, crosscheck_rows)]``
    (import-friendly: the tests drive this directly). ``fusion`` toggles
    the fusion-aware ``comm_fraction`` denominator (materialized bytes
    instead of the raw all-intermediates proxy)."""
    from paddle_tpu import analysis

    results = []
    for name in models:
        step, batch, mesh, measurable = ZOO[name](fixture=fixture)
        report = analysis.lint_step(step, *batch, mesh=mesh,
                                    config={"fusion": bool(fusion)})
        sa = report.sharding  # the propagation lint_step ran
        print(f"\n== {name} ({step.name}) ==", file=out)
        print(report.table(), file=out)
        if sa is not None:
            print(sa.table(), file=out)
        rows = None
        if measure and measurable:
            from paddle_tpu.profiler import devprof

            rep = devprof.device_report(step, *batch, register=False)
            rows = analysis.crosscheck_comm(sa, rep)
            for r in rows:
                ratio = ("n/a" if r["ratio"] is None
                         else f"{r['ratio']:.3f}")
                print(f"crosscheck: axis={r['axis']} "
                      f"predicted={r['predicted_bytes']:.0f} "
                      f"measured={r['measured_bytes']:.0f} "
                      f"ratio={ratio} agrees={r['agrees']}", file=out)
        elif measure:
            print(f"crosscheck: skipped ({name} needs a backend with "
                  f"SPMD PartitionId — static prediction only on this "
                  f"host)", file=out)
        results.append((name, report, sa, rows))
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--models", nargs="+",
                    default=["dp-mp", "dp-mp-sep", "sharding-pp", "moe",
                             "dp-zero"],
                    choices=sorted(ZOO))
    ap.add_argument("--jsonl", default=None,
                    help="write one JSON object per finding to this path")
    ap.add_argument("--format", default="table",
                    choices=["table", "sarif"],
                    help="sarif: emit a SARIF 2.1.0 document on stdout "
                         "(CI annotations) instead of tables")
    ap.add_argument("--fixture", default=None,
                    choices=["mismatched-constraint"],
                    help="inject a wrong with_sharding_constraint after "
                         "the first TP matmul (spmd-implicit-resharding "
                         "regression; the run must exit 1)")
    ap.add_argument("--measure", action="store_true",
                    help="also compile measurable configs via devprof and "
                         "print the predicted-vs-HLO crosscheck")
    ap.add_argument("--no-fusion", action="store_true",
                    help="disable the fusion simulation: comm_fraction "
                         "falls back to the raw all-intermediates bytes "
                         "proxy (pre-ISSUE-18 behavior)")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "never"],
                    help="exit 1 when findings at/above this severity "
                         "exist")
    args = ap.parse_args(argv)

    sink = open(os.devnull, "w") if args.format == "sarif" else sys.stdout
    results = lint_zoo(args.models, fixture=args.fixture,
                       measure=args.measure, out=sink,
                       fusion=not args.no_fusion)

    if args.format == "sarif":
        from paddle_tpu.analysis import sarif_report

        findings = [f for _, report, _, _ in results for f in report]
        json.dump(sarif_report(findings, tool="paddle-tpu-shard-lint"),
                  sys.stdout, indent=1)
        sys.stdout.write("\n")

    if args.jsonl:
        with open(args.jsonl, "w") as fh:
            for name, report, _, _ in results:
                for f in report:
                    fh.write(json.dumps({"model": name, **f.as_dict()},
                                        sort_keys=True) + "\n")
        print(f"wrote {sum(len(r) for _, r, _, _ in results)} findings to "
              f"{args.jsonl}", file=sink)

    n_err = sum(len(r.errors) for _, r, _, _ in results)
    n_warn = sum(len(r.warnings) for _, r, _, _ in results)
    bad_cross = sum(1 for _, _, _, rows in results
                    for r in (rows or ()) if not r["agrees"])
    print(f"\nshard lint: {n_err} error(s), {n_warn} warning(s), "
          f"{bad_cross} crosscheck disagreement(s) across "
          f"{len(results)} config(s)", file=sink)
    if args.fail_on == "never":
        return 0
    gate = n_err + bad_cross + (n_warn if args.fail_on == "warning" else 0)
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
