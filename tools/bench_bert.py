"""BERT-large MLM train-step throughput on one TPU chip (BASELINE.md
config 3).

Prints ONE JSON line per sequence length and (on TPU) writes
``BERT_r05.json`` at the repo root with both entries.

Recipe: BERT-large (340M, 24L/1024H/16 heads), bf16 compute with fp32
layernorms and fp32 master weights, dense bidirectional attention through
the packed seq-major flash kernel (no padding mask — throughput regime),
MLM loss via the fused linear+cross-entropy head (the [tokens, vocab]
logits never materialize). Reference capability: the fleet BERT configs
(``reference/python/paddle/fluid/tests/unittests/test_bert*``) and the
BERT-large tokens/sec/chip metric demanded by BASELINE.md.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python tools/bench_bert.py
       [--seq 128 512] [--batch N] [--iters N] [--no-artifact]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from bench_common import (  # noqa: E402
    compiled_flops,
    device_peak,
    measure_steps,
    telemetry_block,
    retry,
)

# measured per-chip optima on v5e (b256@s128 and b64@s512 OOM against the
# AdamW fp32-master/moment state of the 340M model; s512: b48 42.4k > b32
# 40.6k tok/s)
DEFAULT_BATCH = {128: 128, 512: 48}


def _run_one(seq, batch=None, iters=None):
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import BertConfig, BertForPretraining, bert_large

    if on_tpu:
        cfg = bert_large()
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
        batch = batch or DEFAULT_BATCH.get(seq, max(1, 32768 // seq))
        iters = iters or 10
    else:  # smoke-scale for CPU verification runs
        cfg = BertConfig(vocab_size=512, hidden_size=128, num_layers=2,
                         num_heads=2, intermediate_size=256,
                         max_position_embeddings=max(seq, 64),
                         hidden_dropout=0.0, attention_dropout=0.0)
        batch = batch or 4
        iters = iters or 3

    paddle.seed(0)
    model = BertForPretraining(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        for _, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        multi_precision=on_tpu,
    )

    def train_step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True)

    rng = np.random.RandomState(int.from_bytes(os.urandom(4), "little"))
    batches = []
    for _ in range(3 + iters):
        ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        # MLM regime: loss on ~15% of positions, rest ignore_index
        labels = np.where(rng.rand(batch, seq) < 0.15,
                          rng.randint(0, cfg.vocab_size, (batch, seq)),
                          -100).astype(np.int64)
        batches.append((Tensor(ids), Tensor(labels)))

    total, _ = measure_steps(step, batches, iters)
    tokens_per_sec = batch * seq * iters / total
    telemetry = telemetry_block(total, iters)

    kind, peak = device_peak()
    flops = compiled_flops(step, batches)
    hfu = (flops * tokens_per_sec / (batch * seq) / peak) \
        if (flops and peak) else None
    # analytic: 6*N_matmul + 12*L*H*s flops/token (encoder blocks + tied MLM
    # head + transform), same convention as bench.py
    h_, l_, v_, i_ = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                      cfg.intermediate_size)
    n_matmul = l_ * (4 * h_ * h_ + 2 * h_ * i_) + h_ * h_ + v_ * h_
    flops_per_token = 6 * n_matmul + 12 * l_ * h_ * seq
    mfu = tokens_per_sec * flops_per_token / peak if peak else None

    return {
        "metric": f"bert-large MLM train throughput ({backend})" if on_tpu
                  else f"bert-smoke MLM train throughput ({backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "seq": seq,
        "batch": batch,
        "device_kind": kind,
        "step_flops": flops,
        "hw_flops_util": round(hfu, 4) if hfu else None,
        "mfu": round(mfu, 4) if mfu else None,
        "telemetry": telemetry,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, nargs="+", default=[128, 512])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--no-artifact", action="store_true")
    a = ap.parse_args()

    import jax

    results = []
    for seq in a.seq:
        results.append(retry(lambda s=seq: _run_one(s, a.batch, a.iters)))
        print(json.dumps(results[-1]))
        jax.clear_caches()
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu and not a.no_artifact:
        with open("BERT_r05.json", "w") as f:
            json.dump({"results": results}, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
