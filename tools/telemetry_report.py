#!/usr/bin/env python
"""Render the pipeline phase-breakdown table from a telemetry JSONL.

Reads the scalar stream written by ``profiler.telemetry.export_scalars``
(via ``utils.log_writer.LogWriter`` — e.g. from the
``hapi.callbacks.TelemetryLogger`` callback) and prints the same style of
table as ``telemetry.report()``: cumulative per-phase totals, per-step
phase samples, counters and gauges.

Usage::

    python tools/telemetry_report.py <vdlrecords.jsonl | logdir>

Stdlib-only on purpose: the CI smoke path (tools/run_tests.sh) runs it
without importing jax.
"""
from __future__ import annotations

import glob
import json
import os
import sys

PHASE_ORDER = ("data_wait", "h2d_copy", "compile", "dispatch", "readback")


def load_records(path):
    """Parse one JSONL file (or the newest ``*.jsonl`` in a directory)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl")),
                       key=os.path.getmtime)
        if not files:
            raise FileNotFoundError(f"no *.jsonl files under {path}")
        path = files[-1]
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # tolerate partial trailing writes
    return path, records


def collect(records):
    """Fold the scalar stream: cumulative tags keep their LAST value,
    per-step samples (telemetry/step/<phase>_s) aggregate count/sum/max."""
    last = {}
    steps = {}
    for r in records:
        tag, value = r.get("tag"), r.get("value")
        if not isinstance(tag, str) or value is None:
            continue
        if tag.startswith("telemetry/step/"):
            name = tag[len("telemetry/step/"):]
            if name.endswith("_s"):
                name = name[:-2]
            s = steps.setdefault(name, {"count": 0, "sum": 0.0, "max": 0.0})
            s["count"] += 1
            s["sum"] += float(value)
            s["max"] = max(s["max"], float(value))
        elif tag.startswith("telemetry/"):
            last[tag] = float(value)
    phases = {}
    for tag, value in last.items():
        if tag.startswith("telemetry/phase/"):
            name, _, field = tag[len("telemetry/phase/"):].rpartition("/")
            phases.setdefault(name, {})[field] = value
    counters = {t[len("telemetry/counter/"):]: v for t, v in last.items()
                if t.startswith("telemetry/counter/")}
    gauges = {t[len("telemetry/gauge/"):]: v for t, v in last.items()
              if t.startswith("telemetry/gauge/")}
    return phases, steps, counters, gauges


def build_table(phases, steps, counters, gauges):
    lines = [f"{'Phase':<12} {'Count':>8} {'Total(s)':>12} {'Mean(ms)':>12} "
             f"{'Frac(%)':>9}"]
    lines.append("-" * 58)
    denom = sum(p.get("total_s", 0.0) for p in phases.values()) or 1.0
    order = [p for p in PHASE_ORDER if p in phases]
    order += [p for p in sorted(phases) if p not in PHASE_ORDER]
    for name in order:
        p = phases[name]
        total = p.get("total_s", 0.0)
        count = int(p.get("count", 0))
        mean = p.get("mean_s", total / count if count else 0.0)
        lines.append(f"{name:<12} {count:>8} {total:>12.4f} "
                     f"{mean * 1e3:>12.3f} {100.0 * total / denom:>9.2f}")
    lines.append("-" * 58)
    if steps:
        lines.append(f"{'per-step samples':<21} {'N':>6} {'Mean(ms)':>12} "
                     f"{'Max(ms)':>12}")
        for name in sorted(steps):
            s = steps[name]
            mean = s["sum"] / s["count"] if s["count"] else 0.0
            lines.append(f"  {name:<19} {s['count']:>6} {mean * 1e3:>12.3f} "
                         f"{s['max'] * 1e3:>12.3f}")
    if counters:
        lines.append("counters:")
        for k in sorted(counters):
            v = counters[k]
            lines.append(f"  {k:<38} {int(v) if v == int(v) else v}")
    if gauges:
        lines.append("gauges:")
        for k in sorted(gauges):
            lines.append(f"  {k:<38} {gauges[k]:g}")
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    path, records = load_records(argv[0])
    phases, steps, counters, gauges = collect(records)
    if not (phases or steps or counters or gauges):
        print(f"{path}: no telemetry/* scalars found", file=sys.stderr)
        return 1
    print(f"telemetry report — {path}")
    print(build_table(phases, steps, counters, gauges))
    return 0


if __name__ == "__main__":
    sys.exit(main())
