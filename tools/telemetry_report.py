#!/usr/bin/env python
"""Render the pipeline phase-breakdown table from a telemetry JSONL.

Reads the scalar stream written by ``profiler.telemetry.export_scalars``
(via ``utils.log_writer.LogWriter`` — e.g. from the
``hapi.callbacks.TelemetryLogger`` callback) and prints the same style of
table as ``telemetry.report()``: cumulative per-phase totals, per-step
phase samples, counters and gauges.

Usage::

    python tools/telemetry_report.py <vdlrecords.jsonl | logdir>

Stdlib-only on purpose: the CI smoke path (tools/run_tests.sh) runs it
without importing jax.
"""
from __future__ import annotations

import glob
import json
import os
import sys

PHASE_ORDER = ("data_wait", "h2d_copy", "compile", "dispatch", "readback")

#: devprof harvest scalars rendered in their own section (matches
#: Telemetry.DEVICE_PREFIXES)
DEVICE_PREFIXES = ("hbm.", "comm.", "cost.", "pipeline.", "oom.")

#: serving-tier scalars (scheduler/engine) rendered in their own
#: humanized section instead of the generic counter table
SERVE_PREFIX = "serve."


def _is_device_stat(name):
    return any(name.startswith(p) for p in DEVICE_PREFIXES)


def _is_serve_stat(name):
    return name.startswith(SERVE_PREFIX)


def _human_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{int(n)} B" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024.0


def load_records(path):
    """Parse one JSONL file (or the newest ``*.jsonl`` in a directory)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl")),
                       key=os.path.getmtime)
        if not files:
            raise FileNotFoundError(f"no *.jsonl files under {path}")
        path = files[-1]
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # tolerate partial trailing writes
    return path, records


def collect(records):
    """Fold the scalar stream: cumulative tags keep their LAST value,
    per-step samples (telemetry/step/<phase>_s) aggregate count/sum/max."""
    last = {}
    steps = {}
    for r in records:
        tag, value = r.get("tag"), r.get("value")
        if not isinstance(tag, str) or value is None:
            continue
        if tag.startswith("telemetry/step/"):
            name = tag[len("telemetry/step/"):]
            if name.endswith("_s"):
                name = name[:-2]
            s = steps.setdefault(name, {"count": 0, "sum": 0.0, "max": 0.0})
            s["count"] += 1
            s["sum"] += float(value)
            s["max"] = max(s["max"], float(value))
        elif tag.startswith("telemetry/"):
            last[tag] = float(value)
    phases = {}
    hists = {}
    for tag, value in last.items():
        if tag.startswith("telemetry/phase/"):
            name, _, field = tag[len("telemetry/phase/"):].rpartition("/")
            phases.setdefault(name, {})[field] = value
        elif tag.startswith("telemetry/hist/"):
            name, _, field = tag[len("telemetry/hist/"):].rpartition("/")
            hists.setdefault(name, {})[field] = value
    counters = {t[len("telemetry/counter/"):]: v for t, v in last.items()
                if t.startswith("telemetry/counter/")}
    gauges = {t[len("telemetry/gauge/"):]: v for t, v in last.items()
              if t.startswith("telemetry/gauge/")}
    return phases, steps, counters, gauges, hists


def _hist_rows(hists, lines, indent="  "):
    """Histogram rows: exact count/sum plus the reservoir percentiles."""
    lines.append(f"{indent}{'histogram':<23} {'Count':>7} {'Sum':>11} "
                 f"{'Mean':>10} {'P50':>10} {'P95':>10}")
    for name in sorted(hists):
        h = hists[name]
        count = int(h.get("count", 0))
        total = h.get("sum", 0.0)
        mean = h.get("mean", total / count if count else 0.0)
        lines.append(f"{indent}{name:<23} {count:>7} {total:>11.4f} "
                     f"{mean:>10.4f} {h.get('p50', 0.0):>10.4f} "
                     f"{h.get('p95', 0.0):>10.4f}")


def build_table(phases, steps, counters, gauges, hists=None):
    hists = hists or {}
    has_pct = any("p50_s" in p or "p95_s" in p for p in phases.values())
    head = f"{'Phase':<12} {'Count':>8} {'Total(s)':>12} {'Mean(ms)':>12} "
    if has_pct:
        head += f"{'P50(ms)':>10} {'P95(ms)':>10} "
    head += f"{'Frac(%)':>9}"
    lines = [head]
    width = 79 if has_pct else 58
    lines.append("-" * width)
    denom = sum(p.get("total_s", 0.0) for p in phases.values()) or 1.0
    order = [p for p in PHASE_ORDER if p in phases]
    order += [p for p in sorted(phases) if p not in PHASE_ORDER]
    for name in order:
        p = phases[name]
        total = p.get("total_s", 0.0)
        count = int(p.get("count", 0))
        mean = p.get("mean_s", total / count if count else 0.0)
        row = f"{name:<12} {count:>8} {total:>12.4f} {mean * 1e3:>12.3f} "
        if has_pct:
            row += (f"{p.get('p50_s', 0.0) * 1e3:>10.3f} "
                    f"{p.get('p95_s', 0.0) * 1e3:>10.3f} ")
        row += f"{100.0 * total / denom:>9.2f}"
        lines.append(row)
    lines.append("-" * width)
    if steps:
        lines.append(f"{'per-step samples':<21} {'N':>6} {'Mean(ms)':>12} "
                     f"{'Max(ms)':>12}")
        for name in sorted(steps):
            s = steps[name]
            mean = s["sum"] / s["count"] if s["count"] else 0.0
            lines.append(f"  {name:<19} {s['count']:>6} {mean * 1e3:>12.3f} "
                         f"{s['max'] * 1e3:>12.3f}")
    plain_counters = {k: v for k, v in counters.items()
                      if not _is_device_stat(k) and not _is_serve_stat(k)}
    dev_counters = {k: v for k, v in counters.items() if _is_device_stat(k)}
    serve_counters = {k: v for k, v in counters.items() if _is_serve_stat(k)}
    plain_gauges = {k: v for k, v in gauges.items()
                    if not _is_device_stat(k) and not _is_serve_stat(k)}
    dev_gauges = {k: v for k, v in gauges.items() if _is_device_stat(k)}
    serve_gauges = {k: v for k, v in gauges.items() if _is_serve_stat(k)}
    serve_hists = {k: v for k, v in hists.items() if _is_serve_stat(k)}
    plain_hists = {k: v for k, v in hists.items() if not _is_serve_stat(k)}
    if plain_counters:
        lines.append("counters:")
        for k in sorted(plain_counters):
            v = plain_counters[k]
            lines.append(f"  {k:<38} {int(v) if v == int(v) else v}")
    if plain_gauges:
        lines.append("gauges:")
        for k in sorted(plain_gauges):
            lines.append(f"  {k:<38} {plain_gauges[k]:g}")
    if plain_hists:
        lines.append("histograms:")
        _hist_rows(plain_hists, lines)
    if serve_counters or serve_gauges or serve_hists:
        # serving tier (scheduler/engine): request lifecycle counters,
        # in-flight gauges and the latency/TTFT histograms in one place
        lines.append("serving:")
        for k in sorted(serve_gauges):
            lines.append(f"  {k:<38} {serve_gauges[k]:g}")
        for k in sorted(serve_counters):
            v = serve_counters[k]
            lines.append(f"  {k:<38} {int(v) if v == int(v) else v}")
        if serve_hists:
            _hist_rows(serve_hists, lines)
    if dev_gauges or dev_counters:
        # devprof harvest: HBM breakdown, per-axis collective bytes,
        # pipeline-schedule metrics (see tools/mem_report.py for the
        # ranked standalone view)
        lines.append("device stats:")
        for k in sorted(dev_gauges):
            v = dev_gauges[k]
            if k.endswith(("_bytes", ".bytes")):
                lines.append(f"  {k:<38} {_human_bytes(v)}")
            else:
                lines.append(f"  {k:<38} {v:g}")
        for k in sorted(dev_counters):
            v = dev_counters[k]
            if ".bytes." in k:
                lines.append(f"  {k:<38} {_human_bytes(v)}")
            else:
                lines.append(f"  {k:<38} {int(v) if v == int(v) else v}")
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    path, records = load_records(argv[0])
    phases, steps, counters, gauges, hists = collect(records)
    if not (phases or steps or counters or gauges or hists):
        print(f"{path}: no telemetry/* scalars found", file=sys.stderr)
        return 1
    print(f"telemetry report — {path}")
    print(build_table(phases, steps, counters, gauges, hists))
    return 0


if __name__ == "__main__":
    sys.exit(main())
