#!/usr/bin/env python
"""Memory-lint the MULTICHIP + serving zoo configs (static analysis only —
nothing executes on a device unless ``--measure`` is given).

For each config this builds a dryrun-shaped step (dp×mp Megatron-style TP
train step; the static-shape ``serve_decode`` over the KV cache), runs the
abstract per-equation liveness analysis over its jaxpr
(``paddle_tpu.analysis.mem_lint`` — no XLA invocation), prints the findings
table plus the predicted memory timeline (live-set peak, top contributors
with pytree/eqn provenance), and (with ``--jsonl``) emits one JSON object
per finding. ``--format sarif`` instead writes a SARIF 2.1.0 document to
stdout for CI annotations.

``--measure`` additionally compiles each config through
``profiler.devprof`` and prints the predicted-vs-measured HBM peak
crosscheck (``analysis.crosscheck_mem`` — the accuracy loop; the
prediction is an upper-bound model, gated at ``MEM_RTOL`` and never
allowed to UNDER-predict the compiled peak beyond it).

``--fixture undonated-longctx`` swaps the zoo for a long-context
attention step whose weights are NOT donated, linted against a small HBM
budget: the regression fixture for ``hbm-peak-over-capacity`` (+
``hbm-undonated-input`` with its predicted peak delta) — the run must
exit 1 (``tools/run_tests.sh`` gates exactly this).

``--smoke`` runs the CI gate in one go: clean zoo with ``--measure``
(zero errors, crosscheck agrees) AND the fixture (must exit 1).

Exit status: 1 when any finding at/above ``--fail-on`` severity survived
(default ``error``) or a crosscheck row disagreed.

Usage:
    JAX_PLATFORMS=cpu python tools/mem_lint.py
        [--models dp-mp serve-decode] [--jsonl PATH]
        [--format table|sarif] [--fixture undonated-longctx]
        [--measure] [--capacity BYTES] [--fail-on error|warning|never]
        [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# the dp×mp mesh needs virtual devices; flags must land before jax
# initializes its backend (same forcing as tests/conftest.py)
if os.environ.get("PADDLE_TPU_HW_TESTS") != "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the fixture's injected HBM budget (bytes) when --capacity is not given:
#: well under the undonated long-context peak, well over the clean zoo's
FIXTURE_CAPACITY = 16 << 20

#: the long-context gate's synthetic budget: the blockwise longctx
#: timeline predicts ~45 MiB and fits, the einsum score matrix pushes the
#: SAME shapes to ~80 MiB and must blow it (run_tests.sh asserts both)
LONGCTX_CAPACITY = 56 << 20


def build_dp_mp(fixture=None):
    """Megatron-style TP MLP train step under a dp×mp mesh, sized so real
    activation residuals (not fusion-elidable elementwise temps) dominate
    the peak — the config the predicted-vs-measured crosscheck is gated
    on. Donated state: the timeline's donation aliasing must match XLA's
    arg/out alias accounting."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.utils import unique_name

    mesh = build_mesh({"dp": 2, "mp": 2})
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(512, 2048)
        l2 = paddle.nn.Linear(2048, 512)
    put = jax.device_put
    l1.weight._value = put(l1.weight._value,
                           NamedSharding(mesh, P(None, "mp")))
    l1.bias._value = put(l1.bias._value, NamedSharding(mesh, P("mp")))
    l2.weight._value = put(l2.weight._value,
                           NamedSharding(mesh, P("mp", None)))
    l2.bias._value = put(l2.bias._value, NamedSharding(mesh, P()))
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(l1.parameters()) + list(l2.parameters()))

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        out = l2(h)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "dp_mp_train_step"
    step = CompiledStep(train_step, stateful=[l1, l2, opt],
                        donate_state=True)
    rng = np.random.RandomState(0)
    x = Tensor(put(jnp.asarray(rng.randn(256, 512), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    y = Tensor(put(jnp.asarray(rng.randn(256, 512), jnp.float32),
                   NamedSharding(mesh, P("dp", None))))
    return step, (x, y), mesh, True  # measurable on XLA:CPU


def build_serve_decode(fixture=None):
    """The serving tier's O(1) static-shape ``serve_decode`` over the KV
    cache (small GPT, weights threaded as donated state so the compiled
    ``memory_analysis`` counts them as arguments — the crosscheckable
    configuration)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.utils import unique_name

    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=128, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    eng = GenerationEngine(model, max_batch=4, max_len=128,
                           freeze_weights=False)
    return eng.decode_step, tuple(eng.example_decode_args([3, 5])), None, True


def _build_dp_adam(zero):
    """Shared builder for the ZeRO optimizer-state accounting pair: a bf16
    MLP under a pure-dp mesh with AdamW(multi_precision=True) — 12 bytes of
    fp32 optimizer state per param (master + moment1 + moment2). ``dp-plain``
    keeps that state replicated; ``dp-zero`` wraps the optimizer in
    ``ShardedOptimizer`` so every accumulator lives at 1/dp per replica —
    the predicted peak must drop by ~the sharded accumulator bytes
    (pinned in tests/test_mem_lint.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.sharding import ShardedOptimizer
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.utils import unique_name

    mesh = build_mesh({"dp": 8})
    with unique_name.guard():
        paddle.seed(0)
        l1 = paddle.nn.Linear(256, 1024)
        l2 = paddle.nn.Linear(1024, 256)
    rep = NamedSharding(mesh, P())
    for lyr in (l1, l2):
        for p in lyr.parameters():
            p._value = jax.device_put(p._value.astype(jnp.bfloat16), rep)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, multi_precision=True,
        parameters=list(l1.parameters()) + list(l2.parameters()))
    if zero:
        opt = ShardedOptimizer(opt, axis="dp", mesh=mesh)
    stateful_opt = opt._inner_opt if zero else opt

    def train_step(x, y):
        h = paddle.nn.functional.relu(l1(x))
        out = l2(h)
        loss = ((out - y).astype(jnp.float32) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "dp_zero_step" if zero else "dp_plain_step"
    step = CompiledStep(train_step, stateful=[l1, l2, stateful_opt],
                        donate_state=True)
    rng = np.random.RandomState(4)
    put = jax.device_put
    x = Tensor(put(jnp.asarray(rng.randn(64, 256), jnp.bfloat16),
                   NamedSharding(mesh, P("dp", None))))
    y = Tensor(put(jnp.asarray(rng.randn(64, 256), jnp.bfloat16),
                   NamedSharding(mesh, P("dp", None))))
    # measurable since ISSUE 18: the step is optimizer-temp dominated —
    # exactly where the fusion-blind model over-predicted XLA's fused
    # update kernel — and the fusion-aware timeline (analysis.fusion)
    # elides those elementwise temporaries, so the predicted peak now
    # crosschecks against memory_analysis within MEM_RTOL (the pinned
    # dp-fold peak drop in tests/test_mem_lint.py rides on top)
    return step, (x, y), mesh, True


def build_dp_plain(fixture=None):
    return _build_dp_adam(zero=False)


def build_dp_zero(fixture=None):
    return _build_dp_adam(zero=True)


def build_undonated_longctx(fixture=None):
    """The fixture: a long-context attention forward whose weights are NOT
    donated (``donate_state=False``) — the [b, h, q, k] score matrix plus
    undonated parameters blow past the injected HBM budget, so
    ``hbm-peak-over-capacity`` must fire (error → exit 1) and
    ``hbm-undonated-input`` must report the predicted peak delta."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.ops.dispatch import apply_op
    from paddle_tpu.utils import unique_name

    b, s, h, d = 2, 1024, 4, 64
    with unique_name.guard():
        paddle.seed(0)
        qkv = paddle.nn.Linear(h * d, 3 * h * d)
        out = paddle.nn.Linear(h * d, h * d)
    opt = paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=list(qkv.parameters()) + list(out.parameters()))

    def attn_fn(pv):
        pv = pv.reshape(b, s, 3, h, d)
        q = jnp.moveaxis(pv[:, :, 0], 2, 1)  # [b, h, s, d]
        k = jnp.moveaxis(pv[:, :, 1], 2, 1)
        v = jnp.moveaxis(pv[:, :, 2], 2, 1)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        attn = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores), v)
        return jnp.moveaxis(attn, 1, 2).reshape(b, s, h * d)

    def train_step(x, y):
        proj = qkv(x)  # [b, s, 3hd]
        merged = apply_op("longctx_attn", attn_fn, (proj,), {})
        loss = ((out(merged) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "undonated_longctx_step"
    step = CompiledStep(train_step, stateful=[qkv, out, opt],
                        donate_state=False)
    rng = np.random.RandomState(0)
    x = Tensor(np.asarray(rng.randn(b, s, h * d), np.float32))
    y = Tensor(np.asarray(rng.randn(b, s, h * d), np.float32))
    return step, (x, y), None, False  # static-only: the fixture never runs


def build_longctx(fixture=None):
    """Long-context GPT train step at seq 1024 — over the blockwise
    threshold, so causal training attention runs the KV-block scan (ISSUE
    15) instead of the O(seq²) einsum score matrix. Measurable on
    XLA:CPU: the predicted peak must agree with ``memory_analysis`` and
    never under-predict. ``--disable-blockwise`` forces the einsum path
    on the SAME shapes — the run_tests.sh gate lints both under one
    ``--capacity`` that only the blockwise timeline fits."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.utils import unique_name

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=2, max_position_embeddings=1024,
                    hidden_dropout=0.0, attention_dropout=0.0)
    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    def train_step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = "longctx_train_step"
    step = CompiledStep(train_step, stateful=[model, opt],
                        donate_state=True)
    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (1, 1024))
                 .astype(np.int64))
    return step, (ids, ids), None, True


def build_serve_chunk(fixture=None):
    """The chunked-prefill serving step over a 1024-row KV cache: chunk
    queries attend the slot's FULL cached row through the length-masked
    blockwise path — the serving-side long-context crosscheck target."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.utils import unique_name

    with unique_name.guard():
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=1024, hidden_dropout=0.0,
            attention_dropout=0.0))
    model.eval()
    eng = GenerationEngine(model, max_batch=2, max_len=1024,
                           prefill_buckets=(128,), prefill_chunk=128,
                           freeze_weights=False)
    return (eng.chunk_step, tuple(eng.example_chunk_args([256], off=256)),
            None, True)


def run_remat_fixture(capacity=None, out=sys.stdout):
    """``--fixture remat-plan``: the selective-remat planner must get the
    longctx step's PREDICTED peak under the budget (default: 70% of the
    baseline peak). Returns 0 on success, 1 when the plan misses — the
    run_tests.sh gate asserts 0."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis import remat_plan

    step, batch, _, _ = build_longctx()
    tl = analysis.analyze_memory(step, *batch)
    budget = float(capacity) if capacity else 0.7 * tl.peak_bytes
    plan = remat_plan.plan_remat(tl, budget_bytes=budget,
                                 min_bytes=1 << 16, min_span=0.2)
    print(f"\n== remat-plan fixture ({step.name}) ==", file=out)
    print(plan.table(), file=out)
    ok = plan.ok and plan.sites and plan.peak_after <= budget
    print(f"remat-plan fixture: predicted {tl.peak_bytes:.0f} -> "
          f"{plan.peak_after:.0f} bytes under budget {budget:.0f} -> "
          f"{'OK' if ok else 'FAIL'}", file=out)
    return 0 if ok else 1


def run_fusion_ab(out=sys.stdout):
    """``--fixture fusion-ab``: the fusion on/off A/B gate. The SAME
    optimizer-temp-dominated step (dp-plain) is walked twice; the
    fusion-aware timeline must (a) certify a non-trivial byte volume as
    elided, (b) predict a strictly lower-or-equal peak, and (c) never go
    below the step's irreducible floor (donated state bytes — fusion can
    elide temporaries, not parameters). Returns 0 on success."""
    from paddle_tpu import analysis

    step, batch, _, _ = build_dp_plain()
    tl_on = analysis.analyze_memory(step, *batch, fusion=True)
    tl_off = analysis.analyze_memory(step, *batch, fusion=False)
    floor = tl_on.donated_bytes
    delta = tl_off.peak_bytes - tl_on.peak_bytes
    ok = (tl_on.fused_bytes > 0
          and tl_on.peak_bytes <= tl_off.peak_bytes
          and tl_on.peak_bytes >= floor)
    print(f"\n== fusion A/B ({step.name}) ==", file=out)
    print(f"fusion off peak {tl_off.peak_bytes:.0f} B, on "
          f"{tl_on.peak_bytes:.0f} B (delta {delta:.0f} B, "
          f"{tl_on.fused_bytes:.0f} B of temporaries elided, state floor "
          f"{floor:.0f} B) -> {'OK' if ok else 'FAIL'}", file=out)
    return 0 if ok else 1


ZOO = {
    "dp-mp": build_dp_mp,
    "serve-decode": build_serve_decode,
    "dp-plain": build_dp_plain,
    "dp-zero": build_dp_zero,
    "longctx": build_longctx,
    "serve-chunk": build_serve_chunk,
}

FIXTURES = {
    "undonated-longctx": build_undonated_longctx,
    "remat-plan": run_remat_fixture,  # special-cased: a planner gate
    "fusion-ab": run_fusion_ab,       # special-cased: fusion on/off A/B
}


def lint_zoo(models, fixture=None, measure=False, capacity=None,
             out=sys.stdout, fusion=True):
    """Returns ``[(name, LintReport, MemoryTimeline, crosscheck_rows)]``
    (import-friendly: the tests drive this directly). ``fusion=False``
    runs the fusion-blind legacy timeline (looser upper bound — the A/B
    smoke leg compares both)."""
    from paddle_tpu import analysis

    config = {"fusion": bool(fusion)}
    if capacity is not None:
        config["hbm_capacity_bytes"] = float(capacity)
    builders = (
        [(fixture, FIXTURES[fixture])] if fixture
        else [(name, ZOO[name]) for name in models])
    results = []
    for name, build in builders:
        step, batch, mesh, measurable = build(fixture=fixture)
        report = analysis.lint_step(step, *batch, mesh=mesh, config=config)
        tl = report.memory  # the timeline lint_step attached
        print(f"\n== {name} ({step.name}) ==", file=out)
        print(report.table(), file=out)
        if tl is not None:
            print(tl.table(), file=out)
        else:
            print("memory timeline: unavailable (mem lint failed — see "
                  "warnings)", file=out)
        rows = None
        if measure and measurable:
            from paddle_tpu.profiler import devprof

            rep = devprof.device_report(step, *batch, register=False)
            rtol = (analysis.MEM_RTOL if fusion
                    else analysis.MEM_RTOL_UNFUSED)
            rows = analysis.crosscheck_mem(tl, rep, rtol=rtol)
            for r in rows:
                ratio = ("n/a" if r["ratio"] is None
                         else f"{r['ratio']:.3f}")
                print(f"crosscheck: metric={r['metric']} "
                      f"predicted={r['predicted_bytes']:.0f} "
                      f"measured={r['measured_bytes']:.0f} "
                      f"ratio={ratio} agrees={r['agrees']} "
                      f"under_predicted={r['under_predicted']}"
                      + (f" skipped={r['skipped']}" if r["skipped"]
                         else ""), file=out)
        elif measure:
            print(f"crosscheck: skipped ({name} is static-only)", file=out)
        results.append((name, report, tl, rows))
    return results


def run(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--models", nargs="+",
                    default=["dp-mp", "serve-decode", "dp-plain", "dp-zero",
                             "longctx", "serve-chunk"],
                    choices=sorted(ZOO))
    ap.add_argument("--jsonl", default=None,
                    help="write one JSON object per finding to this path")
    ap.add_argument("--format", default="table",
                    choices=["table", "sarif"],
                    help="sarif: emit a SARIF 2.1.0 document on stdout "
                         "(CI annotations) instead of tables")
    ap.add_argument("--fixture", default=None,
                    choices=sorted(FIXTURES),
                    help="lint the undonated long-context regression "
                         "fixture against a small HBM budget instead of "
                         "the zoo (the run must exit 1)")
    ap.add_argument("--measure", action="store_true",
                    help="also compile measurable configs via devprof and "
                         "print the predicted-vs-measured peak crosscheck")
    ap.add_argument("--capacity", type=float, default=None,
                    help="HBM budget in bytes for hbm-peak-over-capacity "
                         "(default: auto-detected device budget; the "
                         f"fixture defaults to {FIXTURE_CAPACITY})")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "never"],
                    help="exit 1 when findings at/above this severity "
                         "exist")
    ap.add_argument("--disable-blockwise", action="store_true",
                    help="force the einsum attention path (sets the "
                         "disable_blockwise_attention flag) — the "
                         "run_tests.sh long-context gate lints the SAME "
                         "config both ways under one --capacity")
    ap.add_argument("--no-fusion", action="store_true",
                    help="run the fusion-blind legacy timeline (looser "
                         "upper bound, crosschecked at MEM_RTOL_UNFUSED "
                         "instead of MEM_RTOL) — the --smoke A/B leg "
                         "compares both")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: clean zoo with --measure must pass, the "
                         "undonated fixture must exit 1, the longctx config "
                         "must fit a capacity the einsum path blows, and "
                         "the remat planner must hit its budget")
    args = ap.parse_args(argv)

    if args.smoke:
        clean = run(["--measure"])
        fixture = run(["--fixture", "undonated-longctx"])
        # the ISSUE 15 long-context gate: one synthetic HBM budget that
        # the blockwise timeline fits and the einsum score matrix blows
        bw = run(["--models", "longctx", "--capacity",
                  str(LONGCTX_CAPACITY)])
        es = run(["--models", "longctx", "--capacity",
                  str(LONGCTX_CAPACITY), "--disable-blockwise"])
        remat = run(["--fixture", "remat-plan"])
        ab = run(["--fixture", "fusion-ab"])
        ok = (clean == 0 and fixture == 1 and bw == 0 and es == 1
              and remat == 0 and ab == 0)
        print(f"\nmem lint smoke: clean-zoo rc={clean} (want 0), "
              f"fixture rc={fixture} (want 1), longctx-blockwise rc={bw} "
              f"(want 0), longctx-einsum rc={es} (want 1), remat-plan "
              f"rc={remat} (want 0), fusion-ab rc={ab} (want 0) -> "
              f"{'OK' if ok else 'FAIL'}")
        return 0 if ok else 1

    if args.disable_blockwise:
        from paddle_tpu.framework.flags import set_flags

        set_flags({"disable_blockwise_attention": True})

    capacity = args.capacity
    if args.fixture == "remat-plan":
        return run_remat_fixture(capacity)
    if args.fixture == "fusion-ab":
        return run_fusion_ab()
    if args.fixture and capacity is None:
        capacity = FIXTURE_CAPACITY

    sink = open(os.devnull, "w") if args.format == "sarif" else sys.stdout
    results = lint_zoo(args.models, fixture=args.fixture,
                       measure=args.measure, capacity=capacity, out=sink,
                       fusion=not args.no_fusion)

    if args.format == "sarif":
        from paddle_tpu.analysis import sarif_report

        findings = [f for _, report, _, _ in results for f in report]
        json.dump(sarif_report(findings, tool="paddle-tpu-mem-lint"),
                  sys.stdout, indent=1)
        sys.stdout.write("\n")

    if args.jsonl:
        with open(args.jsonl, "w") as fh:
            for name, report, _, _ in results:
                for f in report:
                    fh.write(json.dumps({"model": name, **f.as_dict()},
                                        sort_keys=True) + "\n")
        print(f"wrote {sum(len(r) for _, r, _, _ in results)} findings to "
              f"{args.jsonl}", file=sink)

    n_err = sum(len(r.errors) for _, r, _, _ in results)
    n_warn = sum(len(r.warnings) for _, r, _, _ in results)
    # fusion-aware timelines must agree both ways; the legacy --no-fusion
    # path over-predicts by design (fusion-blindness is its documented
    # bias), so only under-prediction gates there
    fusion_on = not getattr(args, "no_fusion", False)
    bad_cross = sum(
        1 for _, _, _, rows in results for r in (rows or ())
        if r["under_predicted"] or (fusion_on and r["agrees"] is False))
    print(f"\nmem lint: {n_err} error(s), {n_warn} warning(s), "
          f"{bad_cross} crosscheck disagreement(s) across "
          f"{len(results)} config(s)", file=sink)
    if args.fail_on == "never":
        return 0
    gate = n_err + bad_cross + (n_warn if args.fail_on == "warning" else 0)
    return 1 if gate else 0


def main(argv=None):
    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
