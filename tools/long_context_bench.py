"""Long-context training demonstration on one chip.

Trains the flagship GPT at growing sequence lengths. Attention memory
stays O(s·d): the Pallas flash kernel (1024x1024 tiles) on TPU, the
blockwise online-softmax KV scan (ISSUE 15) everywhere else — never the
O(s²) einsum score matrix. The multi-chip extension is ring attention
over the `sep` axis (distributed/meta_parallel/sequence_parallel.py),
dryrun-validated on the virtual mesh; this tool shows the single-chip
long-seq numbers the ring composes from.

Every row also carries the PREDICTED HBM peak of the train step
(``analysis.analyze_memory`` — abstract trace, the upper-bound model the
mem-lint crosscheck gates) next to the einsum path's predicted peak on
the same shapes: the static series is honest on CPU, where the 16k/32k
rows never execute. ``--predict-only`` (the default off-TPU) skips
execution entirely; ``--remat BYTES|auto`` runs the selective-remat
autopilot first; ``--capacity BYTES`` turns the run into a gate — every
blockwise row must fit the budget (exit 1 otherwise), and rows where the
einsum peak blows it are marked.

Run: python tools/long_context_bench.py [--seqs 2048,...,32768]
Writes LONGCTX_r15.json at the repo root (TPU measured run, or a
--predict-only static run).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2048,4096,8192,16384,32768")
    # per-seq batch optima measured on v5e (r5): s2048 b16 > b12/b8;
    # s4096 b6 > b4/b8; s8192 b4 > b2/b3/b6. 16k/32k run at FIXED batch 2:
    # the r15 acceptance is context growth at constant batch, not a
    # tokens-per-batch trade
    ap.add_argument("--tokens-per-batch", type=int, default=0)
    ap.add_argument("--no-artifact", action="store_true")
    ap.add_argument("--predict-only", action="store_true", default=None,
                    help="static analysis only, no device execution "
                         "(default on non-TPU backends)")
    ap.add_argument("--remat", default=None,
                    help='selective-remat autopilot budget: "auto" '
                         "(device HBM capacity) or bytes")
    ap.add_argument("--capacity", type=float, default=None,
                    help="HBM budget in bytes: every blockwise row must "
                         "fit (exit 1 otherwise); einsum rows that blow "
                         "it are marked")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu import analysis
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = jax.default_backend() != "cpu"
    predict_only = (not on_tpu if args.predict_only is None
                    else args.predict_only)
    remat = args.remat
    if remat not in (None, "auto"):
        remat = float(remat)
    results = []
    over_capacity = False
    MEASURED_BATCH = {2048: 16, 4096: 6, 8192: 4, 16384: 2, 32768: 2}
    for seq in [int(s) for s in args.seqs.split(",")]:
        if args.tokens_per_batch:
            batch = max(1, args.tokens_per_batch // seq)
        else:
            batch = MEASURED_BATCH.get(seq, max(1, 32768 // seq))
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=seq,
                        hidden_dropout=0.0, attention_dropout=0.0)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        if on_tpu:
            model.to(dtype="bfloat16")
            for name, sub in model.named_sublayers():
                if type(sub).__name__ == "LayerNorm":
                    sub.to(dtype="float32")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=on_tpu)

        def train_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        def make_step():
            return CompiledStep(train_step, stateful=[model, opt],
                                donate_state=True)

        rng = np.random.RandomState(0)  # fixed: numbers must reproduce
        example = Tensor(rng.randint(0, cfg.vocab_size,
                                     (batch, seq)).astype(np.int64))

        # the static series: predicted peak for the blockwise step and
        # for the einsum path on the SAME shapes (abstract trace only)
        set_flags({"disable_blockwise_attention": True})
        peak_einsum = analysis.analyze_memory(
            make_step(), example, example).peak_bytes
        set_flags({"disable_blockwise_attention": False})
        remat_report = None
        if remat is not None:
            remat_report = analysis.auto_remat(
                model, remat, make_step, (example, example),
                name=f"longctx_{seq}")
            peak_pred = remat_report.peak_after
        else:
            peak_pred = analysis.analyze_memory(
                make_step(), example, example).peak_bytes

        fits = None
        if args.capacity is not None:
            fits = peak_pred <= args.capacity
            over_capacity |= not fits

        row = {"seq": seq, "batch": batch,
               "hbm_peak_bytes": float(peak_pred),
               "hbm_peak_bytes_einsum": float(peak_einsum),
               "predicted_only": predict_only}
        if remat_report is not None:
            row["remat_blocks"] = remat_report.blocks_wrapped
        if fits is not None:
            row["fits_capacity"] = bool(fits)
            row["einsum_fits_capacity"] = bool(
                peak_einsum <= args.capacity)
        cap_note = ""
        if fits is not None:
            cap_note = (" fits-capacity" if fits else " OVER-CAPACITY") \
                + ("" if peak_einsum <= args.capacity
                   else " (einsum blows it)")

        if predict_only:
            print(f"seq={seq:6d} batch={batch:3d}: predicted peak "
                  f"{peak_pred / 2**30:7.2f} GiB (einsum "
                  f"{peak_einsum / 2**30:7.2f} GiB, "
                  f"{peak_einsum / peak_pred:.2f}x){cap_note}", flush=True)
            results.append(row)
            jax.clear_caches()
            continue

        step = make_step()
        n = 6
        batches = [Tensor(rng.randint(0, cfg.vocab_size,
                                      (batch, seq)).astype(np.int64))
                   for _ in range(2 + n)]
        for i in range(2):
            np.asarray(step(batches[i], batches[i])._value)
        t0 = time.perf_counter()
        outs = [step(b, b) for b in batches[2:]]
        last = float(np.asarray(outs[-1]._value))
        dt = (time.perf_counter() - t0) / n
        toks = batch * seq / dt
        # attention share grows with s: flops/token = 6*N_mat + 12*L*H*s;
        # MFU only against a KNOWN chip peak (tools/bench_common.py policy)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_common import device_peak

        n_mat = cfg.num_layers * 12 * cfg.hidden_size ** 2 \
            + cfg.vocab_size * cfg.hidden_size
        fpt = 6 * n_mat + 12 * cfg.num_layers * cfg.hidden_size * seq
        _, peak = device_peak()
        mfu = toks * fpt / peak if (on_tpu and peak) else float("nan")
        assert np.isfinite(last)
        print(f"seq={seq:6d} batch={batch:3d}: {dt * 1e3:8.1f} ms/step "
              f"{toks:9.0f} tok/s  mfu={mfu:.3f}  loss={last:.3f}"
              f"{cap_note}", flush=True)
        row.update({"ms_per_step": round(dt * 1e3, 1),
                    "tokens_per_sec": round(toks, 1),
                    "mfu": round(mfu, 4) if np.isfinite(mfu) else None})
        results.append(row)
        jax.clear_caches()
    if (on_tpu or predict_only) and not args.no_artifact:
        with open("LONGCTX_r15.json", "w") as f:
            json.dump({"results": results,
                       "predict_only": predict_only,
                       "remat": args.remat,
                       "capacity": args.capacity}, f, indent=1)
            f.write("\n")
    if over_capacity:
        print("FAIL: a blockwise row exceeded --capacity", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
