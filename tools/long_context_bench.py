"""Long-context training demonstration on one chip.

Trains the flagship GPT at growing sequence lengths with the Pallas flash
kernel (1024x1024 tiles): attention memory stays O(s·d) so sequence length
scales until the weights/activations bound, not the s² score matrix. The
multi-chip extension is ring attention over the `sep` axis
(distributed/meta_parallel/sequence_parallel.py), dryrun-validated on the
virtual mesh; this tool shows the single-chip long-seq numbers the ring
composes from.

Run: python tools/long_context_bench.py [--seqs 2048,4096,8192]
Writes LONGCTX_r05.json at the repo root when run on TPU hardware.
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2048,4096,8192")
    # per-seq batch optima measured on v5e (r5): s2048 b16 > b12/b8;
    # s4096 b6 > b4/b8; s8192 b4 > b2/b3/b6
    ap.add_argument("--tokens-per-batch", type=int, default=0)
    ap.add_argument("--no-artifact", action="store_true")
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = jax.default_backend() != "cpu"
    results = []
    MEASURED_BATCH = {2048: 16, 4096: 6, 8192: 4}
    for seq in [int(s) for s in args.seqs.split(",")]:
        if args.tokens_per_batch:
            batch = max(1, args.tokens_per_batch // seq)
        else:
            batch = MEASURED_BATCH.get(seq, max(1, 32768 // seq))
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=seq,
                        hidden_dropout=0.0, attention_dropout=0.0)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        if on_tpu:
            model.to(dtype="bfloat16")
            for name, sub in model.named_sublayers():
                if type(sub).__name__ == "LayerNorm":
                    sub.to(dtype="float32")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=on_tpu)

        def train_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step = CompiledStep(train_step, stateful=[model, opt],
                            donate_state=True)
        rng = np.random.RandomState(0)  # fixed: numbers must reproduce
        n = 6
        batches = [Tensor(rng.randint(0, cfg.vocab_size,
                                      (batch, seq)).astype(np.int64))
                   for _ in range(2 + n)]
        for i in range(2):
            np.asarray(step(batches[i], batches[i])._value)
        t0 = time.perf_counter()
        outs = [step(b, b) for b in batches[2:]]
        last = float(np.asarray(outs[-1]._value))
        dt = (time.perf_counter() - t0) / n
        toks = batch * seq / dt
        # attention share grows with s: flops/token = 6*N_mat + 12*L*H*s;
        # MFU only against a KNOWN chip peak (tools/bench_common.py policy)
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_common import device_peak

        n_mat = cfg.num_layers * 12 * cfg.hidden_size ** 2 \
            + cfg.vocab_size * cfg.hidden_size
        fpt = 6 * n_mat + 12 * cfg.num_layers * cfg.hidden_size * seq
        _, peak = device_peak()
        mfu = toks * fpt / peak if (on_tpu and peak) else float("nan")
        assert np.isfinite(last)
        print(f"seq={seq:6d} batch={batch:3d}: {dt * 1e3:8.1f} ms/step "
              f"{toks:9.0f} tok/s  mfu={mfu:.3f}  loss={last:.3f}",
              flush=True)
        results.append({"seq": seq, "batch": batch,
                        "ms_per_step": round(dt * 1e3, 1),
                        "tokens_per_sec": round(toks, 1),
                        "mfu": round(mfu, 4) if np.isfinite(mfu) else None})
        jax.clear_caches()
    if on_tpu and not args.no_artifact:
        with open("LONGCTX_r05.json", "w") as f:
            json.dump({"results": results}, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
