"""Decompose the GPT train-step time on the real chip.

Every probe uses DISTINCT inputs per call (the remote execution layer caches
results keyed on (executable, inputs) — see bench.py) and measures k calls
issued back-to-back with one fetch sweep at the end, so the ~87 ms relay
round-trip latency is amortized instead of measured k times.

Run:  PYTHONPATH=/root/.axon_site:/root/repo python tools/perf_probe.py
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def timeit_batch(step, batches, k=6):
    """Issue k calls back-to-back on distinct inputs; fence via the LAST
    output only (each fetch is a full ~87 ms relay round trip, and the donated
    state chain means the last output already depends on every prior step)."""
    outs = [step(*b) for b in batches[:2]]          # warmup/compile
    np.asarray(outs[-1]._value)
    t0 = time.perf_counter()
    outs = [step(*b) for b in batches[2:2 + k]]
    np.asarray(outs[-1]._value)
    dt = (time.perf_counter() - t0) / k
    assert all(np.isfinite(np.asarray(o._value)).all() for o in outs)
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = jax.default_backend() != "cpu"
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=args.layers,
                    num_heads=12, max_position_embeddings=1024,
                    hidden_dropout=0.0, attention_dropout=0.0)
    batch, seq = args.batch, args.seq
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        for name, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=on_tpu)
    rng = np.random.RandomState(time.time_ns() % (2**31))
    tok = batch * seq
    k = 6
    data = [Tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
            for _ in range(2 + k)]

    def fwd_only(ids):
        return model.gpt(ids).astype("float32").sum()

    def fwd_loss_fused(ids, labels):
        return model.loss(ids, labels)

    def fwd_loss_unfused(ids, labels):
        logits = model(ids)
        return F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]).astype("float32"),
            labels.reshape([-1, 1])).mean()

    def grad_fused(ids, labels):
        # return a grad-dependent scalar so XLA cannot DCE the backward
        loss = model.loss(ids, labels)
        loss.backward()
        gsum = None
        for p in model.parameters():
            if p.grad is not None:
                s = p.grad.astype("float32").sum()
                gsum = s if gsum is None else gsum + s
        opt.clear_grad()
        return loss + gsum

    def opt_only(ids, labels):
        # grads of a cheap surrogate so step() cost dominates
        loss = (model.gpt.embeddings.word_embeddings.weight.astype("float32").sum())
        for p in model.parameters():
            p._grad = Tensor(p._value * 0 + 1e-6)
        opt.step()
        opt.clear_grad()
        return loss

    def full_step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    probes = [
        ("fwd body only (no head)", CompiledStep(fwd_only, stateful=[model]),
         [(d,) for d in data]),
        ("fwd + fused head+CE", CompiledStep(fwd_loss_fused, stateful=[model]),
         [(d, d) for d in data]),
        ("fwd + unfused head+CE", CompiledStep(fwd_loss_unfused, stateful=[model]),
         [(d, d) for d in data]),
        ("fwd+bwd fused", CompiledStep(grad_fused, stateful=[model, opt]),
         [(d, d) for d in data]),
        ("optimizer only", CompiledStep(opt_only, stateful=[model, opt]),
         [(d, d) for d in data]),
        ("full step (fused)", CompiledStep(full_step, stateful=[model, opt]),
         [(d, d) for d in data]),
    ]
    for name, step, b in probes:
        t = timeit_batch(step, b, k=k)
        print(f"{name:28s} {t * 1e3:8.2f} ms   {tok / t:10.0f} tok/s", flush=True)


if __name__ == "__main__":
    main()
