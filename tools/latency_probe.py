"""Separate relay/dispatch latency from on-device step time.

Three measurements on the real chip:
  1. trivial jitted add with fresh inputs -> pure round-trip latency
  2. GPT full step, per-step loss fetch (bench.py's current fencing)
  3. GPT full step, N chained steps then ONE fetch — the state returned by
     step i feeds step i+1, so every call has distinct inputs (no replay
     caching possible) and the aggregate time is honest.
"""
from __future__ import annotations

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    # 1. round-trip latency
    @jax.jit
    def triv(x):
        return x + 1.0

    xs = [np.full((8,), i, np.float32) for i in range(8)]
    np.asarray(triv(xs[0]))
    ts = []
    for x in xs:
        t0 = time.perf_counter()
        np.asarray(triv(x))
        ts.append(time.perf_counter() - t0)
    print(f"trivial round-trip: median {np.median(ts) * 1e3:.2f} ms "
          f"min {min(ts) * 1e3:.2f} ms", flush=True)

    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position_embeddings=1024,
                    hidden_dropout=0.0, attention_dropout=0.0)
    batch, seq = 16, 1024
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    for name, sub in model.named_sublayers():
        if type(sub).__name__ == "LayerNorm":
            sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)

    def train_step(ids, labels):
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True)
    rng = np.random.RandomState(time.time_ns() % (2**31))
    n = 14
    batches = [Tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
               for _ in range(n)]
    for i in range(3):
        np.asarray(step(batches[i], batches[i])._value)

    # 2. per-step fetch
    ts = []
    for i in range(3, 8):
        t0 = time.perf_counter()
        np.asarray(step(batches[i], batches[i])._value)
        ts.append(time.perf_counter() - t0)
    per_step = float(np.median(ts))
    print(f"per-step fetch:     {per_step * 1e3:.1f} ms  "
          f"{batch * seq / per_step:.0f} tok/s", flush=True)

    # 3. chained, one fetch
    t0 = time.perf_counter()
    losses = [step(batches[i], batches[i]) for i in range(8, 14)]
    vals = [float(np.asarray(l._value)) for l in losses]
    total = time.perf_counter() - t0
    per = total / 6
    print(f"chained x6, 1 fetch: {per * 1e3:.1f} ms/step  "
          f"{batch * seq / per:.0f} tok/s  losses finite={np.isfinite(vals).all()}",
          flush=True)


if __name__ == "__main__":
    main()
