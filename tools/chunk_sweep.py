"""Sweep the fused linear+CE chunk size on the real chip (the two lax.scan
loops were 21% of device step time in the profile — bigger chunks mean
fewer scan trips and bigger MXU matmuls, at the cost of a larger transient
logits block). Run: PYTHONPATH=/root/.axon_site:/root/repo python tools/chunk_sweep.py
"""
from __future__ import annotations

import time

import numpy as np


def main():
    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.ops import fused

    batch, seq = 16, 1024
    tok = batch * seq
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position_embeddings=1024,
                    hidden_dropout=0.0, attention_dropout=0.0)
    rng = np.random.RandomState(0)
    k = 6
    data = [Tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
            for _ in range(2 + k)]

    for chunk in (1024, 2048, 4096, 8192, 16384):
        fused._FORCE_CHUNK = chunk
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
        for name, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)

        def full_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step = CompiledStep(full_step, stateful=[model, opt], donate_state=True)
        outs = [step(d, d) for d in data[:2]]
        np.asarray(outs[-1]._value)
        t0 = time.perf_counter()
        outs = [step(d, d) for d in data[2:]]
        np.asarray(outs[-1]._value)
        t = (time.perf_counter() - t0) / k
        print(f"chunk={chunk:<6} {t*1e3:8.2f} ms  {tok/t:9.0f} tok/s", flush=True)
    fused._FORCE_CHUNK = None


if __name__ == "__main__":
    main()
