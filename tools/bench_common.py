"""Shared harness for the hardware model benchmarks (bench.py,
tools/bench_resnet.py, tools/bench_bert.py).

Measurement discipline (identical to bench.py, see its comments for the
rationale): 3 warmup steps, then issue all measured steps back-to-back with
donated state so each step's inputs depend on the previous step's outputs
(the remote relay's (executable, inputs) result cache can never replay),
fence on the LAST loss only, fetch the rest after the timer for the
finiteness check.

Measurement protocol (async-pipeline revision): batches flow through
``paddle_tpu.io.DeviceLoader`` — a background thread double-buffers the
host→device transfer of the next ``prefetch`` batches — and per-step losses
accumulate on device in a ``metric.AsyncMetricBuffer``; the ONLY in-timer
fence is the final loss. The measured number therefore reflects the
production input pipeline (prefetch + deferred readback), not a host-bound
loop. Pass ``prefetch=0`` to ``measure_steps`` for the legacy synchronous
feed. Steps compiled with ``donate_inputs=True`` consume the staged
batches — don't reuse a batch list across two measured runs in-process.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# chip bf16 peak FLOP/s by device_kind substring; MFU is only reported when
# the chip is known — never against a guessed peak
PEAKS = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12, "v5p": 459e12,
         "v6 lite": 918e12, "v6e": 918e12}


def device_peak():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    return kind, next((p for k, p in PEAKS.items() if k in kind), None)


def retry(run, attempts=3):
    """The remote-compile tunnel to the TPU terminal can drop mid-run;
    transient infra failures get `attempts` tries before reporting failure."""
    last = None
    for attempt in range(attempts):
        if attempt:
            time.sleep(5.0 * attempt)
        try:
            return run()
        except Exception as e:  # noqa: BLE001 - retry any runtime failure
            last = e
            print(f"bench attempt {attempt + 1} failed: {e!r}", file=sys.stderr)
            try:
                import jax

                jax.clear_caches()
            except Exception:
                pass
    raise last


def measure_steps(step, batches, iters, warmup=3, prefetch=2,
                  collect_telemetry=True):
    """Run the warmup+steady-state protocol; returns (seconds, losses).

    ``batches`` may be host batches (numpy tuples) or device Tensors; with
    ``prefetch > 0`` they are staged host→device through ``DeviceLoader``
    so transfers overlap compute, and losses are read back only after the
    timer stops (single fence on the last loss inside the timed region).

    With ``collect_telemetry`` (default) the run enables the runtime
    telemetry registry (reset first, spanning warmup so compile counts are
    captured) and marks a phase record per measured step; summarize it into
    the BENCH json with :func:`telemetry_block`. The per-step cost is a few
    guarded ns-clock reads — noise against any real step.
    """
    from paddle_tpu.io import DeviceLoader
    from paddle_tpu.metric import AsyncMetricBuffer

    telemetry = None
    if collect_telemetry:
        from paddle_tpu.profiler import telemetry

        telemetry.reset()
        telemetry.enable()
    try:
        feed = iter(DeviceLoader(batches, buffer_size=prefetch)
                    if prefetch else batches)
        buf = AsyncMetricBuffer()
        for _ in range(warmup):
            loss = step(*next(feed))
            np.asarray(loss._value)
        t0 = time.perf_counter()
        losses = []
        for _ in range(iters):
            if telemetry is not None:
                telemetry.step_begin()
            losses.append(step(*next(feed)))
        float(np.asarray(losses[-1]._value))  # fence on the dependence chain
        total = time.perf_counter() - t0
        if telemetry is not None:
            telemetry.step_end()
        for l in losses:
            buf.append(l)
        vals = buf.result()  # post-timer readback for the finiteness check
        assert all(np.isfinite(v) for v in vals), \
            f"bench losses not finite: {vals}"
        return total, vals
    finally:
        if telemetry is not None:
            telemetry.disable()  # data stays readable for telemetry_block


def telemetry_block(total_seconds, steps):
    """Phase-attribution block for the emitted BENCH json, from the
    telemetry collected by ``measure_steps``: steps/s, mean data-wait
    fraction of the timed region, compile/recompile counts, per-phase
    seconds (measured steps only — warmup phases are outside the step
    records), DeviceLoader prefetch stats, and the devprof device ground
    truth — ``hbm_peak_bytes`` (compiled HBM peak), ``comm_fraction``
    (interconnect bytes / total memory traffic) and per-mesh-axis
    collective byte counters — harvested at the step's first compile."""
    from paddle_tpu.profiler import devprof, telemetry

    s = telemetry.summary()
    recs = telemetry.get_telemetry().steps()
    phase_s = {}
    for r in recs:
        for k, v in r.phases.items():
            phase_s[k] = phase_s.get(k, 0.0) + v
    counters = s["counters"]
    gauges = s["gauges"]
    # device stats: prefer the live gauges; fall back to the harvest
    # registry when another enable/reset cycle cleared them
    hbm_peak = gauges.get("hbm.peak_bytes")
    comm_fraction = gauges.get("comm.fraction")
    comm_by_axis = {k[len("comm.bytes."):]: int(v)
                    for k, v in counters.items()
                    if k.startswith("comm.bytes.")}
    rep = devprof.last_report()
    if rep is not None:
        if hbm_peak is None and rep.memory is not None:
            hbm_peak = rep.memory.peak_bytes
        if comm_fraction is None:
            comm_fraction = rep.comm_fraction
        if not comm_by_axis:
            comm_by_axis = {a: int(st["bytes"])
                            for a, st in rep.collectives.as_dict().items()}
    return {
        "steps_per_sec": round(steps / total_seconds, 3) if total_seconds
        else None,
        "data_wait_frac": round(phase_s.get("data_wait", 0.0) / total_seconds,
                                4) if total_seconds else None,
        "compile_count": int(counters.get("compile.count", 0)),
        "recompile_count": int(s["recompile_count"]),
        "phase_s": {k: round(v, 6) for k, v in sorted(phase_s.items())},
        "prefetch": {
            "hits": int(counters.get("device_loader.prefetch_hit", 0)),
            "misses": int(counters.get("device_loader.prefetch_miss", 0)),
            "stall_s": round(float(
                counters.get("device_loader.stall_s", 0.0)), 6),
            "bytes_staged": int(
                counters.get("device_loader.bytes_staged", 0)),
        },
        "hbm_peak_bytes": int(hbm_peak) if hbm_peak is not None else None,
        "comm_fraction": (round(float(comm_fraction), 4)
                          if comm_fraction is not None else None),
        "comm_bytes_by_axis": comm_by_axis,
    }


def compiled_flops(step, batches):
    """FLOPs of ONE compiled train step from XLA's own cost analysis
    (includes remat recompute — i.e. this yields hardware-FLOPs utilization,
    the honest number for 'how busy is the MXU'). Prefers the devprof
    report harvested at the step's first compile (no second lowering);
    falls back to lowering against the example batch."""
    from paddle_tpu.profiler import devprof
    from paddle_tpu.profiler.devprof import normalize_cost_analysis

    rep = devprof.get_report(getattr(step, "name", ""))
    if rep is not None and rep.flops:
        return rep.flops
    try:
        lowered = step.lower(*batches[0])
        cost = normalize_cost_analysis(lowered.compile().cost_analysis())
        return cost.get("flops", 0.0) or None
    except Exception as e:  # pragma: no cover - cost analysis is best-effort
        print(f"cost_analysis unavailable: {e!r}", file=sys.stderr)
        return None


def emit(result, artifact=None):
    """Print the one-line JSON and optionally persist a repo-root artifact."""
    print(json.dumps(result))
    if artifact:
        with open(artifact, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
