#!/usr/bin/env python
"""Checkpoint doctor: verify / inspect / prune ``fault.CheckpointManager``
checkpoint directories from the shell.

Usage::

    python tools/ckpt_doctor.py verify  <ckpt_dir> [--step N]
    python tools/ckpt_doctor.py inspect <ckpt_dir> [--step N]
    python tools/ckpt_doctor.py prune   <ckpt_dir> --keep N [--dry-run]

``verify`` re-checks every payload against the manifest CRC32s (exit 1 on
any corruption — CI-friendly); ``inspect`` adds per-payload tensor
shapes/dtypes; ``prune`` deletes the oldest step dirs beyond ``--keep``.

``verify`` and ``prune`` are stdlib-only (json + zlib over the manifest
layout) so they work on machines without the framework installed;
``inspect`` unpickles payloads and needs numpy.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import zlib

STEP_PREFIX = "step_"
MANIFEST = "manifest.json"
LATEST = "latest"


def _steps(root):
    out = []
    for name in os.listdir(root):
        if name.startswith(STEP_PREFIX):
            try:
                out.append(int(name[len(STEP_PREFIX):]))
            except ValueError:
                pass
    return sorted(out)


def _latest(root):
    try:
        with open(os.path.join(root, LATEST)) as f:
            name = f.read().strip()
        return int(name[len(STEP_PREFIX):])
    except (OSError, ValueError):
        return None


def _crc32_file(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
    return crc & 0xFFFFFFFF


def _verify_step(root, step):
    """Returns (manifest | None, [problem strings])."""
    d = os.path.join(root, f"{STEP_PREFIX}{step:08d}")
    mpath = os.path.join(d, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return None, [f"manifest unreadable: {e}"]
    problems = []
    for name, ent in manifest.get("payloads", {}).items():
        fpath = os.path.join(d, ent["file"])
        if not os.path.exists(fpath):
            problems.append(f"{name}: missing {ent['file']}")
            continue
        size = os.path.getsize(fpath)
        if size != ent["size"]:
            problems.append(f"{name}: size {size} != manifest {ent['size']}")
        elif _crc32_file(fpath) != ent["crc32"]:
            problems.append(f"{name}: crc32 mismatch")
    return manifest, problems


def cmd_verify(args):
    steps = [args.step] if args.step is not None else _steps(args.ckpt_dir)
    if not steps:
        print(f"no {STEP_PREFIX}* checkpoints under {args.ckpt_dir}")
        return 1
    latest = _latest(args.ckpt_dir)
    bad = 0
    for s in steps:
        manifest, problems = _verify_step(args.ckpt_dir, s)
        mark = " <- latest" if s == latest else ""
        if problems:
            bad += 1
            print(f"step {s:>10}  CORRUPT{mark}")
            for p in problems:
                print(f"    {p}")
        else:
            n = len(manifest.get("payloads", {}))
            print(f"step {s:>10}  ok ({n} payloads){mark}")
    if latest is not None and latest not in steps and args.step is None:
        bad += 1
        print(f"latest pointer names missing step {latest}")
    return 1 if bad else 0


def _describe(obj, prefix="", out=None, limit=200):
    out = out if out is not None else []
    if len(out) >= limit:
        return out
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        out.append(f"    {prefix}: {obj.dtype} {tuple(obj.shape)}")
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _describe(v, f"{prefix}.{k}" if prefix else str(k), out, limit)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _describe(v, f"{prefix}[{i}]", out, limit)
    else:
        out.append(f"    {prefix}: {type(obj).__name__} = {obj!r:.60}")
    return out


def cmd_inspect(args):
    steps = _steps(args.ckpt_dir)
    if not steps:
        print(f"no {STEP_PREFIX}* checkpoints under {args.ckpt_dir}")
        return 1
    step = args.step if args.step is not None else (_latest(args.ckpt_dir)
                                                    or steps[-1])
    manifest, problems = _verify_step(args.ckpt_dir, step)
    print(f"checkpoint {args.ckpt_dir} step {step} "
          f"({'CORRUPT: ' + '; '.join(problems) if problems else 'verified'})")
    if manifest is None:
        return 1
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from paddle_tpu.framework.io import load as pload

    d = os.path.join(args.ckpt_dir, f"{STEP_PREFIX}{step:08d}")
    for name, ent in manifest.get("payloads", {}).items():
        print(f"  {name} ({ent['file']}, {ent['size']} bytes)")
        try:
            payload = pload(os.path.join(d, ent["file"]), return_numpy=True)
        except Exception as e:
            print(f"    <unreadable: {e}>")
            continue
        for line in _describe(payload):
            print(line)
    return 1 if problems else 0


def cmd_prune(args):
    steps = _steps(args.ckpt_dir)
    latest = _latest(args.ckpt_dir)
    victims = [s for s in steps[:-args.keep]] if args.keep else []
    victims = [s for s in victims if s != latest]
    for s in victims:
        d = os.path.join(args.ckpt_dir, f"{STEP_PREFIX}{s:08d}")
        if args.dry_run:
            print(f"would prune {d}")
        else:
            shutil.rmtree(d, ignore_errors=True)
            print(f"pruned {d}")
    kept = [s for s in steps if s not in victims]
    print(f"kept {len(kept)}/{len(steps)} checkpoints: {kept}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("verify", cmd_verify), ("inspect", cmd_inspect),
                     ("prune", cmd_prune)):
        p = sub.add_parser(name)
        p.add_argument("ckpt_dir")
        p.set_defaults(fn=fn)
        if name in ("verify", "inspect"):
            p.add_argument("--step", type=int, default=None)
        if name == "prune":
            p.add_argument("--keep", type=int, required=True)
            p.add_argument("--dry-run", action="store_true")
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
