#!/usr/bin/env python
"""Scrape and parse an OpenMetrics ``/metrics`` endpoint (round-trip check).

The parsing half of ``paddle_tpu/profiler/export.py``: fetch the exposition
text (HTTP URL or a local file), parse it into metric families, and render
a table. ``--assert-family`` makes it a CI gate — exit 1 unless every named
family was scraped (tools/run_tests.sh asserts the ``serve_*``/``step_*``
families survive the render→HTTP→parse round trip).

Usage::

    python tools/metrics_scrape.py http://127.0.0.1:9464/metrics
    python tools/metrics_scrape.py dump.txt --assert-family serve_ttft_s

Stdlib-only on purpose: a fleet monitor sidecar (or CI) must be able to
scrape without importing jax — mirrors tools/telemetry_report.py.
"""
from __future__ import annotations

import argparse
import re
import sys

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(\s+(?P<ts>[^\s]+))?\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: sample-name suffixes that belong to a parent summary/counter family
_SUFFIXES = ("_total", "_count", "_sum", "_bucket", "_created")


def _family_of(sample_name, types):
    """Map a sample name back to its family (``x_total`` → ``x`` when
    ``x`` was TYPEd)."""
    if sample_name in types:
        return sample_name
    for suf in _SUFFIXES:
        if sample_name.endswith(suf) and sample_name[: -len(suf)] in types:
            return sample_name[: -len(suf)]
    return sample_name


def parse_openmetrics(text):
    """Parse exposition text → ``{family: {"type", "help", "samples"}}``
    where samples is a list of ``(sample_name, labels_dict, value)``.
    Raises ``ValueError`` on an unparseable sample line or a missing
    ``# EOF`` terminator (a truncated scrape must not pass silently)."""
    families = {}
    types = {}
    saw_eof = False
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line == "# EOF":
            saw_eof = True
            continue
        if saw_eof:
            raise ValueError(f"line {lineno}: content after # EOF")
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
                families.setdefault(parts[2], {"type": parts[3],
                                               "help": None, "samples": []})
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam = families.setdefault(
                    parts[2], {"type": None, "help": None, "samples": []})
                fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        labels = {}
        if m.group("labels"):
            for k, v in _LABEL_RE.findall(m.group("labels")):
                labels[k] = v.replace('\\"', '"').replace("\\\\", "\\")
        name = m.group("name")
        fam = families.setdefault(
            _family_of(name, types), {"type": None, "help": None,
                                      "samples": []})
        fam["samples"].append((name, labels, float(m.group("value"))))
    if not saw_eof:
        raise ValueError("exposition not terminated by # EOF")
    return families


def sample_value(families, family, sample_name=None, **labels):
    """Convenience lookup: the first sample of ``family`` matching the
    sample name (default: the family name itself) and label subset."""
    fam = families.get(family)
    if fam is None:
        return None
    want = sample_name or family
    for name, lbls, value in fam["samples"]:
        if name == want and all(lbls.get(k) == v for k, v in labels.items()):
            return value
    return None


def fetch(target, timeout=10.0):
    """Read exposition text from an http(s) URL or a local file path."""
    if target.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(target, timeout=timeout) as resp:
            return resp.read().decode("utf-8")
    with open(target) as f:
        return f.read()


def build_table(families):
    lines = [f"{'family':<36} {'type':<9} {'samples':>8} {'value':>16}"]
    lines.append("-" * 72)
    for fam in sorted(families):
        f = families[fam]
        head = ""
        if f["samples"]:
            name, labels, value = f["samples"][0]
            lbl = ",".join(f"{k}={v}" for k, v in labels.items())
            head = f"{value:g}" + (f" [{name}{{{lbl}}}]" if labels else "")
        lines.append(f"{fam:<36} {f['type'] or '?':<9} "
                     f"{len(f['samples']):>8} {head:>16}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", help="http(s)://host:port/metrics URL or a "
                                   "file of exposition text")
    ap.add_argument("--assert-family", action="append", default=[],
                    metavar="NAME",
                    help="fail (exit 1) unless this family was scraped "
                         "with at least one sample; repeatable")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the table (assertions still run)")
    args = ap.parse_args(argv)

    try:
        text = fetch(args.target)
        families = parse_openmetrics(text)
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"scrape failed: {e}", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"scraped {len(families)} families from {args.target}")
        print(build_table(families))
    missing = [n for n in args.assert_family
               if not families.get(n, {}).get("samples")]
    if missing:
        print(f"missing families: {', '.join(missing)}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
