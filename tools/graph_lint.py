#!/usr/bin/env python
"""Graph-lint the bench model zoo (static analysis only — nothing executes
on a device unless ``--run-steps`` is given).

For each model this builds the same train step the benchmarks measure
(``bench_resnet.py`` / ``bench_bert.py`` recipes at CPU smoke scale),
abstractly traces it with ``paddle_tpu.analysis.lint_step`` against two
example batches, prints the findings table, and (with ``--jsonl``) emits one
JSON object per finding — ``Finding.as_dict()`` plus a ``model`` key;
``Finding.from_dict`` round-trips the lines.

Exit status: 1 when any finding at/above ``--fail-on`` severity survived
(default ``error``) — ``tools/run_tests.sh`` smoke-runs this as a CI gate.

``--fixture adam-lazy`` swaps every model's optimizer for a pre-fix Adam
whose accumulators materialize lazily during the first step: the regression
fixture for the retrace-state-structure rule (the Adam/AdamW double-trace
PR 2's telemetry measured). ``--run-steps N`` additionally executes N real
steps per model under telemetry and prints the static-prediction vs
observed-compile-count crosscheck.

Usage:
    JAX_PLATFORMS=cpu python tools/graph_lint.py [--models mlp resnet bert]
        [--jsonl PATH] [--fixture adam-lazy] [--fail-on error|warning|never]
        [--run-steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _lazy_adam(paddle):
    class LazyAdam(paddle.optimizer.Adam):
        """Pre-fix fixture: defeat the eager accumulator init so moment/
        beta-pow state materializes lazily inside the first traced step —
        the state-pytree instability the lint must catch."""

        def _ensure_accumulators(self):
            pass

    return LazyAdam


def _step_of(model_fwd_loss, model, opt, name):
    from paddle_tpu.jit.functionalize import CompiledStep

    def train_step(x, y):
        loss = model_fwd_loss(x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    train_step.__name__ = name
    return CompiledStep(train_step, stateful=[model, opt], donate_state=True)


def _batches(x_fn, y_fn, n=2):
    from paddle_tpu.framework.tensor import Tensor

    rng = np.random.RandomState(0)
    return [(Tensor(x_fn(rng)), Tensor(y_fn(rng))) for _ in range(n)]


def build_mlp(fixture=None):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(32, 64), paddle.nn.ReLU(),
                               paddle.nn.Linear(64, 10))
    opt_cls = (_lazy_adam(paddle) if fixture == "adam-lazy"
               else paddle.optimizer.Adam)
    opt = opt_cls(learning_rate=1e-3, parameters=net.parameters())

    def fwd_loss(x, y):
        return F.cross_entropy(net(x), y).mean()

    step = _step_of(fwd_loss, net, opt, "mlp_train_step")
    return step, _batches(
        lambda r: r.randn(8, 32).astype(np.float32),
        lambda r: r.randint(0, 10, (8, 1)).astype(np.int64))


def build_resnet(fixture=None):
    """ResNet-50 at the bench script's CPU smoke scale (32x32, 10 classes,
    SGD+momentum — bench_resnet.py recipe)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=10)
    if fixture == "adam-lazy":
        opt = _lazy_adam(paddle)(learning_rate=0.1,
                                 parameters=model.parameters())
    else:
        opt = paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9, parameters=model.parameters(),
            weight_decay=1e-4)

    def fwd_loss(x, y):
        return F.cross_entropy(model(x).astype("float32"), y,
                               reduction="mean")

    step = _step_of(fwd_loss, model, opt, "resnet_train_step")
    return step, _batches(
        lambda r: r.randn(4, 3, 32, 32).astype(np.float32),
        lambda r: r.randint(0, 10, (4, 1)).astype(np.int64))


def build_bert(fixture=None):
    """BERT MLM at the bench script's CPU smoke config (bench_bert.py),
    AdamW — the optimizer whose lazy double-trace this lint regression-
    tests."""
    import paddle_tpu as paddle
    from paddle_tpu.models import BertConfig, BertForPretraining

    paddle.seed(0)
    cfg = BertConfig(vocab_size=512, hidden_size=128, num_layers=2,
                     num_heads=2, intermediate_size=256,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attention_dropout=0.0)
    model = BertForPretraining(cfg)
    opt_cls = (_lazy_adam(paddle) if fixture == "adam-lazy"
               else paddle.optimizer.AdamW)
    opt = opt_cls(learning_rate=1e-4, parameters=model.parameters())

    def fwd_loss(ids, labels):
        return model.loss(ids, labels)

    step = _step_of(fwd_loss, model, opt, "bert_train_step")
    return step, _batches(
        lambda r: r.randint(0, 512, (4, 64)).astype(np.int32),
        lambda r: r.randint(0, 512, (4, 64)).astype(np.int32))


def build_serve_decode(fixture=None):
    """The serving tier's batched decode step (tiny GPT, static-shape KV
    cache) against two CONSECUTIVE generation positions — the O(1)-decode
    acceptance gate: with the preallocated cache both example batches have
    IDENTICAL signatures, so the `retrace-shape-churn` and
    `kv-cache-concat` rules must stay silent (the grow-by-concat cache
    they exist to catch is regression-tested in tests/test_serving.py)."""
    del fixture  # no optimizer in the serving path
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    engine = GenerationEngine(GPTForCausalLM(cfg), max_batch=2, max_len=32,
                              prefill_buckets=(8,))
    return engine.decode_step, [engine.example_decode_args([5, 3]),
                                engine.example_decode_args([6, 4])]


def build_serve_verify(fixture=None):
    """The speculative-decoding verify step (``[batch, k+1]`` window)
    against two different slot-length vectors — the ISSUE-13 analogue of
    the serve-decode gate: lengths live inside the static cache, so both
    example signatures are identical and the shape-churn rules must stay
    silent (one compile serves every acceptance pattern)."""
    del fixture  # no optimizer in the serving path
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    engine = GenerationEngine(GPTForCausalLM(cfg), max_batch=2, max_len=32,
                              prefill_buckets=(8,), spec_k=3)
    return engine.verify_step, [engine.example_verify_args([5, 3]),
                                engine.example_verify_args([9, 6])]


ZOO = {"mlp": build_mlp, "resnet": build_resnet, "bert": build_bert,
       "serve-decode": build_serve_decode, "serve-verify": build_serve_verify}


def lint_zoo(models, fixture=None, run_steps=0, out=sys.stdout):
    """Returns ``[(model_name, LintReport)]`` (import-friendly: the tests
    drive this directly)."""
    from paddle_tpu import analysis

    results = []
    for name in models:
        step, batches = ZOO[name](fixture=fixture)
        args = batches[0]  # (x, y) train pairs or n-ary serving args
        report = analysis.lint_step(step, *args, extra_args=batches[1:])
        print(f"\n== {name} ({step.name}) ==", file=out)
        print(report.table(), file=out)
        if run_steps > 0:
            from paddle_tpu.profiler import telemetry

            telemetry.reset()
            telemetry.enable()
            try:
                for _ in range(run_steps):
                    step(*args)
                checks = analysis.crosscheck_telemetry(report)
            finally:
                telemetry.disable()
            for c in checks:
                print(f"crosscheck: predicted_retrace="
                      f"{c['predicted_retrace']} observed_compiles="
                      f"{c['observed_compiles']} agrees={c['agrees']}",
                      file=out)
        results.append((name, report))
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--models", nargs="+", default=["mlp", "resnet", "bert"],
                    choices=sorted(ZOO))
    ap.add_argument("--jsonl", default=None,
                    help="write one JSON object per finding to this path")
    ap.add_argument("--format", default="table", choices=["table", "sarif"],
                    help="sarif: emit a SARIF 2.1.0 document on stdout "
                         "(CI annotations) instead of tables")
    ap.add_argument("--fixture", default=None, choices=["adam-lazy"],
                    help="adam-lazy: pre-fix lazy-accumulator optimizer")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "never"],
                    help="exit 1 when findings at/above this severity exist")
    ap.add_argument("--run-steps", type=int, default=0,
                    help="also run N real steps per model under telemetry "
                         "and print the lint-vs-telemetry crosscheck")
    args = ap.parse_args(argv)

    sink = open(os.devnull, "w") if args.format == "sarif" else sys.stdout
    results = lint_zoo(args.models, fixture=args.fixture,
                       run_steps=args.run_steps, out=sink)

    if args.format == "sarif":
        from paddle_tpu.analysis import sarif_report

        findings = [f for _, report in results for f in report]
        json.dump(sarif_report(findings, tool="paddle-tpu-graph-lint"),
                  sys.stdout, indent=1)
        sys.stdout.write("\n")

    if args.jsonl:
        with open(args.jsonl, "w") as fh:
            for name, report in results:
                for f in report:
                    fh.write(json.dumps({"model": name, **f.as_dict()},
                                        sort_keys=True) + "\n")
        print(f"\nwrote {sum(len(r) for _, r in results)} findings to "
              f"{args.jsonl}", file=sink)

    n_err = sum(len(r.errors) for _, r in results)
    n_warn = sum(len(r.warnings) for _, r in results)
    print(f"\ngraph lint: {n_err} error(s), {n_warn} warning(s) across "
          f"{len(results)} model(s)", file=sink)
    if args.fail_on == "never":
        return 0
    gate = n_err + (n_warn if args.fail_on == "warning" else 0)
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
