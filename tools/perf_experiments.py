"""A/B experiments for the GPT train-step on the real chip: attention kernel
choice, layernorm dtype, and a same-shape pure-GEMM ceiling.

Run:  PYTHONPATH=/root/.axon_site:/root/repo python tools/perf_experiments.py
"""
from __future__ import annotations

import time

import numpy as np


def timeit_batch(step, batches, k=6):
    outs = [step(*b) for b in batches[:2]]
    np.asarray(outs[-1]._value) if hasattr(outs[-1], "_value") else None
    t0 = time.perf_counter()
    outs = [step(*b) for b in batches[2:2 + k]]
    last = outs[-1]
    np.asarray(last._value if hasattr(last, "_value") else last)
    return (time.perf_counter() - t0) / k


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    batch, seq = 16, 1024
    tok = batch * seq
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position_embeddings=1024,
                    hidden_dropout=0.0, attention_dropout=0.0)

    rng = np.random.RandomState(0)
    k = 6
    data = [
        (Tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)),) * 2
        for _ in range(2 + k)
    ]

    def build(ln_fp32=True):
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
        if ln_fp32:
            for name, sub in model.named_sublayers():
                if type(sub).__name__ == "LayerNorm":
                    sub.to(dtype="float32")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     multi_precision=True)

        def full_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return CompiledStep(full_step, stateful=[model, opt],
                            donate_state=True)

    # 1) baseline
    t = timeit_batch(build(), data, k)
    print(f"baseline (flash, ln fp32)      {t*1e3:8.2f} ms  {tok/t:9.0f} tok/s", flush=True)

    # 2) XLA attention instead of Pallas flash
    paddle.set_flags({"disable_flash_attention": True})
    try:
        t = timeit_batch(build(), data, k)
        print(f"xla attention (no flash)       {t*1e3:8.2f} ms  {tok/t:9.0f} tok/s", flush=True)
    finally:
        paddle.set_flags({"disable_flash_attention": False})

    # 3) all-bf16 layernorm
    t = timeit_batch(build(ln_fp32=False), data, k)
    print(f"flash, ln bf16                 {t*1e3:8.2f} ms  {tok/t:9.0f} tok/s", flush=True)

    # 4) pure-GEMM ceiling with the step's dominant shapes (fwd+bwd pattern:
    # each fwd matmul has two bwd partners of the same flop count)
    h = cfg.hidden_size
    x = jnp.asarray(rng.randn(tok, h), jnp.bfloat16)
    ws = {
        "qkv": jnp.asarray(rng.randn(h, 3 * h), jnp.bfloat16),
        "proj": jnp.asarray(rng.randn(h, h), jnp.bfloat16),
        "up": jnp.asarray(rng.randn(h, 4 * h), jnp.bfloat16),
        "down": jnp.asarray(rng.randn(4 * h, h), jnp.bfloat16),
        "head": jnp.asarray(rng.randn(h, cfg.vocab_size), jnp.bfloat16),
    }

    x4 = jnp.asarray(rng.randn(tok, 4 * h), jnp.bfloat16)

    @jax.jit
    def gemm_chain(x, x4):
        acc = jnp.zeros((), jnp.float32)
        for _ in range(cfg.num_layers):
            for wname in ("qkv", "proj", "up", "down"):
                w = ws[wname]
                inp = x if w.shape[0] == h else x4
                for _rep in range(3):  # fwd + 2 bwd-equivalent flops
                    z = jnp.dot(inp, w)
                    acc = acc + z.astype(jnp.float32).sum() * 1e-9
        for _rep in range(3):
            z = jnp.dot(x, ws["head"])
            acc = acc + z.astype(jnp.float32).sum() * 1e-9
        return acc

    outs = [gemm_chain(x + i, x4 + i) for i in range(2)]
    np.asarray(outs[-1])
    t0 = time.perf_counter()
    outs = [gemm_chain(x + 2 + i, x4 + 2 + i) for i in range(k)]
    np.asarray(outs[-1])
    t = (time.perf_counter() - t0) / k
    flops = 3 * (cfg.num_layers * (2 * tok * h * 3 * h + 2 * tok * h * h
                                   + 2 * tok * h * 4 * h + 2 * tok * 4 * h * h)
                 + 2 * tok * h * cfg.vocab_size)
    print(f"pure GEMM chain (same shapes)  {t*1e3:8.2f} ms  "
          f"{flops/t/1e12:6.1f} TF/s achieved", flush=True)


if __name__ == "__main__":
    main()
