#!/usr/bin/env python
"""Serving benchmark: continuous batching vs batch-of-1 sequential decode.

Serves a GPT config from ``paddle_tpu.models`` through the
``paddle_tpu.serving`` tier (static-shape KV cache, prefill/decode split,
slot-based continuous batching) and reports:

* aggregate tokens/sec for (a) SEQUENTIAL serving — one request at a
  time through a batch-1 engine, the no-batching baseline — and (b)
  CONTINUOUS batching at ``--concurrency`` slots, plus the speedup;
* user-perceived p50/p95 request latency (arrival → last token, so the
  sequential baseline pays its queue wait — that is the point);
* decode-batch occupancy and requests-in-flight from telemetry;
* the O(1)-decode proof: telemetry compile counters (decode must compile
  EXACTLY once; prefill once per length bucket; the speculative verify
  and chunked-prefill steps exactly once each) and a static graph-lint
  of the decode step at two consecutive positions (zero shape-churn /
  kv-cache findings).

Serving speed v2 (ISSUE 13): the continuous engine runs with
speculative decoding (``--spec-k``, n-gram prompt-lookup drafts verified
in one ``[batch, k+1]`` forward — output stays byte-identical to greedy)
and chunked prefill (``--prefill-chunk``) ON by default; pass 0 to
disable either. ``--prompt-len-sweep`` appends TTFT-vs-prompt-length
rows to the artifact so the flat-TTFT claim is a tracked series, and the
telemetry block carries ``serve.spec_acceptance_rate`` plus the
``recompile_whitelist`` marker that lets bench_sentinel hard-gate
``recompile_count`` as an 'equal' contract metric.

Long-context raw speed (ISSUE 15): ``--long-prompt`` switches to the
long-prompt leg — 4x max_len and prefill buckets, every prompt in the
top bucket — so prefill, chunked prefill, and decode all route through
the blockwise cached attention path (length-masked KV-block scan / the
Pallas flash cached kernel on TPU) instead of the dense additive mask.
All the contract assertions below still apply verbatim: the blockwise
route must stay O(1)-decode, recompile-free, and byte-identical greedy.

Emits one JSON line and (with ``--artifact``) a SERVE_r*.json. ``--smoke``
runs a tiny CPU config and hard-asserts the telemetry contract — wired
into ``tools/run_tests.sh`` as a CI gate.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pctl(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else 0.0


def build_model(smoke, long_prompt=False):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if smoke:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2,
                        max_position_embeddings=256 if long_prompt else 64,
                        hidden_dropout=0.0, attention_dropout=0.0)
    else:
        # GPT-2 small (124M) — the same flagship config bench.py trains
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        hidden_dropout=0.0, attention_dropout=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return cfg, model


def make_requests(cfg, n, max_new, buckets, seed, long_prompt=False):
    from paddle_tpu.serving import Request

    rng = np.random.RandomState(seed)
    if long_prompt:
        # every prompt lands in the top bucket: prefill runs at blockwise
        # lengths instead of the short-prompt regime
        lo, hi = buckets[-1] // 2 + 1, buckets[-1]
    else:
        lo, hi = 4, max(5, buckets[-1] // 2)
    return [Request(prompt=rng.randint(0, cfg.vocab_size,
                                       int(rng.randint(lo, hi))).tolist(),
                    max_new_tokens=max_new)
            for _ in range(n)]


def run_sequential(model, requests, max_len, buckets):
    """Batch-of-1 serial decode: every request waits for its predecessors
    (user-perceived latency includes that wait — all requests 'arrive' at
    t0). Run OUTSIDE the telemetry window so the continuous engine's
    compile counters stay clean (both steps share their step names)."""
    from paddle_tpu.serving import GenerationEngine

    eng = GenerationEngine(model, max_batch=1, max_len=max_len,
                           prefill_buckets=buckets)
    # warm every executable (one per bucket + decode) outside the timer
    for b in buckets:
        eng.generate([1] * min(b, max_len - 2), max_new_tokens=2)
    t0 = time.perf_counter()
    lat, tokens = [], 0
    for req in requests:
        out = eng.generate(req.prompt, max_new_tokens=req.max_new_tokens,
                           eos_id=req.eos_id)
        tokens += len(out)
        lat.append(time.perf_counter() - t0)  # includes queue wait
    wall = time.perf_counter() - t0
    return {
        "wall_s": round(wall, 4),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else None,
        "p50_latency_s": round(_pctl(lat, 50), 4),
        "p95_latency_s": round(_pctl(lat, 95), 4),
    }


def warm_engine(eng, buckets, max_len, concurrency):
    """Compile every serving executable outside the timers: one prefill
    per bucket, the decode step, and (when built) the chunked-prefill and
    speculative-verify steps. Compiles still land in telemetry."""
    for b in buckets:
        eng.prefill(0, [1] * min(b, max_len - 2))
    eng.decode_once(np.zeros(concurrency, np.int32))
    if eng.prefill_chunk:
        warm = [1] * (eng.prefill_chunk + 1)  # exactly two chunks
        off, tok = 0, None
        while tok is None:
            tok = eng.prefill_chunk_step(0, warm, off)
            off += eng.prefill_chunk
    if eng.spec_k:
        # lengths are NOT advanced by a verify, so this leaves no state
        eng.verify_once(np.zeros((concurrency, eng.spec_k + 1), np.int32))


def run_continuous(model, requests, max_len, buckets, concurrency,
                   spec_k=0, prefill_chunk=None):
    """Continuous batching under telemetry: compiles (during warmup) and
    the scheduler's serve.* stats all land in the registry."""
    from paddle_tpu.profiler import telemetry
    from paddle_tpu.serving import GenerationEngine, Scheduler

    telemetry.reset()
    # recompiling once per prefill bucket is the DESIGN here, not churn —
    # lift the per-step-name warning threshold above the bucket count
    telemetry.enable(recompile_warn_threshold=len(buckets) + 2)
    eng = GenerationEngine(model, max_batch=concurrency, max_len=max_len,
                           prefill_buckets=buckets, spec_k=spec_k,
                           prefill_chunk=prefill_chunk or None)
    warm_engine(eng, buckets, max_len, concurrency)

    sched = Scheduler(eng)
    t0 = time.perf_counter()
    submit_ns = time.perf_counter_ns()
    for req in requests:
        sched.submit(req)
        req.submit_ns = submit_ns  # common arrival instant, like sequential
    finished = sched.run()
    wall = time.perf_counter() - t0

    lat = [r.latency_s for r in finished if r.latency_s is not None]
    ttft = [r.ttft_s for r in finished if r.ttft_s is not None]
    tokens = sum(len(r.tokens) for r in finished)
    tm = telemetry.get_telemetry()
    stats = {
        "wall_s": round(wall, 4),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else None,
        "p50_latency_s": round(_pctl(lat, 50), 4),
        "p95_latency_s": round(_pctl(lat, 95), 4),
        "p50_ttft_s": round(_pctl(ttft, 50), 4),
        "p95_ttft_s": round(_pctl(ttft, 95), 4),
        "batch_occupancy": round(sched.occupancy(), 4),
        "decode_steps": sched.decode_steps,
        # the drain retires the in-flight gauges (stale-gauge fix); a
        # fully-drained run reports 0 by construction
        "requests_in_flight": tm.gauges().get("serve.requests_in_flight",
                                              0.0),
    }
    # publish the bench headline back into the registry so the telemetry
    # block (and anything tailing the exporter) carries it
    tm.set_gauge("serve.tokens_per_s", stats["tokens_per_sec"] or 0.0)
    tm.set_gauge("serve.p95_latency_s", stats["p95_latency_s"])
    tm.set_gauge("serve.p50_latency_s", stats["p50_latency_s"])
    tm.set_gauge("serve.batch_occupancy", stats["batch_occupancy"])
    telemetry.disable()  # data stays readable for the block below
    return eng, sched, stats


def lint_decode(eng):
    """Static O(1) proof: lint the decode step against two CONSECUTIVE
    positions — with the static cache both signatures are identical, so
    shape-churn/kv-cache findings must be zero."""
    from paddle_tpu import analysis

    a1 = eng.example_decode_args([5] * min(2, eng.max_batch))
    a2 = eng.example_decode_args([6] * min(2, eng.max_batch))
    report = analysis.lint_step(eng.decode_step, *a1, extra_args=[a2])
    churn = [f for f in report
             if f.rule in ("retrace-shape-churn", "kv-cache-concat")]
    return {
        "findings": len(report),
        "shape_churn_findings": len(churn),
        "rules": sorted({f.rule for f in report}),
    }


def run_prompt_len_sweep(cfg, model, max_len, buckets, concurrency,
                         spec_k, prefill_chunk, seed, lengths=None):
    """TTFT vs prompt length, at queue pressure (2× concurrency, every
    prompt the same length L): with one-shot prefill the second wave's
    TTFT inherits every first-wave prefill whole, so p95 TTFT scales
    with L; chunked prefill amortizes each prompt into bounded per-tick
    chunks that ride along with decode. Rows land in the artifact so the
    claim is a tracked series; ``growth_ratio`` < 1 means p95 TTFT grew
    sub-linearly vs the prompt length itself."""
    from paddle_tpu.serving import GenerationEngine, Request, Scheduler

    eng = GenerationEngine(model, max_batch=concurrency, max_len=max_len,
                           prefill_buckets=buckets, spec_k=spec_k,
                           prefill_chunk=prefill_chunk or None)
    warm_engine(eng, buckets, max_len, concurrency)
    max_new = 8  # short decode budget: the sweep isolates TTFT
    lengths = [x for x in (lengths or (4, 8, 16, 24, 32))
               if x <= buckets[-1] and x + max_new <= max_len]
    rng = np.random.RandomState(seed)
    rows = []
    for L in lengths:
        reqs = [Request(prompt=rng.randint(0, cfg.vocab_size, L).tolist(),
                        max_new_tokens=max_new)
                for _ in range(2 * concurrency)]
        sched = Scheduler(eng)
        t0 = time.perf_counter_ns()
        for r in reqs:
            sched.submit(r)
            r.submit_ns = t0  # common arrival instant
        sched.run()
        ttft = [r.ttft_s for r in reqs if r.ttft_s is not None]
        rows.append({"prompt_len": int(L),
                     "requests": len(reqs),
                     "p50_ttft_s": round(_pctl(ttft, 50), 4),
                     "p95_ttft_s": round(_pctl(ttft, 95), 4)})
    lo, hi = rows[0], rows[-1]
    growth = None
    if lo["p95_ttft_s"] > 0 and hi["prompt_len"] > lo["prompt_len"]:
        growth = round((hi["p95_ttft_s"] / lo["p95_ttft_s"])
                       / (hi["prompt_len"] / lo["prompt_len"]), 4)
    return {"rows": rows, "growth_ratio": growth,
            "sub_linear": bool(growth is not None and growth < 1.0)}


def telemetry_serve_block():
    from paddle_tpu.profiler import telemetry

    s = telemetry.summary()
    block = {k: v for k, v in s["gauges"].items() if k.startswith("serve.")}
    block.update({k: v for k, v in s["counters"].items()
                  if k.startswith("serve.")})
    block["compiles"] = dict(s["compiles"])
    block["recompile_count"] = int(s["recompile_count"])
    tm = telemetry.get_telemetry()
    # the marker bench_sentinel keys on: recompile_count in THIS artifact
    # is declared-variant aware (per-bucket prefill compiles are design,
    # not churn), so the sentinel may 'equal'-gate it at 0
    block["recompile_whitelist"] = {
        k: int(v) for k, v in sorted(tm.declared_variants().items())}
    for name in ("serve.ttft_s", "serve.tpot_s", "serve.latency_s"):
        st = tm.get(name)
        if st and st.get("count"):
            block[name + ".mean"] = round(st["sum"] / st["count"], 6)
            # exact running sum plus the reservoir percentiles (the
            # sentinel and scrapers want rate-correct figures)
            block[name + ".sum"] = round(st["sum"], 6)
            block[name + ".p50"] = round(tm.stat(name, "p50"), 6)
            block[name + ".p95"] = round(tm.stat(name, "p95"), 6)
    return block


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config + hard telemetry assertions "
                         "(the run_tests.sh CI gate)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--max-new-tokens", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--spec-k", type=int, default=None,
                    help="speculative draft length (default 4; 0 disables)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked-prefill width (default 16, smoke 4; "
                         "0 disables)")
    ap.add_argument("--prompt-len-sweep", action="store_true",
                    help="append TTFT-vs-prompt-length rows to the "
                         "artifact (sub-linear growth is the contract)")
    ap.add_argument("--long-prompt", action="store_true",
                    help="long-prompt leg (ISSUE 15): 4x max_len and "
                         "buckets, every prompt in the top bucket, so "
                         "prefill/decode take the blockwise cached-"
                         "attention route instead of the dense mask")
    ap.add_argument("--artifact", default=None)
    ap.add_argument("--chaos", action="store_true",
                    help="also run tools/chaos_serve.py and embed its "
                         "verdict as the chaos_ok contract metric (the "
                         "bench_sentinel 'equal'-direction gate)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.concurrency = min(args.concurrency, 4)
    n_req = args.requests or 2 * args.concurrency
    max_new = args.max_new_tokens or (8 if args.smoke else 64)
    # serving speed v2 is the default path; 0 opts out of either feature
    spec_k = 4 if args.spec_k is None else max(0, args.spec_k)
    prefill_chunk = ((4 if args.smoke else 16) if args.prefill_chunk is None
                     else max(0, args.prefill_chunk))

    cfg, model = build_model(args.smoke, long_prompt=args.long_prompt)
    # size the cache to the workload: largest prompt (buckets[-1]/2) plus
    # the generation budget — decode attention + cache traffic scale with
    # max_len, so capacity beyond the worst case is pure per-step cost
    if args.long_prompt:
        # long-prompt leg: the KV lengths must cross the blockwise route.
        # The full config reaches the stock min-kv threshold (1024) on its
        # own; the smoke config is held small, so lower the threshold to
        # its bucket scale — same route, CPU-sized shapes
        max_len = 256 if args.smoke else cfg.max_position_embeddings
        buckets = (64, 128) if args.smoke else (256, 512)
        if args.smoke:
            from paddle_tpu.framework.flags import set_flags

            set_flags({"blockwise_attention_min_kv": 64})
    else:
        max_len = 64 if args.smoke else 32 + max_new
        buckets = (8, 16) if args.smoke else (16, 64)

    requests = make_requests(cfg, n_req, max_new, buckets, args.seed,
                             long_prompt=args.long_prompt)
    # identical prompts for both runs (Request objects are stateful):
    from paddle_tpu.serving import Request

    seq_requests = [Request(prompt=list(r.prompt),
                            max_new_tokens=r.max_new_tokens)
                    for r in requests]

    sequential = run_sequential(model, seq_requests, max_len, buckets)
    eng, sched, continuous = run_continuous(model, requests, max_len,
                                            buckets, args.concurrency,
                                            spec_k=spec_k,
                                            prefill_chunk=prefill_chunk)
    lint = lint_decode(eng)
    tblock = telemetry_serve_block()

    speedup = None
    if sequential["tokens_per_sec"] and continuous["tokens_per_sec"]:
        speedup = round(continuous["tokens_per_sec"]
                        / sequential["tokens_per_sec"], 3)

    result = {
        "metric": "serve_tokens_per_sec",
        "value": continuous["tokens_per_sec"],
        "unit": "tok/s",
        "speedup_vs_sequential": speedup,
        "config": {
            "model": "gpt2-smoke" if args.smoke else "gpt2-124M",
            "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
            "max_len": max_len, "prefill_buckets": list(buckets),
            "concurrency": args.concurrency, "requests": n_req,
            "max_new_tokens": max_new,
            "spec_k": spec_k, "prefill_chunk": prefill_chunk,
            "long_prompt": bool(args.long_prompt),
        },
        "sequential": sequential,
        "continuous": continuous,
        "decode_lint": lint,
        "telemetry": tblock,
    }
    if args.prompt_len_sweep:
        # runs after the telemetry block is captured so the sweep's own
        # engine/compiles cannot perturb the contract counters above
        sweep_lengths = None
        if args.long_prompt:
            sweep_lengths = (buckets[0] // 2, buckets[0],
                             (buckets[0] + buckets[-1]) // 2, buckets[-1])
        sweep = run_prompt_len_sweep(cfg, model, max_len, buckets,
                                     args.concurrency, spec_k,
                                     prefill_chunk, args.seed,
                                     lengths=sweep_lengths)
        result["prompt_len_sweep"] = sweep
    chaos = None
    if args.chaos:
        # the chaos contract is config-independent, so the harness always
        # runs its own tiny deterministic config — cheap even when the
        # bench itself ran gpt2-124M
        import chaos_serve

        chaos = chaos_serve.run_chaos(seed=args.seed)
        result["chaos_ok"] = 1.0 if chaos["ok"] else 0.0
        result["chaos"] = {k: chaos[k] for k in
                           ("finish_reasons", "survivors", "slo_alerts",
                            "problems")}
    print(json.dumps(result))
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")

    # CI contract (the satellite gate): the telemetry block must carry the
    # serving headline gauges, the decode step must have compiled exactly
    # once, and the static lint must see a shape-stable decode
    problems = []
    if "serve.tokens_per_s" not in tblock:
        problems.append("telemetry block missing serve.tokens_per_s")
    if "serve.p95_latency_s" not in tblock:
        problems.append("telemetry block missing serve.p95_latency_s")
    if tblock["compiles"].get("serve_decode") != 1:
        problems.append(f"decode compiled "
                        f"{tblock['compiles'].get('serve_decode')}x "
                        f"(want exactly 1)")
    if tblock["compiles"].get("serve_prefill", 0) > len(buckets):
        problems.append("prefill compiled more than once per bucket")
    if spec_k and tblock["compiles"].get("serve_verify") != 1:
        problems.append(f"verify compiled "
                        f"{tblock['compiles'].get('serve_verify')}x "
                        f"(want exactly 1)")
    if prefill_chunk and tblock["compiles"].get("serve_prefill_chunk") != 1:
        problems.append(f"chunked prefill compiled "
                        f"{tblock['compiles'].get('serve_prefill_chunk')}x "
                        f"(want exactly 1)")
    if tblock["recompile_count"] != 0:
        problems.append(f"recompile_count {tblock['recompile_count']} "
                        f"(every variant must be declared)")
    if spec_k and not tblock.get("serve.spec_ticks"):
        problems.append("speculation enabled but no speculative ticks ran")
    sweep = result.get("prompt_len_sweep")
    if sweep is not None and prefill_chunk and not sweep["sub_linear"]:
        problems.append(f"p95 TTFT grew super-linearly with prompt length "
                        f"(growth_ratio {sweep['growth_ratio']})")
    if lint["shape_churn_findings"]:
        problems.append(f"decode lint: {lint['shape_churn_findings']} "
                        f"shape-churn/kv-cache finding(s)")
    if chaos is not None and not chaos["ok"]:
        problems.append("chaos harness: " + "; ".join(chaos["problems"]))
    if problems:
        print("bench_serve FAILED: " + "; ".join(problems), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
