"""Capture an XPlane device profile of the bench train step and print the
top device ops by self time (parsed from the trace.json.gz the jax profiler
writes). Run: PYTHONPATH=/root/.axon_site:/root/repo python tools/capture_profile.py
"""
from __future__ import annotations

import glob
import gzip
import json
import tempfile
import time

import numpy as np


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--model", default="gpt", choices=["gpt", "bert", "resnet"])
    a = ap.parse_args()

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    batch, seq = a.batch, a.seq
    paddle.seed(0)
    if a.model == "resnet":
        import paddle_tpu.nn.functional as F
        from paddle_tpu.vision.models import resnet50

        paddle.incubate.autotune.set_config({"layout": {"enable": True}})
        cfg = None
        model = resnet50(num_classes=1000)

        class _M:
            def loss(self, x, y):
                logits = model(x)
                return F.cross_entropy(logits.astype("float32"), y,
                                       reduction="mean")

            to = model.to
            named_sublayers = model.named_sublayers
            parameters = model.parameters

        model_wrap = _M()
    elif a.model == "bert":
        from paddle_tpu.models import BertForPretraining, bert_large

        cfg = bert_large()
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
        model = BertForPretraining(cfg)
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=a.seq,
                        hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    for name, sub in model.named_sublayers():
        if (type(sub).__name__ == "LayerNorm"
                or type(sub).__name__.startswith("BatchNorm")):
            sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)

    loss_model = model_wrap if a.model == "resnet" else model

    def full_step(ids, labels):
        loss = loss_model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(full_step, stateful=[model, opt], donate_state=True)
    rng = np.random.RandomState(0)
    if a.model == "resnet":
        import jax.numpy as jnp

        data = [(Tensor(jnp.asarray(rng.randn(batch, 3, 224, 224)
                                    .astype(np.float32)).astype("bfloat16")),
                 Tensor(rng.randint(0, 1000, (batch, 1)).astype(np.int64)))
                for _ in range(8)]
    else:
        data = [Tensor(rng.randint(0, cfg.vocab_size,
                                   (batch, seq)).astype(np.int64))
                for _ in range(8)]
    def _args(d):
        return d if isinstance(d, tuple) else (d, d)

    for i in range(3):
        np.asarray(step(*_args(data[i]))._value)

    d = tempfile.mkdtemp(prefix="xplane_")
    with jax.profiler.trace(d):
        outs = [step(*_args(data[3 + i])) for i in range(4)]
        np.asarray(outs[-1]._value)

    time.sleep(2)
    files = glob.glob(f"{d}/**/*.trace.json.gz", recursive=True)
    print("trace files:", files)
    if not files:
        return
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X" and e.get("dur")]
    # The trace mixes host python lanes, module-level wrappers, and the
    # flat XLA-op device lane — summing everything double-counts nested
    # parents and mixes host time into the denominator. Aggregate ONLY
    # within the (pid, tid) lane that holds the XLA fusion events; that
    # lane is flat, so totals there are true self times.
    lanes = {}
    for e in events:
        lanes.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    xla_lane = None
    for key, evs in lanes.items():
        if any(e.get("name", "").startswith("fusion") for e in evs):
            if xla_lane is None or (sum(x["dur"] for x in evs)
                                    > sum(x["dur"] for x in lanes[xla_lane])):
                xla_lane = key
    if xla_lane is None:
        print("no XLA op lane found in trace")
        return
    agg = {}
    for e in lanes[xla_lane]:
        name = e.get("name", "")
        agg.setdefault(name, [0, 0.0])
        agg[name][0] += 1
        agg[name][1] += e["dur"]
    total = sum(v[1] for v in agg.values())
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])[:40]
    print(f"device-op lane {xla_lane}: total {total/1e3:.1f} ms")
    print(f"{'name':<72} {'calls':>6} {'total_us':>12} {'%':>6}")
    for name, (cnt, dur) in rows:
        print(f"{name[:72]:<72} {cnt:>6} {dur:>12.0f} {100 * dur / total:>5.1f}%")


if __name__ == "__main__":
    main()
