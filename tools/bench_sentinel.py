#!/usr/bin/env python
"""Bench-history regression sentinel over the checked-in round artifacts.

Five rounds of BENCH/SERVE/MULTICHIP evidence sit in the repo and nothing
machine-checks them — throughput went flat for two rounds and only a human
noticed. This tool loads every ``BENCH_r*.json`` / ``SERVE_r*.json`` /
``MULTICHIP_r*.json`` series, extracts the headline metrics per round
(tokens/sec, MFU, comm_fraction, p95 latency/TTFT, decode compile counts,
dryrun parity), and compares the NEWEST round against a trailing baseline:

* baseline = median of up to ``--window`` prior rounds carrying the metric
  (median, not mean: one outlier round must not move the bar) — EXCEPT
  when the newest prior round is beyond tolerance better than that
  median: that is a confirmed step-change (the round passed this very
  sentinel when it was checked in), so the bar ratchets to it instead of
  letting a lagging median quietly forgive a slide back to the old level;
* tolerance = ``max(--rel-tol, --noise-k × noise)`` where noise is the
  robust coefficient of variation (1.4826·MAD/|median|) of the baseline
  window, capped at ``--noise-cap`` — a historically jittery metric gets
  slack, a historically flat one is held tight;
* direction-aware: tokens/sec and MFU regress DOWN, latency and compile
  counts regress UP, booleans (dryrun ok) regress on any flip.

Exits nonzero with a ranked table on regression — wired into
``tools/run_tests.sh`` (``--smoke``) so every future PR's bench round is
checked mechanically. ``--smoke`` both (a) runs the real history, which
must be clean, and (b) self-tests detection by injecting a synthetic 25%
tokens/sec drop as a new round, which MUST be flagged (25%, not 20%: the
drop must clear the ``--noise-cap`` ceiling on widened tolerance, or a
jittery history could legally absorb the self-test's own injection).

Usage::

    python tools/bench_sentinel.py                 # check repo history
    python tools/bench_sentinel.py --smoke         # CI gate
    python tools/bench_sentinel.py --inject bench:tokens_per_sec=0.8

Stdlib-only on purpose (CI runs it without jax), like the other tools/
report CLIs.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

#: robust-noise cap: never let a wild history widen tolerance past this
DEFAULT_NOISE_CAP = 0.20
DEFAULT_REL_TOL = 0.08
DEFAULT_WINDOW = 3


def _get(d, *path):
    for p in path:
        if not isinstance(d, dict) or p not in d:
            return None
        d = d[p]
    return d


def extract_bench(doc):
    """BENCH rounds: training throughput + MFU (+ device stats when the
    telemetry block carries them)."""
    out = {}
    v = _get(doc, "parsed", "value")
    if isinstance(v, (int, float)):
        out["tokens_per_sec"] = (float(v), "higher")
    mfu = _get(doc, "parsed", "mfu")
    if isinstance(mfu, (int, float)):
        out["mfu"] = (float(mfu), "higher")
    for path, name, direction in (
            (("telemetry", "comm_fraction"), "comm_fraction", "lower"),
            (("parsed", "comm_fraction"), "comm_fraction", "lower"),
            (("telemetry", "recompile_count"), "recompile_count", "lower"),
            # devprof's hbm.peak_bytes gauge, when the round carried it:
            # a step whose compiled peak creeps up is a regression even
            # while throughput holds (it forecloses batch-size headroom)
            (("telemetry", "hbm_peak_bytes"), "hbm_peak_bytes", "lower"),
            (("parsed", "hbm_peak_bytes"), "hbm_peak_bytes", "lower")):
        v = _get(doc, *path)
        if isinstance(v, (int, float)) and name not in out:
            out[name] = (float(v), direction)
    return out


def extract_serve(doc):
    """SERVE rounds: serving throughput, tail latency/TTFT, batching
    speedup, and the O(1)-decode compile contract."""
    out = {}
    v = doc.get("value")
    if isinstance(v, (int, float)):
        out["tokens_per_sec"] = (float(v), "higher")
    for path, name, direction in (
            (("continuous", "p95_latency_s"), "p95_latency_s", "lower"),
            (("continuous", "p95_ttft_s"), "p95_ttft_s", "lower"),
            (("speedup_vs_sequential",), "speedup_vs_sequential", "higher"),
            (("telemetry", "compiles", "serve_decode"),
             "decode_compiles", "equal"),
            (("decode_lint", "shape_churn_findings"),
             "shape_churn_findings", "lower"),
            (("telemetry", "hbm_peak_bytes"), "hbm_peak_bytes", "lower"),
            # chaos_serve verdict (1.0 = every resilience contract held);
            # 'equal' direction: ANY flip from the baseline is a regression
            (("chaos_ok",), "chaos_ok", "equal")):
        v = _get(doc, *path)
        if isinstance(v, (int, float)):
            out[name] = (float(v), direction)
    # recompile_count became a hard 'equal' contract (0) once bench_serve
    # started declaring expected per-step variants; the whitelist marker
    # distinguishes those artifacts from older rounds where the counter
    # legitimately read 1 (per-bucket prefill counted as churn) — gating
    # on the old semantics would flag the 1 → 0 improvement as drift
    tel = doc.get("telemetry")
    if (isinstance(tel, dict) and isinstance(
            tel.get("recompile_whitelist"), dict)
            and isinstance(tel.get("recompile_count"), (int, float))):
        out["recompile_count"] = (float(tel["recompile_count"]), "equal")
        out["verify_compiles"] = (float(
            tel.get("compiles", {}).get("serve_verify", 0)), "equal")
    return out


def extract_multichip(doc):
    """MULTICHIP rounds: the dryrun must keep passing at the same scale."""
    out = {}
    ok = doc.get("ok")
    if isinstance(ok, bool):
        out["dryrun_ok"] = (1.0 if ok else 0.0, "equal")
    n = doc.get("n_devices")
    if isinstance(n, (int, float)):
        out["n_devices"] = (float(n), "equal")
    return out


def extract_longctx(doc):
    """LONGCTX rounds: per-seq long-context throughput plus the PREDICTED
    HBM peak of the train step (the static mem-lint series — honest on
    CPU, where the 16k/32k rows never execute). A peak that creeps up at
    fixed batch forecloses the context-length headroom the blockwise
    attention path bought."""
    out = {}
    for row in doc.get("results") or []:
        seq = row.get("seq")
        if not isinstance(seq, (int, float)):
            continue
        v = row.get("tokens_per_sec")
        if isinstance(v, (int, float)):
            out[f"tokens_per_sec@{int(seq)}"] = (float(v), "higher")
        p = row.get("hbm_peak_bytes")
        if isinstance(p, (int, float)):
            out[f"hbm_peak_bytes@{int(seq)}"] = (float(p), "lower")
    return out


SERIES = (
    ("bench", "BENCH_r*.json", extract_bench),
    ("serve", "SERVE_r*.json", extract_serve),
    ("multichip", "MULTICHIP_r*.json", extract_multichip),
    ("longctx", "LONGCTX_r*.json", extract_longctx),
)


def load_series(root):
    """→ {series: [(round, {metric: (value, direction)}), ...]} sorted by
    round number; rounds that fail to parse are skipped with a note."""
    out = {}
    for name, pattern, extract in SERIES:
        rounds = []
        for path in glob.glob(os.path.join(root, pattern)):
            m = _ROUND_RE.search(os.path.basename(path))
            if m is None:
                continue
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                print(f"note: skipping unreadable {path}: {e}",
                      file=sys.stderr)
                continue
            metrics = extract(doc)
            if metrics:
                rounds.append((int(m.group(1)), metrics))
        rounds.sort()
        if rounds:
            out[name] = rounds
    return out


def _robust_noise(values):
    """1.4826·MAD / |median| — the robust coefficient of variation. 0.0
    when fewer than 3 points (no spread estimate worth trusting)."""
    if len(values) < 3:
        return 0.0
    med = statistics.median(values)
    if med == 0:
        return 0.0
    mad = statistics.median(abs(v - med) for v in values)
    return 1.4826 * mad / abs(med)


def compare(series, window=DEFAULT_WINDOW, rel_tol=DEFAULT_REL_TOL,
            noise_k=1.0, noise_cap=DEFAULT_NOISE_CAP):
    """Compare each series' newest round against its trailing baseline.
    → list of finding dicts (every metric gets one, regression or not)."""
    findings = []
    for name, rounds in series.items():
        newest_round, newest = rounds[-1]
        for metric, (value, direction) in sorted(newest.items()):
            prior = [(r, m[metric][0]) for r, m in rounds[:-1]
                     if metric in m]
            f = {
                "series": name,
                "metric": metric,
                "round": newest_round,
                "value": value,
                "direction": direction,
                "baseline": None,
                "baseline_rounds": [r for r, _ in prior[-window:]],
                "tolerance": None,
                "delta": None,
                "severity": 0.0,
                "status": "no-history",
            }
            if prior:
                base_vals = [v for _, v in prior[-window:]]
                baseline = statistics.median(base_vals)
                noise = min(_robust_noise(base_vals), noise_cap)
                tol = max(rel_tol, noise_k * noise)
                # step-change ratchet: when the newest prior round sits
                # beyond tolerance on the GOOD side of the window median,
                # that round is a confirmed improvement (it passed this
                # sentinel when it landed), not jitter — so it becomes
                # the bar. Without this, a 60% throughput jump leaves the
                # median lagging for two rounds and a slide back to the
                # old level reads as "ok".
                prev = base_vals[-1]
                if direction == "higher" and prev > baseline * (1.0 + tol):
                    baseline = prev
                elif direction == "lower" and prev < baseline * (1.0 - tol):
                    baseline = prev
                f["baseline"] = baseline
                f["tolerance"] = tol
                if baseline != 0:
                    f["delta"] = value / baseline - 1.0
                else:
                    f["delta"] = 0.0 if value == 0 else float("inf")
                regressed = False
                if direction == "higher":
                    regressed = value < baseline * (1.0 - tol)
                elif direction == "lower":
                    if baseline == 0:
                        # a metric that has been 0 (lint findings, give-
                        # ups) regresses on ANY appearance
                        regressed = value > 0
                    else:
                        regressed = value > baseline * (1.0 + tol)
                else:  # equal: contract metrics (compile counts, dryrun ok)
                    regressed = value != baseline
                if regressed:
                    f["status"] = "REGRESSION"
                    over = abs(f["delta"]) if f["delta"] not in (None,) \
                        else 1.0
                    f["severity"] = (over / tol) if tol else float("inf")
                else:
                    f["status"] = "ok"
            findings.append(f)
    findings.sort(key=lambda f: (-f["severity"], f["series"], f["metric"]))
    return findings


def build_table(findings, verbose=False):
    rows = [f for f in findings
            if verbose or f["status"] == "REGRESSION"] or findings
    lines = [f"{'status':<11} {'series':<10} {'metric':<24} {'round':>5} "
             f"{'value':>12} {'baseline':>12} {'delta':>8} {'tol':>7}"]
    lines.append("-" * 96)
    for f in rows:
        base = "-" if f["baseline"] is None else f"{f['baseline']:g}"
        delta = "-" if f["delta"] is None else f"{100 * f['delta']:+.1f}%"
        tol = "-" if f["tolerance"] is None else f"{100 * f['tolerance']:.0f}%"
        lines.append(f"{f['status']:<11} {f['series']:<10} "
                     f"{f['metric']:<24} {f['round']:>5} {f['value']:>12g} "
                     f"{base:>12} {delta:>8} {tol:>7}")
    return "\n".join(lines)


def _parse_inject(spec):
    """``series:metric=factor`` → (series, metric, factor)."""
    m = re.match(r"^(\w+):([\w.]+)=([-+0-9.eE]+)$", spec)
    if m is None:
        raise ValueError(f"bad --inject spec {spec!r} "
                         f"(want series:metric=factor)")
    return m.group(1), m.group(2), float(m.group(3))


def inject_round(series, target, metric, factor):
    """Append a synthetic next round scaling ``metric`` by ``factor``
    (other metrics copied forward) — the detection self-test."""
    if target not in series or not series[target]:
        raise ValueError(f"no history for series {target!r}")
    rounds = series[target]
    last_round, last = rounds[-1]
    if metric not in last:
        raise ValueError(f"metric {metric!r} absent from {target} "
                         f"round {last_round}")
    synth = {k: (v * factor if k == metric else v, d)
             for k, (v, d) in last.items()}
    series = dict(series)
    series[target] = rounds + [(last_round + 1, synth)]
    return series


def run_check(series, args, label=""):
    findings = compare(series, window=args.window, rel_tol=args.rel_tol,
                       noise_k=args.noise_k, noise_cap=args.noise_cap)
    regressions = [f for f in findings if f["status"] == "REGRESSION"]
    tag = f" [{label}]" if label else ""
    print(f"bench sentinel{tag}: {len(findings)} metrics across "
          f"{len(series)} series — {len(regressions)} regression(s)")
    print(build_table(findings, verbose=args.verbose))
    return findings, regressions


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="directory holding the *_r*.json history "
                         "(default: the repo root above tools/)")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing rounds in the baseline median")
    ap.add_argument("--rel-tol", type=float, default=DEFAULT_REL_TOL,
                    help="minimum relative tolerance before flagging")
    ap.add_argument("--noise-k", type=float, default=1.0,
                    help="multiplier on the robust history noise")
    ap.add_argument("--noise-cap", type=float, default=DEFAULT_NOISE_CAP,
                    help="upper bound on the noise term")
    ap.add_argument("--inject", action="append", default=[],
                    metavar="SERIES:METRIC=FACTOR",
                    help="append a synthetic round with METRIC scaled by "
                         "FACTOR (detection self-test); repeatable")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: real history must be clean AND an "
                         "injected 25%% tokens/sec drop must be flagged")
    ap.add_argument("--json", default=None,
                    help="also dump the findings to this JSON file")
    ap.add_argument("--verbose", action="store_true",
                    help="list non-regressed metrics too")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    series = load_series(root)
    if not series:
        print(f"no *_r*.json bench history under {root}", file=sys.stderr)
        return 2

    for spec in args.inject:
        series = inject_round(series, *_parse_inject(spec))

    findings, regressions = run_check(series, args)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(findings, f, indent=1)
            f.write("\n")

    if args.smoke:
        if regressions:
            print("SMOKE FAIL: checked-in history flagged as regressed",
                  file=sys.stderr)
            return 1
        # detection self-test: a 25% tokens/sec drop on every series that
        # carries the metric MUST be flagged. 25% because tolerance can
        # legitimately widen up to --noise-cap (20%) on a jittery
        # history; the injection has to clear the widest legal band or
        # the self-test fails exactly when a big improvement just landed
        tested = 0
        for name in series:
            if "tokens_per_sec" not in series[name][-1][1]:
                continue
            if len(series[name]) < 2:
                continue  # single-round series can't regress yet
            tested += 1
            injected = inject_round(series, name, "tokens_per_sec", 0.75)
            _, regs = run_check(injected, args, label=f"inject {name} -25%")
            if not any(r["metric"] == "tokens_per_sec"
                       and r["series"] == name for r in regs):
                print(f"SMOKE FAIL: injected 25% {name} tokens/sec drop "
                      f"was NOT flagged", file=sys.stderr)
                return 1
        if not tested:
            print("SMOKE FAIL: no multi-round tokens/sec series to "
                  "self-test against", file=sys.stderr)
            return 1
        print(f"SMOKE OK: history clean; injected-drop detection verified "
              f"on {tested} series")
        return 0

    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
