#!/usr/bin/env bash
# Sharded CPU test run (round-5 VERDICT item 9: one -x failure late in a
# cold serial run costs half an hour).
#
#   tools/run_tests.sh            # sharded across 4 workers (~3x faster cold)
#   tools/run_tests.sh -n 8      # custom worker count / extra pytest args
#
# --dist loadfile keeps every test file on one worker: the launch/elastic
# tests spawn their own 2-process jobs and the per-file jax fixtures
# (virtual 8-device CPU mesh, persistent compile cache keyed by host CPU)
# stay coherent. The persistent XLA:CPU cache in /tmp/jax_pt_cache_* is
# shared across workers and across runs — a warm sharded run is ~3 min.
set -euo pipefail
cd "$(dirname "$0")/.."

ARGS=("$@")
if [[ ! " ${ARGS[*]-} " =~ " -n " ]]; then
  ARGS=(-n 4 "${ARGS[@]-}")
fi

PYTHONPATH="/root/.axon_site:$(pwd)${PYTHONPATH:+:$PYTHONPATH}" \
  exec python -m pytest tests/ -q -p no:cacheprovider \
    --dist loadfile "${ARGS[@]}"
