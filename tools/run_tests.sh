#!/usr/bin/env bash
# Test-suite runner (round-5 VERDICT item 9).
#
#   tools/run_tests.sh          # full suite, serial
#   tools/run_tests.sh --fast   # skip the table-driven sweeps + spawned
#                               # multi-process jobs: warm < 10 min
#   tools/run_tests.sh --slow   # ONLY the sweeps + multi-process jobs
#                               # (the --fast complement; fast ∪ slow = full)
#
# Why serial: this suite is COMPILE-dominated and per-process jit caches
# don't share — measured on the 8-core pool host, pytest-xdist made it
# SLOWER (warm: 20:42 @ -n4 loadfile vs 15:40 serial; cold: 36:01 @ -n4
# worksteal vs ~24 min serial) because workers race to compile the same
# executables 4x. The fast/slow split is the useful shard: run --fast for
# the quick signal, --slow in a second (or later) job.
#
# The persistent XLA:CPU compile cache (/tmp/jax_pt_cache_*, keyed by host
# CPU flags — see tests/conftest.py) is what makes warm runs fast; if a
# run SIGABRTs mid-suite after a pool-machine change, rm -rf the cache.
set -euo pipefail
cd "$(dirname "$0")/.."

# the sweep files re-check every op-table entry (fp32 FD + bf16/fp16),
# the launch/elastic files spawn real 2-process jobs, and the deep
# parallelism files (ring attention / 1F1B pipeline / per-tick RNG) carry
# the heaviest mesh compiles — together they are the bulk of wall-time
# (measured --durations=25: sequence_parallel ~194 s, pipeline ~104 s)
SLOW_FILES=(
  tests/test_op_grad_sweep.py
  tests/test_op_grad_sweep_lowp.py
  tests/test_static_parity_sweep.py
  tests/test_launch_multiprocess.py
  tests/test_native_core.py
  tests/test_sequence_parallel.py
  tests/test_pipeline_schedule.py
  tests/test_rng_dropout.py
)

MODE="full"
ARGS=()
for a in "$@"; do
  case "$a" in
    --fast) MODE="fast" ;;
    --slow) MODE="slow" ;;
    *) ARGS+=("$a") ;;
  esac
done

PY=(python -m pytest -q -p no:cacheprovider)
# the axon TPU-tunnel site dir only exists on pool hosts; gate it so the
# runner stays portable (AXON_SITE_DIR overrides the default location)
AXON_SITE="${AXON_SITE_DIR:-/root/.axon_site}"
if [[ -d "$AXON_SITE" ]]; then
  export PYTHONPATH="$AXON_SITE:$(pwd)${PYTHONPATH:+:$PYTHONPATH}"
else
  export PYTHONPATH="$(pwd)${PYTHONPATH:+:$PYTHONPATH}"
fi

# telemetry exporter smoke (full/fast paths): enable the runtime telemetry
# registry, push a few spans through the LogWriter JSONL exporter, and
# render the phase table with tools/telemetry_report.py — CI exercises the
# whole export chain even when no test touches it
if [[ "$MODE" != "slow" ]]; then
  SMOKE_DIR="$(mktemp -d /tmp/pt_telemetry_smoke.XXXXXX)"
  JAX_PLATFORMS=cpu python - "$SMOKE_DIR" <<'PYEOF'
import sys, time
from paddle_tpu.profiler import telemetry
from paddle_tpu.utils.log_writer import LogWriter

telemetry.reset()
telemetry.enable()
tm = telemetry.get_telemetry()
for _ in range(3):
    telemetry.step_begin()
    for phase in telemetry.PHASES:
        with telemetry.phase_span(phase):
            time.sleep(0.001)
telemetry.step_end()
tm.inc("smoke.batches", 3)
tm.set_gauge("device_loader.queue_depth", 2)
with LogWriter(sys.argv[1], file_name="telemetry_smoke.jsonl") as w:
    tm.export_scalars(w, step=3)
telemetry.disable()
PYEOF
  python tools/telemetry_report.py "$SMOKE_DIR/telemetry_smoke.jsonl"
  # devprof smoke: compile a tiny train step with telemetry on (triggering
  # the auto-harvest of memory/cost/comm ground truth), run it through the
  # bench measurement path, assert the BENCH telemetry_block carries the
  # new device keys, export the scalars, and render the ranked HBM/comm
  # table with the stdlib-only tools/mem_report.py
  JAX_PLATFORMS=cpu python - "$SMOKE_DIR" <<'PYEOF'
import sys
import numpy as np
sys.path.insert(0, "tools")
from bench_common import measure_steps, telemetry_block
import paddle_tpu as paddle
from paddle_tpu.jit.functionalize import CompiledStep
from paddle_tpu.profiler import devprof, telemetry
from paddle_tpu.utils.log_writer import LogWriter

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                           paddle.nn.Linear(32, 16))
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
def train_step(x, y):
    loss = ((net(x) - y) ** 2).mean()
    loss.backward(); opt.step(); opt.clear_grad()
    return loss
step = CompiledStep(train_step, stateful=[net, opt])
rng = np.random.RandomState(0)
batches = [(rng.rand(8, 16).astype("float32"),
            rng.rand(8, 16).astype("float32")) for _ in range(8)]
total, _ = measure_steps(step, batches, iters=4, warmup=2)
blk = telemetry_block(total, 4)
assert blk.get("hbm_peak_bytes"), f"missing hbm_peak_bytes: {blk}"
assert blk.get("comm_fraction") is not None, f"missing comm_fraction: {blk}"
rep = devprof.get_report("train_step")
assert rep is not None and rep.memory.peak_bytes > 0
with LogWriter(sys.argv[1], file_name="devprof_smoke.jsonl") as w:
    telemetry.get_telemetry().export_scalars(w, step=4)
PYEOF
  python tools/mem_report.py "$SMOKE_DIR/devprof_smoke.jsonl"
  # graph-lint gate: statically lint the bench-zoo train steps (resnet +
  # bert, no device execution) plus the serving tier's batched decode
  # and speculative-verify steps — any error-severity finding (a
  # state-pytree retrace hazard, or a kv-cache-concat/shape-churn finding
  # on either serving step, which must be shape-stable across positions
  # and acceptance patterns) fails the runner via exit status
  JAX_PLATFORMS=cpu python tools/graph_lint.py \
    --models resnet bert serve-decode serve-verify \
    --jsonl "$SMOKE_DIR/graph_lint.jsonl"
  # shard-lint gate (ISSUE 7 + 14): abstract SPMD propagation over the
  # MULTICHIP zoo — the dp×mp + MoE + dp-zero (ZeRO sharded update)
  # configs must lint with zero error findings AND the predicted per-axis
  # collective bytes must agree with the compiled-HLO measurement
  # (--measure; exit 1 on either; dp-zero also proves the deliberate
  # param all-gather is a declared reshard, not an implicit one), while
  # the injected mismatched-constraint fixture MUST be flagged (exit 1)
  JAX_PLATFORMS=cpu python tools/shard_lint.py --models dp-mp moe dp-zero \
    --measure --jsonl "$SMOKE_DIR/shard_lint.jsonl"
  if JAX_PLATFORMS=cpu python tools/shard_lint.py --models dp-mp \
      --fixture mismatched-constraint > /dev/null 2>&1; then
    echo "shard_lint missed the mismatched-constraint fixture" >&2; exit 1
  fi
  # mem-lint gate (ISSUE 12 + 15 + 18): fusion-aware per-eqn liveness
  # over the zoo — the clean configs (incl. the blockwise longctx train
  # step, the chunked-prefill serving step, and the now-measurable
  # dp-plain/dp-zero steps) must lint with zero errors AND the predicted
  # HBM peak must agree with compiled.memory_analysis() within the
  # ratcheted MEM_RTOL=0.10 (+64 KiB atol) band (--measure, never
  # under-predicting beyond it); the undonated long-context fixture MUST
  # be flagged over its injected budget (exit 1); the longctx config
  # must FIT a synthetic capacity that the einsum path
  # (--disable-blockwise) must BLOW on the same shapes; the
  # selective-remat planner must get the predicted peak under its budget
  # (--fixture remat-plan, exit 0); and the fusion A/B leg
  # (--fixture fusion-ab) must show the fusion simulation eliding
  # temporaries without dipping under the donated-state floor;
  # --smoke runs every leg
  JAX_PLATFORMS=cpu python tools/mem_lint.py --smoke
  # ZeRO dp-parity gate (ISSUE 14): the dp=2 sharded-update smoke bench
  # must hold loss parity against replicated Adam (--parity asserts it),
  # cut per-replica optimizer-state bytes ~dp-fold, and emit comm
  # telemetry (comm_fraction + comm.bytes.dp) for the bench artifact
  python bench.py --dp 2 --zero --parity \
    --artifact "$SMOKE_DIR/zero_bench.json"
  python - "$SMOKE_DIR/zero_bench.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["parity"]["max_rel"] < 1e-5, doc["parity"]
sb = doc["state_bytes"]
assert sb["ratio"] and sb["ratio"] > 1.9, sb
tel = doc["telemetry"]
assert tel.get("comm_fraction") is not None, tel
assert tel.get("comm_bytes_by_axis", {}).get("dp"), tel
PYEOF
  # serving smoke (tiny gpt, CPU): continuous batching vs sequential
  # decode through the static KV cache, speculative decoding + chunked
  # prefill ON (ISSUE 13 defaults); bench_serve --smoke hard-asserts the
  # telemetry contract — serve.tokens_per_s / serve.p95_latency_s
  # present, decode/verify/chunk each compiled EXACTLY once, prefill <=
  # once per length bucket, recompile_count 0 against the declared
  # variants, speculation actually engaged, zero shape-churn findings
  JAX_PLATFORMS=cpu python tools/bench_serve.py --smoke \
    --artifact "$SMOKE_DIR/serve_smoke.json"
  # long-prompt serving leg (ISSUE 15): 4x max_len/buckets, every prompt
  # in the top bucket, blockwise cached attention forced on at smoke
  # scale — the SAME telemetry contract must hold on the blockwise route
  JAX_PLATFORMS=cpu python tools/bench_serve.py --smoke --long-prompt \
    --artifact "$SMOKE_DIR/serve_smoke_longprompt.json"
  # serving chaos gate (ISSUE 10 + 13): flood the scheduler (speculation
  # + chunked prefill ON) under injected OOM/transient-error/stall plus
  # draft and mid-verify faults, and hard-assert the resilience contract
  # — every request ends with exactly one terminal finish_reason,
  # survivors match the PLAIN-GREEDY clean run token-for-token, verify
  # faults degrade to plain ticks, the overload SLOs page, and
  # post-chaos throughput recovers to >=90%
  JAX_PLATFORMS=cpu python tools/chaos_serve.py --smoke
  # checkpoint-doctor smoke: write two CheckpointManager steps (one torn
  # via fault injection), then exercise the verify/inspect/prune CLI —
  # verify MUST flag the torn step (exit 1) and pass the intact one
  JAX_PLATFORMS=cpu python - "$SMOKE_DIR/ckpt" <<'PYEOF'
import sys
import numpy as np
from paddle_tpu.fault import CheckpointManager, inject

m = CheckpointManager(sys.argv[1])
m.save(1, {"model": {"w": np.arange(8, dtype=np.float32)},
           "cursor": {"epoch": 0, "step": 1}})
inject.arm("torn", "ckpt.write", at=1)
m.save(2, {"model": {"w": np.ones(8, np.float32)},
           "cursor": {"epoch": 0, "step": 2}})
inject.disarm_all()
assert m.verify(2), "torn injection failed to corrupt step 2"
assert m.load()[0] == 1, "fallback to verified step 1 failed"
PYEOF
  if python tools/ckpt_doctor.py verify "$SMOKE_DIR/ckpt"; then
    echo "ckpt_doctor verify missed the torn checkpoint" >&2; exit 1
  fi
  python tools/ckpt_doctor.py verify "$SMOKE_DIR/ckpt" --step 1
  python tools/ckpt_doctor.py inspect "$SMOKE_DIR/ckpt" --step 1
  python tools/ckpt_doctor.py prune "$SMOKE_DIR/ckpt" --keep 1 --dry-run
  # /metrics scrape round-trip (ISSUE 8): populate the registry with
  # serve.*/step.* families, stand the OpenMetrics endpoint up on an
  # ephemeral port, scrape it over HTTP with the stdlib parser, and
  # assert the known families (incl. histogram _count/_sum via the
  # summary family) survived the render→serve→parse round trip
  JAX_PLATFORMS=cpu python - <<'PYEOF'
import sys, time
sys.path.insert(0, "tools")
import metrics_scrape
from paddle_tpu.profiler import telemetry

telemetry.reset()
telemetry.enable()
tm = telemetry.get_telemetry()
telemetry.step_begin()
for phase in telemetry.PHASES:
    with telemetry.phase_span(phase):
        time.sleep(0.001)
telemetry.step_end()
tm.inc("serve.decode_steps", 7)
tm.set_gauge("serve.queue_depth", 3)
for v in (0.05, 0.1, 0.2):
    tm.observe("serve.ttft_s", v)
srv = telemetry.serve_metrics(port=0)
try:
    rc = metrics_scrape.main([
        srv.url,
        "--assert-family", "serve_decode_steps",
        "--assert-family", "serve_queue_depth",
        "--assert-family", "serve_ttft_s",
        "--assert-family", "step_time_s",
        "--assert-family", "phase_dispatch",
    ])
    assert rc == 0, "metrics scrape round trip failed"
    fams = metrics_scrape.parse_openmetrics(metrics_scrape.fetch(srv.url))
    count = metrics_scrape.sample_value(fams, "serve_ttft_s",
                                        "serve_ttft_s_count")
    total = metrics_scrape.sample_value(fams, "serve_ttft_s",
                                        "serve_ttft_s_sum")
    assert count == 3 and abs(total - 0.35) < 1e-9, (count, total)
finally:
    srv.close()
    telemetry.disable()
    telemetry.reset()
PYEOF
  # bench-history regression sentinel (ISSUE 8): the checked-in
  # BENCH/SERVE/MULTICHIP rounds must pass the noise-aware baseline
  # check, and an injected 25% tokens/sec drop MUST be flagged (clears
  # the 20% noise-cap so a jittery history can't absorb the self-test)
  python tools/bench_sentinel.py --smoke
  rm -rf "$SMOKE_DIR"
fi

# Run pytest with a single retry-on-crash (PR 7 HOST NOTE): this pool host
# intermittently SIGABRTs/segfaults inside XLA:CPU dispatch mid-suite. A
# crash exit (rc >= 128) with NO test failures recorded in the log is that
# host flake, not a red suite — re-run once before reporting red. A run
# with real failures (or a second crash) still exits nonzero.
run_pytest() {
  local log rc
  log="$(mktemp /tmp/pt_pytest_run.XXXXXX.log)"
  set +e
  "${PY[@]}" "$@" 2>&1 | tee "$log"
  rc=${PIPESTATUS[0]}
  set -e
  if (( rc >= 128 )) && \
      ! grep -qaE '^(FAILED|ERROR)[ :]|[0-9]+ (failed|errors?)' "$log"; then
    echo "run_tests.sh: pytest crashed (rc=$rc) with no test failures in" \
         "the log — retrying once (intermittent XLA dispatch crash on this" \
         "pool host; see the PR 7 HOST NOTE)" >&2
    set +e
    "${PY[@]}" "$@" 2>&1 | tee "$log"
    rc=${PIPESTATUS[0]}
    set -e
  fi
  rm -f "$log"
  return "$rc"
}

case "$MODE" in
  full)
    run_pytest tests/ "${ARGS[@]:-}"
    ;;
  fast)
    IGNORES=()
    for f in "${SLOW_FILES[@]}"; do IGNORES+=("--ignore=$f"); done
    run_pytest tests/ "${IGNORES[@]}" "${ARGS[@]:-}"
    ;;
  slow)
    run_pytest "${SLOW_FILES[@]}" "${ARGS[@]:-}"
    ;;
esac
