"""ResNet-50 train-step throughput on one TPU chip (BASELINE.md configs 2/4).

Prints ONE JSON line {"metric", "value", "unit", ...} and (on TPU) writes
``RESNET_r05.json`` at the repo root.

Recipe: ImageNet-shape synthetic data (224x224), bf16 compute with fp32
batch-norm statistics, NHWC convolutions via layout autotune (the TPU conv
units natively consume channels-last; XLA folds the interior transposes of
back-to-back convs), SGD+momentum. Reference capability: the fleet ResNet
configs under ``reference/python/paddle/fluid/tests/unittests/collective/``
and the op-perf gate in ``tools/ci_op_benchmark.sh``.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python tools/bench_resnet.py
       [--batch N] [--iters N] [--no-artifact]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from bench_common import (  # noqa: E402
    compiled_flops,
    device_peak,
    emit,
    measure_steps,
    telemetry_block,
    retry,
)


def _run(batch=None, iters=None, artifact=True):
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch = batch or 128
        size, classes = 224, 1000
        iters = iters or 10
    else:  # smoke-scale for CPU verification runs
        batch = batch or 4
        size, classes = 32, 10
        iters = iters or 3

    paddle.seed(0)
    paddle.incubate.autotune.set_config({"layout": {"enable": True}})
    model = resnet50(num_classes=classes)
    if on_tpu:
        model.to(dtype="bfloat16")
        # batch-norm statistics/affine stay fp32 for numerical stability
        # (same policy as the GPT bench's fp32 layernorms)
        for _, sub in model.named_sublayers():
            if type(sub).__name__.startswith("BatchNorm"):
                sub.to(dtype="float32")
    opt = paddle.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=model.parameters(),
        weight_decay=1e-4, use_nesterov=False,
        multi_precision=on_tpu,
    )

    def train_step(images, labels):
        logits = model(images)
        loss = F.cross_entropy(logits.astype("float32"),
                               labels, reduction="mean")
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True)

    # distinct, time-seeded data per step (see bench_common docstring)
    rng = np.random.RandomState(int.from_bytes(os.urandom(4), "little"))
    dtype = np.float32
    batches = []
    for _ in range(3 + iters):
        img = rng.randn(batch, 3, size, size).astype(dtype)
        lab = rng.randint(0, classes, (batch, 1)).astype(np.int64)
        batches.append((Tensor(jax.numpy.asarray(img).astype(
            "bfloat16" if on_tpu else "float32")), Tensor(lab)))

    total, _ = measure_steps(step, batches, iters)
    images_per_sec = batch * iters / total
    telemetry = telemetry_block(total, iters)

    kind, peak = device_peak()
    flops = compiled_flops(step, batches)
    hfu = (flops * images_per_sec / batch / peak) if (flops and peak) else None
    # analytic model FLOPs: ResNet-50 fwd = 4.09 GMACs @224^2 (8.18 GFLOPs in
    # mul+add counting); train step ~= 3x fwd
    mfu_analytic = (3 * 2 * 4.089e9 * images_per_sec / peak) if peak else None

    emit({
        "metric": f"resnet50 train throughput ({backend})",
        "value": round(images_per_sec, 1),
        "unit": "images/sec/chip",
        "batch": batch,
        "image_size": size,
        "device_kind": kind,
        "step_flops": flops,
        "hw_flops_util": round(hfu, 4) if hfu else None,
        "mfu_analytic": round(mfu_analytic, 4) if mfu_analytic else None,
        "telemetry": telemetry,
    }, artifact="RESNET_r05.json" if (on_tpu and artifact) else None)
    return images_per_sec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--no-artifact", action="store_true")
    a = ap.parse_args()
    retry(lambda: _run(a.batch, a.iters, artifact=not a.no_artifact))


if __name__ == "__main__":
    main()
