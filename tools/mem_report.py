#!/usr/bin/env python
"""Ranked HBM/communication report from a telemetry JSONL.

Reads the scalar stream written by ``profiler.telemetry.export_scalars``
(via ``utils.log_writer.LogWriter`` — e.g. from the
``hapi.callbacks.DeviceStatsLogger`` callback, or any run with telemetry on
after ``profiler.devprof`` harvested a compiled step) and renders the
device-side ground truth:

* the HBM peak broken into argument/output/temp/generated-code segments,
  ranked largest first with percent-of-peak;
* per-mesh-axis collective traffic (``comm.bytes.<axis>`` /
  ``comm.count.<axis>``), ranked by bytes, plus the comm-vs-compute
  fraction;
* compiled cost figures (FLOPs, bytes accessed) and pipeline-schedule
  metrics when present;
* the serving tier (``serve.*`` gauges/counters and latency/TTFT
  histograms) when the run served requests.

Usage::

    python tools/mem_report.py <vdlrecords.jsonl | logdir>

Stdlib-only on purpose: the CI smoke path (tools/run_tests.sh) runs it
without importing jax (mirrors tools/telemetry_report.py / ckpt_doctor.py).
"""
from __future__ import annotations

import glob
import json
import os
import sys

HBM_ORDER = ("argument_bytes", "output_bytes", "temp_bytes",
             "generated_code_bytes")


def load_records(path):
    """Parse one JSONL file (or the newest ``*.jsonl`` in a directory)."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl")),
                       key=os.path.getmtime)
        if not files:
            raise FileNotFoundError(f"no *.jsonl files under {path}")
        path = files[-1]
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # tolerate partial trailing writes
    return path, records


def collect(records):
    """Fold the scalar stream to last-value per tag; split out the device
    stats (telemetry/gauge/hbm.* etc. and telemetry/counter/comm.*)."""
    last = {}
    for r in records:
        tag, value = r.get("tag"), r.get("value")
        if isinstance(tag, str) and value is not None:
            last[tag] = float(value)
    hbm = {t[len("telemetry/gauge/hbm."):]: v for t, v in last.items()
           if t.startswith("telemetry/gauge/hbm.")}
    cost = {t[len("telemetry/gauge/cost."):]: v for t, v in last.items()
            if t.startswith("telemetry/gauge/cost.")}
    pipeline = {t[len("telemetry/gauge/pipeline."):]: v
                for t, v in last.items()
                if t.startswith("telemetry/gauge/pipeline.")}
    comm_gauges = {t[len("telemetry/gauge/comm."):]: v
                   for t, v in last.items()
                   if t.startswith("telemetry/gauge/comm.")}
    comm_bytes = {t[len("telemetry/counter/comm.bytes."):]: v
                  for t, v in last.items()
                  if t.startswith("telemetry/counter/comm.bytes.")}
    comm_count = {t[len("telemetry/counter/comm.count."):]: v
                  for t, v in last.items()
                  if t.startswith("telemetry/counter/comm.count.")}
    # serving tier: serve.* gauges/counters plus the latency histograms
    # (telemetry/hist/serve.<name>/<field>)
    serve = {}
    for prefix, kind in (("telemetry/gauge/serve.", "gauge"),
                         ("telemetry/counter/serve.", "counter")):
        for t, v in last.items():
            if t.startswith(prefix):
                serve[t[len(prefix):]] = v
    serve_hists = {}
    for t, v in last.items():
        if t.startswith("telemetry/hist/serve."):
            name, _, field = t[len("telemetry/hist/serve."):].rpartition("/")
            serve_hists.setdefault(name, {})[field] = v
    return hbm, cost, pipeline, comm_gauges, comm_bytes, comm_count, \
        serve, serve_hists


def human_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{int(n)} B" if unit == "B" else f"{n:.2f} {unit}"
        n /= 1024.0


def build_report(hbm, cost, pipeline, comm_gauges, comm_bytes, comm_count,
                 serve=None, serve_hists=None):
    lines = []
    if hbm:
        peak = hbm.get("peak_bytes") or 1.0
        lines.append(f"HBM peak: {human_bytes(peak)}")
        lines.append(f"  {'segment':<24} {'bytes':>14} {'% of peak':>10}")
        lines.append("  " + "-" * 50)
        segs = [(k, hbm.get(k, 0.0)) for k in HBM_ORDER]
        for k, v in sorted(segs, key=lambda kv: -kv[1]):
            if v:
                lines.append(f"  {k:<24} {human_bytes(v):>14} "
                             f"{100.0 * v / peak:>9.1f}%")
        alias = hbm.get("alias_bytes", 0.0)
        if alias:
            lines.append(f"  {'alias (donated, reused)':<24} "
                         f"{'-' + human_bytes(alias):>14}")
        if hbm.get("alias_unavailable"):
            lines.append("  alias term unavailable (persistent-cache "
                         "executable): peak over-counts donated arguments")
    if cost:
        lines.append("compiled cost:")
        if cost.get("flops"):
            lines.append(f"  {'flops':<24} {cost['flops']:>14,.0f}")
        if cost.get("bytes_accessed"):
            lines.append(f"  {'bytes accessed':<24} "
                         f"{human_bytes(cost['bytes_accessed']):>14}")
        if cost.get("optimal_seconds"):
            lines.append(f"  {'optimal seconds':<24} "
                         f"{cost['optimal_seconds']:>14.6f}")
    if comm_bytes or comm_gauges:
        frac = comm_gauges.get("fraction")
        total = comm_gauges.get("bytes", sum(comm_bytes.values()))
        lines.append(f"collective traffic: {human_bytes(total)} "
                     f"moved/device"
                     + (f", comm_fraction {frac:.4f}" if frac is not None
                        else ""))
        if comm_bytes:
            lines.append(f"  {'mesh axis':<16} {'bytes':>14} {'ops':>6}")
            lines.append("  " + "-" * 38)
            for axis, v in sorted(comm_bytes.items(), key=lambda kv: -kv[1]):
                n = int(comm_count.get(axis, 0))
                lines.append(f"  {axis:<16} {human_bytes(v):>14} {n:>6}")
    if pipeline:
        lines.append("pipeline schedule:")
        for k in sorted(pipeline):
            lines.append(f"  {k:<24} {pipeline[k]:g}")
    if serve or serve_hists:
        lines.append("serving:")
        for k in sorted(serve or {}):
            v = serve[k]
            lines.append(f"  serve.{k:<24} "
                         f"{int(v) if v == int(v) else round(v, 6):g}")
        for name in sorted(serve_hists or {}):
            h = serve_hists[name]
            count = int(h.get("count", 0))
            lines.append(
                f"  serve.{name:<24} n={count} "
                f"mean={h.get('mean', 0.0) * 1e3:.1f}ms "
                f"p50={h.get('p50', 0.0) * 1e3:.1f}ms "
                f"p95={h.get('p95', 0.0) * 1e3:.1f}ms")
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    path, records = load_records(argv[0])
    parts = collect(records)
    if not any(parts):
        print(f"{path}: no device stats (hbm.*/comm.*/cost.*) found — "
              f"was the run harvested by profiler.devprof?",
              file=sys.stderr)
        return 1
    print(f"device memory/comm report — {path}")
    print(build_report(*parts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
