"""Vision transforms (reference ``python/paddle/vision/transforms/``). Numpy
(HWC uint8/float) based; run in dataloader workers on host, off the TPU."""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "ColorJitter", "Grayscale", "RandomRotation",
]


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    """HWC [0,255] uint8 -> CHW float32 [0,1] numpy (kept host-side)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        if a.dtype == np.uint8:
            a = a.astype(np.float32) / 255.0
        else:
            a = a.astype(np.float32)
        if self.data_format == "CHW":
            a = np.transpose(a, (2, 0, 1))
        return a


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            n = a.shape[0]
            return (a - self.mean[:n, None, None]) / self.std[:n, None, None]
        n = a.shape[-1]
        return (a - self.mean[:n]) / self.std[:n]


def _resize_np(a, size):
    """nearest-neighbor resize for HWC numpy (host-side, no PIL dependency)."""
    h, w = a.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    ri = (np.arange(nh) * h / nh).astype(int).clip(0, h - 1)
    ci = (np.arange(nw) * w / nw).astype(int).clip(0, w - 1)
    return a[ri][:, ci]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(np.asarray(img), self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        a = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            a = np.pad(a, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (a.ndim - 2))
        h, w = a.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return a[i : i + th, j : j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return a[i : i + th, j : j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return np.transpose(a, self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = [padding] * 4 if isinstance(padding, int) else list(padding)
        self.fill = fill

    def _apply_image(self, img):
        a = np.asarray(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        width = ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (a.ndim - 2)
        return np.pad(a, width, constant_values=self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3), interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            ar = random.uniform(*self.ratio)
            tw = int(round((target_area * ar) ** 0.5))
            th = int(round((target_area / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return _resize_np(a[i : i + th, j : j + tw], self.size)
        return _resize_np(a, self.size)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(a * f, 0, 255 if a.max() > 1 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = a.mean()
        return np.clip((a - mean) * f + mean, 0, 255 if a.max() > 1 else 1.0)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if a.ndim < 3 or a.shape[-1] == 1:
            return a
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = a.mean(axis=-1, keepdims=True)
        return np.clip(gray + (a - gray) * f, 0, 255 if a.max() > 1 else 1.0)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))

    def _apply_image(self, img):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        if a.ndim == 3 and a.shape[-1] >= 3:
            g = a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114
        else:
            g = a.reshape(a.shape[:2])
        g = g[:, :, None]
        return np.repeat(g, self.n, axis=-1) if self.n > 1 else g


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) else degrees

    def _apply_image(self, img):
        import scipy.ndimage as ndi

        a = np.asarray(img)
        angle = random.uniform(*self.degrees)
        return ndi.rotate(a, angle, reshape=False, order=1, mode="nearest")


from . import functional  # noqa: E402,F401
from .functional import (  # noqa: E402,F401
    to_tensor,
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    normalize,
    pad,
    perspective,
    resize,
    rotate,
    to_grayscale,
    vflip,
)


class HueTransform(BaseTransform):
    """reference HueTransform: random hue in [-value, value]."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, random.uniform(-self.value, self.value))


class RandomAffine(BaseTransform):
    """reference RandomAffine: random rotation/translate/scale/shear."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, numbers.Number) else degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale else 1.0
        sh = random.uniform(*self.shear) if self.shear else 0.0
        return affine(a, angle, (tx, ty), sc, sh,
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomErasing(BaseTransform):
    """reference RandomErasing (Cutout-style regularization)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img)
        if random.random() >= self.prob:
            return a
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return erase(a, i, j, eh, ew, self.value)
        return a


class RandomPerspective(BaseTransform):
    """reference RandomPerspective: random 4-corner perspective warp."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        a = np.asarray(img)
        if random.random() >= self.prob:
            return a
        h, w = a.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [[random.randint(0, dx), random.randint(0, dy)],
               [w - 1 - random.randint(0, dx), random.randint(0, dy)],
               [w - 1 - random.randint(0, dx), h - 1 - random.randint(0, dy)],
               [random.randint(0, dx), h - 1 - random.randint(0, dy)]]
        return perspective(a, start, end, interpolation=self.interpolation,
                           fill=self.fill)
