"""Functional image transforms (reference
``python/paddle/vision/transforms/functional*.py``).

Host-side numpy on HWC arrays (PIL images are converted) — the same
execution model as the reference's cv2/PIL backends: transforms are data
preparation that runs in DataLoader workers, never on the accelerator.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "to_grayscale", "hflip", "vflip", "normalize", "pad",
    "resize", "crop", "center_crop", "adjust_brightness", "adjust_contrast",
    "adjust_hue", "rotate", "affine", "perspective", "erase",
]


def _np_img(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def to_tensor(pic, data_format="CHW"):
    from ...framework.tensor import Tensor

    a = _np_img(pic).astype(np.float32)
    if a.dtype == np.uint8 or a.max() > 1.5:
        a = a / 255.0
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return Tensor(a)


def to_grayscale(img, num_output_channels=1):
    a = _np_img(img).astype(np.float32)
    if a.shape[-1] >= 3:
        g = a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114
    else:
        g = a[..., 0]
    g = g[:, :, None]
    return np.repeat(g, num_output_channels, axis=-1) \
        if num_output_channels > 1 else g


def hflip(img):
    return _np_img(img)[:, ::-1].copy()


def vflip(img):
    return _np_img(img)[::-1].copy()


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (a - mean[:, None, None]) / std[:, None, None]
    return (a - mean) / std


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _np_img(img)
    if isinstance(padding, numbers.Number):
        p = [padding] * 4
    elif len(padding) == 2:
        p = [padding[0], padding[1], padding[0], padding[1]]
    else:
        p = list(padding)
    widths = ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (a.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(a, widths, mode=mode, **kw)


def resize(img, size, interpolation="bilinear"):
    from . import _resize_np

    return _resize_np(_np_img(img), size)


def crop(img, top, left, height, width):
    return _np_img(img)[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    a = _np_img(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    th, tw = output_size
    h, w = a.shape[:2]
    return crop(a, max((h - th) // 2, 0), max((w - tw) // 2, 0), th, tw)


def adjust_brightness(img, brightness_factor):
    a = _np_img(img).astype(np.float32)
    out = a * brightness_factor
    return np.clip(out, 0, 255 if a.max() > 1.5 else 1.0).astype(
        np.asarray(img).dtype)


def adjust_contrast(img, contrast_factor):
    a = _np_img(img).astype(np.float32)
    mean = to_grayscale(a).mean()
    out = mean + contrast_factor * (a - mean)
    return np.clip(out, 0, 255 if a.max() > 1.5 else 1.0).astype(
        np.asarray(img).dtype)


def _rgb_to_hsv(a):
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    mx, mn = a.max(-1), a.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b) / diff)[m] % 6
    m = mx == g
    h[m] = ((b - r) / diff + 2)[m]
    m = mx == b
    h[m] = ((r - g) / diff + 4)[m]
    h = h / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return h, s, mx


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    out = np.zeros(h.shape + (3,), np.float32)
    conds = [(i == 0, (v, t, p)), (i == 1, (q, v, p)), (i == 2, (p, v, t)),
             (i == 3, (p, q, v)), (i == 4, (t, p, v)), (i == 5, (v, p, q))]
    for cond, (rr, gg, bb) in conds:
        out[..., 0][cond] = rr[cond]
        out[..., 1][cond] = gg[cond]
        out[..., 2][cond] = bb[cond]
    return out


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = _np_img(img).astype(np.float32)
    scale = 255.0 if a.max() > 1.5 else 1.0
    h, s, v = _rgb_to_hsv(a / scale)
    h = (h + hue_factor) % 1.0
    out = _hsv_to_rgb(h, s, v) * scale
    return out.astype(np.asarray(img).dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    import scipy.ndimage as ndi

    a = _np_img(img)
    return ndi.rotate(a, angle, reshape=bool(expand),
                      order=0 if interpolation == "nearest" else 1,
                      mode="constant", cval=fill)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """2-D affine (reference transforms.functional.affine): rotation +
    translation + scale + shear about the image center."""
    import scipy.ndimage as ndi

    a = _np_img(img)
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    ang = np.deg2rad(angle)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix (y, x) convention
    rot = np.array([[np.cos(ang), -np.sin(ang)],
                    [np.sin(ang), np.cos(ang)]])
    shr = np.array([[1.0, np.tan(sy)], [np.tan(sx), 1.0]])
    m = rot @ shr * scale
    minv = np.linalg.inv(m)
    offset = np.array([cy, cx]) - minv @ (
        np.array([cy, cx]) + np.array([translate[1], translate[0]]))
    order = 0 if interpolation == "nearest" else 1
    out = np.stack([
        ndi.affine_transform(a[..., c], minv, offset=offset, order=order,
                             mode="constant", cval=fill)
        for c in range(a.shape[-1])], axis=-1)
    return out


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping ``startpoints`` -> ``endpoints`` (reference
    transforms.functional.perspective); homography solved from the 4 point
    pairs, applied by inverse mapping."""
    import scipy.ndimage as ndi

    a = _np_img(img)
    # solve h such that endpoints = H(startpoints); we need the INVERSE map
    src = np.asarray(endpoints, np.float64)
    dst = np.asarray(startpoints, np.float64)
    A = []
    for (x, y), (u, v) in zip(src, dst):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    b = dst.reshape(-1)
    hvec = np.linalg.lstsq(np.asarray(A), b, rcond=None)[0]
    H = np.append(hvec, 1.0).reshape(3, 3)
    hgt, wid = a.shape[:2]
    ys, xs = np.meshgrid(np.arange(hgt), np.arange(wid), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float64)
    mapped = H @ coords
    mx = (mapped[0] / mapped[2]).reshape(hgt, wid)
    my = (mapped[1] / mapped[2]).reshape(hgt, wid)
    # snap fp solver noise: a -1e-15 coordinate would otherwise fall
    # "outside" the image and read the constant fill
    mx = np.where(np.abs(mx - np.round(mx)) < 1e-6, np.round(mx), mx)
    my = np.where(np.abs(my - np.round(my)) < 1e-6, np.round(my), my)
    order = 0 if interpolation == "nearest" else 1
    out = np.stack([
        ndi.map_coordinates(a[..., c], [my, mx], order=order,
                            mode="constant", cval=fill)
        for c in range(a.shape[-1])], axis=-1)
    return out


def erase(img, i, j, h, w, v, inplace=False):
    """Erase region [i:i+h, j:j+w] with value ``v`` (reference
    transforms.functional.erase). Accepts HWC numpy or CHW Tensor."""
    from ...framework.tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        arr = img._value
        val = jnp.broadcast_to(jnp.asarray(v, arr.dtype),
                               arr[..., i:i + h, j:j + w].shape)
        out = arr.at[..., i:i + h, j:j + w].set(val)
        return Tensor(out)
    a = _np_img(img).copy()
    a[i:i + h, j:j + w] = v
    return a
