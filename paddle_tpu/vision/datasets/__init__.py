"""Vision datasets (reference ``python/paddle/vision/datasets/``).

Zero-egress environment: datasets load from local files when present
(``~/.cache/paddle_tpu/datasets`` or explicit paths); otherwise MNIST and
Cifar fall back to a deterministic synthetic sample set so training loops and
tests run offline."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder", "ImageFolder"]

_CACHE = os.path.expanduser("~/.cache/paddle_tpu/datasets")


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    imgs = (rng.rand(n, *shape) * 255).astype(np.uint8)
    # make classes separable: add a class-dependent bright square
    side = shape[0] // 4
    for i, lab in enumerate(labels):
        r = (lab * 2) % (shape[0] - side)
        imgs[i, r : r + side, r : r + side] = 255 - (lab * 9) % 128
    return imgs, labels


class MNIST(Dataset):
    """reference ``python/paddle/vision/datasets/mnist.py`` (idx-ubyte files)."""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        images, labels = self._load(image_path, label_path, mode)
        self.images = images
        self.labels = labels

    def _load(self, image_path, label_path, mode):
        name = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(_CACHE, "mnist", f"{name}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(_CACHE, "mnist", f"{name}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            return images, labels
        n = 6000 if mode == "train" else 1000
        return _synthetic_images(n, (28, 28), 10, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None, :, :] / 255.0
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    def _load(self, image_path, label_path, mode):
        name = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(_CACHE, "fashion-mnist", f"{name}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(_CACHE, "fashion-mnist", f"{name}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            return super()._load(image_path, label_path, mode)
        n = 6000 if mode == "train" else 1000
        return _synthetic_images(n, (28, 28), 10, seed=2 if mode == "train" else 3)


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        path = data_file or os.path.join(_CACHE, "cifar10", f"{mode}.npz")
        if os.path.exists(path):
            d = np.load(path)
            imgs, labels = d["images"], d["labels"]
        else:
            n = 5000 if mode == "train" else 1000
            imgs, labels = _synthetic_images(n, (32, 32, 3), self.NUM_CLASSES, seed=4 if mode == "train" else 5)
        self.images, self.labels = imgs, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32) / 255.0, (2, 0, 1))
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """reference vision/datasets/folder.py — directory-per-class layout."""

    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        raise RuntimeError(
            f"no image decoder available for {path}; provide loader= or use .npy"
        )

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        self.samples = [
            os.path.join(root, f)
            for f in sorted(os.listdir(root))
            if f.lower().endswith(extensions)
        ]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)
