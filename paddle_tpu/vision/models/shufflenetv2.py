"""ShuffleNet V2 (reference
``python/paddle/vision/models/shufflenetv2.py``)."""
from __future__ import annotations

from ... import nn, ops

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]


def _channel_shuffle(x, groups):
    import paddle_tpu.nn.functional as F

    return F.channel_shuffle(x, groups)


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _InvertedResidual(nn.Layer):
    def __init__(self, c_in, c_out, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = c_out // 2
        if stride == 1:
            self.b2 = nn.Sequential(
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )
        else:
            self.b1 = nn.Sequential(
                nn.Conv2D(c_in, c_in, 3, stride=stride, padding=1,
                          groups=c_in, bias_attr=False),
                nn.BatchNorm2D(c_in),
                nn.Conv2D(c_in, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )
            self.b2 = nn.Sequential(
                nn.Conv2D(c_in, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act),
            )

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = ops.concat([x1, self.b2(x2)], axis=1)
        else:
            out = ops.concat([self.b1(x), self.b2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stage_repeats = [4, 8, 4]
        cfgs = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
                0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
                1.5: [24, 176, 352, 704, 1024],
                2.0: [24, 244, 488, 976, 2048]}
        ch = cfgs[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, ch[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(ch[0]), _act(act))
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        c_in = ch[0]
        for stage, reps in enumerate(stage_repeats):
            c_out = ch[stage + 1]
            for i in range(reps):
                blocks.append(_InvertedResidual(
                    c_in, c_out, stride=2 if i == 0 else 1, act=act))
                c_in = c_out
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(c_in, ch[-1], 1, bias_attr=False),
            nn.BatchNorm2D(ch[-1]), _act(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(ch[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.blocks(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
