"""GoogLeNet / Inception-v1 (reference
``python/paddle/vision/models/googlenet.py``)."""
from __future__ import annotations

from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


class _Inception(nn.Layer):
    def __init__(self, c_in, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(c_in, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(c_in, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(c_in, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(c_in, proj, 1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        return ops.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                          axis=1)


class GoogLeNet(nn.Layer):
    """Returns (main_logits, aux1_logits, aux2_logits) like the reference
    (aux heads active in train mode)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        a1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ... import ops

            x = ops.flatten(x, 1)
            x = self.fc(self.dropout(x))
            return x, a1, a2
        return x


class _AuxHead(nn.Layer):
    def __init__(self, c_in, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = nn.Conv2D(c_in, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.fc2 = nn.Linear(1024, num_classes)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)

    def forward(self, x):
        from ... import ops

        x = self.relu(self.conv(self.pool(x)))
        x = ops.flatten(x, 1)
        x = self.relu(self.fc1(x))
        return self.fc2(self.dropout(x))


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
