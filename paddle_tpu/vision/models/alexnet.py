"""AlexNet + SqueezeNet (reference ``python/paddle/vision/models/
{alexnet,squeezenet}.py``)."""
from __future__ import annotations

from ... import nn
from ...nn import functional as F

__all__ = ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
        )
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(dropout), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        x = F.adaptive_avg_pool2d(x, output_size=6)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(start_axis=1))
        return x


def alexnet(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights are not bundled")
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        from ... import ops

        x = F.relu(self.squeeze(x))
        return ops.concat([F.relu(self.expand1(x)), F.relu(self.expand3(x))],
                          axis=1)


class SqueezeNet(nn.Layer):
    """Reference ``squeezenet.py`` (version "1.0"/"1.1")."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError("version must be 1.0 or 1.1")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.stem = nn.Conv2D(3, 96, 7, stride=2)
            fires = [_Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                     _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256)]
            self._pool_after = {0: False, 2: True, 6: True}
        else:
            self.stem = nn.Conv2D(3, 64, 3, stride=2, padding=1)
            fires = [_Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                     _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256)]
            self._pool_after = {1: True, 3: True}
        self.fires = nn.LayerList(fires)
        self.final_conv = nn.Conv2D(512, num_classes, 1)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.stem(x)), kernel_size=3, stride=2)
        for i, fire in enumerate(self.fires):
            x = fire(x)
            if self._pool_after.get(i):
                x = F.max_pool2d(x, kernel_size=3, stride=2)
        x = F.relu(self.final_conv(x))
        if self.with_pool:
            x = F.adaptive_avg_pool2d(x, output_size=1)
        return x.flatten(start_axis=1)


def squeezenet1_0(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights are not bundled")
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights are not bundled")
    return SqueezeNet("1.1", **kw)
