"""MobileNet v1/v2/v3 (reference ``python/paddle/vision/models/mobilenetv1.py``,
``mobilenetv2.py``, ``mobilenetv3.py``)."""
from __future__ import annotations

from ... import nn

__all__ = [
    "MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small", "mobilenet_v3_large",
]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, act=nn.ReLU6):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            act(),
        )


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                ConvBNReLU(in_c, in_c, 3, stride, groups=in_c, act=nn.ReLU),
                ConvBNReLU(in_c, out_c, 1, 1, act=nn.ReLU),
            )

        s = lambda c: int(c * scale)
        self.features = nn.Sequential(
            ConvBNReLU(3, s(32), 3, 2, act=nn.ReLU),
            dw_sep(s(32), s(64), 1),
            dw_sep(s(64), s(128), 2),
            dw_sep(s(128), s(128), 1),
            dw_sep(s(128), s(256), 2),
            dw_sep(s(256), s(256), 1),
            dw_sep(s(256), s(512), 2),
            *[dw_sep(s(512), s(512), 1) for _ in range(5)],
            dw_sep(s(512), s(1024), 2),
            dw_sep(s(1024), s(1024), 1),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manipulation

            x = self.fc(manipulation.flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, 1))
        layers += [
            ConvBNReLU(hidden, hidden, 3, stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [
            (1, 16, 1, 1),
            (6, 24, 2, 2),
            (6, 32, 3, 2),
            (6, 64, 4, 2),
            (6, 96, 3, 1),
            (6, 160, 3, 2),
            (6, 320, 1, 1),
        ]
        input_channel = _make_divisible(32 * scale)
        layers = [ConvBNReLU(3, input_channel, 3, 2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(InvertedResidual(input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        layers.append(ConvBNReLU(input_channel, self.last_channel, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            from ...ops import manipulation

            x = self.classifier(manipulation.flatten(x, 1))
        return x


class SqueezeExcite(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, kernel, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(ConvBNReLU(in_c, exp, 1, act=act))
        layers.append(ConvBNReLU(exp, exp, kernel, stride, groups=exp, act=act))
        if se:
            layers.append(SqueezeExcite(exp))
        layers += [nn.Conv2D(exp, out_c, 1, bias_attr=False), nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, exp, out, se, act, stride
    (3, 16, 16, True, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1),
    (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1),
    (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2),
    (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]

_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1),
    (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1),
    (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1),
    (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2),
    (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1),
    (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2),
    (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [ConvBNReLU(3, in_c, 3, 2, act=nn.Hardswish)]
        for k, exp, out_c, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            o = _make_divisible(out_c * scale)
            layers.append(_MBV3Block(in_c, exp_c, o, k, s, se, act))
            in_c = o
        last_c = _make_divisible(last_exp * scale)
        layers.append(ConvBNReLU(in_c, last_c, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_c, 1280),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(1280, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manipulation

            x = self.classifier(manipulation.flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
