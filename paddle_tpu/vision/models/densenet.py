"""DenseNet (reference ``python/paddle/vision/models/densenet.py``)."""
from __future__ import annotations

from ... import nn
from ...nn import functional as F

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class _DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(num_channels)
        self.conv1 = nn.Conv2D(num_channels, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = dropout

    def forward(self, x):
        y = self.conv1(F.relu(self.bn1(x)))
        y = self.conv2(F.relu(self.bn2(y)))
        if self.dropout:
            y = F.dropout(y, p=self.dropout, training=self.training)
        from ... import ops

        return ops.concat([x, y], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_channels + i * growth_rate, growth_rate, bn_size,
                        dropout)
            for i in range(num_layers)
        ])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, num_channels, num_out):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_channels)
        self.conv = nn.Conv2D(num_channels, num_out, 1, bias_attr=False)

    def forward(self, x):
        x = self.conv(F.relu(self.bn(x)))
        return F.avg_pool2d(x, kernel_size=2, stride=2)


_CFGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    """Reference ``densenet.py DenseNet(layers=121, ...)``."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _CFGS:
            raise ValueError(f"layers must be one of {sorted(_CFGS)}")
        init_ch, growth, blocks = _CFGS[layers]
        self.stem_conv = nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                                   bias_attr=False)
        self.stem_bn = nn.BatchNorm2D(init_ch)
        ch = init_ch
        dense, trans = [], []
        for i, n in enumerate(blocks):
            dense.append(_DenseBlock(n, ch, growth, bn_size, dropout))
            ch += n * growth
            if i != len(blocks) - 1:
                trans.append(_Transition(ch, ch // 2))
                ch //= 2
        self.dense_blocks = nn.LayerList(dense)
        self.transitions = nn.LayerList(trans)
        self.final_bn = nn.BatchNorm2D(ch)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.stem_bn(self.stem_conv(x))
        x = F.max_pool2d(F.relu(x), kernel_size=3, stride=2, padding=1)
        for i, block in enumerate(self.dense_blocks):
            x = block(x)
            if i < len(self.transitions):
                x = self.transitions[i](x)
        x = F.relu(self.final_bn(x))
        if self.with_pool:
            x = F.adaptive_avg_pool2d(x, output_size=1)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(start_axis=1))
        return x


def _make(layers, pretrained=False, **kw):
    if pretrained:
        raise ValueError("pretrained weights are not bundled")
    return DenseNet(layers=layers, **kw)


def densenet121(pretrained=False, **kw):
    return _make(121, pretrained, **kw)


def densenet161(pretrained=False, **kw):
    return _make(161, pretrained, **kw)


def densenet169(pretrained=False, **kw):
    return _make(169, pretrained, **kw)


def densenet201(pretrained=False, **kw):
    return _make(201, pretrained, **kw)


def densenet264(pretrained=False, **kw):
    return _make(264, pretrained, **kw)
