"""paddle.vision equivalent."""
from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet  # noqa: F401
