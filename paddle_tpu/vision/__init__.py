"""paddle.vision equivalent."""
from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet  # noqa: F401

_IMAGE_BACKEND = ["pil"]


def set_image_backend(backend):
    """reference ``vision/image.py set_image_backend`` ('pil' | 'cv2')."""
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unknown image backend {backend!r}")
    _IMAGE_BACKEND[0] = backend


def get_image_backend():
    return _IMAGE_BACKEND[0]


def image_load(path, backend=None):
    """reference ``vision/image.py image_load``: returns a PIL image (or a
    cv2 ndarray when that backend is selected and installed)."""
    backend = backend or _IMAGE_BACKEND[0]
    if backend == "cv2":
        from ..utils import try_import

        cv2 = try_import("cv2")
        return cv2.imread(path)
    from PIL import Image

    return Image.open(path)
