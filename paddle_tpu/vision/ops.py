"""paddle.vision.ops — detection operators.

Reference: ``python/paddle/vision/ops.py`` (roi_align/roi_pool/nms/
deform_conv2d) backed by CUDA kernels under ``paddle/phi/kernels/gpu/``.
TPU-native: bilinear sampling expressed as gathers + weighted sums that XLA
vectorizes, vmapped over RoIs/kernel-offsets; greedy NMS as a
``lax.fori_loop`` over score-sorted boxes (sequential by definition)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, op

__all__ = ["roi_align", "roi_pool", "nms", "deform_conv2d", "DeformConv2D"]


def _bilinear(feat, y, x):
    """feat [C,H,W]; y,x arbitrary same-shape grids -> [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = jnp.clip(y - y0, 0.0, 1.0)
    wx = jnp.clip(x - x0, 0.0, 1.0)
    y0i, y1i, x0i, x1i = (a.astype(jnp.int32) for a in (y0, y1, x0, x1))

    def g(yy, xx):
        return feat[:, yy, xx]

    v = (g(y0i, x0i) * (1 - wy) * (1 - wx) + g(y0i, x1i) * (1 - wy) * wx
         + g(y1i, x0i) * wy * (1 - wx) + g(y1i, x1i) * wy * wx)
    # zero outside the feature map (reference behavior for OOB samples)
    inside = (y >= -1.0) & (y <= H) & (x >= -1.0) & (x <= W)
    return jnp.where(inside, v, 0.0)


@op("roi_align")
def _roi_align_raw(x, boxes, boxes_num, output_size=(1, 1), spatial_scale=1.0,
                   sampling_ratio=-1, aligned=True):
    ph, pw = output_size
    n_img = x.shape[0]
    # image index per roi from boxes_num
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n_img), counts,
                         total_repeat_length=boxes.shape[0])

    off = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(box, idx):
        feat = x[idx]
        x1, y1, x2, y2 = box * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        w = x2 - x1
        h = y2 - y1
        if not aligned:
            w = jnp.maximum(w, 1.0)
            h = jnp.maximum(h, 1.0)
        bin_h, bin_w = h / ph, w / pw
        # sr x sr samples per bin, averaged
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jnp.arange(sr) + 0.5)[None, :] * bin_h / sr)  # [ph, sr]
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jnp.arange(sr) + 0.5)[None, :] * bin_w / sr)  # [pw, sr]
        yy = iy.reshape(-1)[:, None]          # [ph*sr, 1]
        xx = ix.reshape(-1)[None, :]          # [1, pw*sr]
        grid_y = jnp.broadcast_to(yy, (ph * sr, pw * sr))
        grid_x = jnp.broadcast_to(xx, (ph * sr, pw * sr))
        v = _bilinear(feat, grid_y, grid_x)   # [C, ph*sr, pw*sr]
        C = v.shape[0]
        v = v.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))
        return v

    return jax.vmap(one_roi)(boxes, img_idx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference ``vision/ops.py roi_align``. x [N,C,H,W]; boxes
    [num_rois, 4] (x1,y1,x2,y2); boxes_num [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_align_raw(x, boxes, boxes_num, output_size=tuple(output_size),
                          spatial_scale=float(spatial_scale),
                          sampling_ratio=int(sampling_ratio),
                          aligned=bool(aligned))


@op("roi_pool")
def _roi_pool_raw(x, boxes, boxes_num, output_size=(1, 1), spatial_scale=1.0):
    ph, pw = output_size
    n_img = x.shape[0]
    H, W = x.shape[-2:]
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n_img), counts,
                         total_repeat_length=boxes.shape[0])

    def one_roi(box, idx):
        feat = x[idx]
        x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
        w = jnp.maximum(x2 - x1 + 1, 1)
        h = jnp.maximum(y2 - y1 + 1, 1)

        ys = jnp.arange(H)[None, :]
        xs = jnp.arange(W)[None, :]
        # bin boundaries per output cell
        oy = jnp.arange(ph)[:, None]
        ox = jnp.arange(pw)[:, None]
        y_lo = y1 + jnp.floor(oy * h / ph).astype(jnp.int32)
        y_hi = y1 + jnp.ceil((oy + 1) * h / ph).astype(jnp.int32)
        x_lo = x1 + jnp.floor(ox * w / pw).astype(jnp.int32)
        x_hi = x1 + jnp.ceil((ox + 1) * w / pw).astype(jnp.int32)
        ymask = (ys >= y_lo) & (ys < jnp.maximum(y_hi, y_lo + 1))  # [ph, H]
        xmask = (xs >= x_lo) & (xs < jnp.maximum(x_hi, x_lo + 1))  # [pw, W]
        m = ymask[:, None, :, None] & xmask[None, :, None, :]      # [ph,pw,H,W]
        big = jnp.where(m[None], feat[:, None, None, :, :], -jnp.inf)
        out = big.max(axis=(-2, -1))                               # [C, ph, pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(boxes, img_idx)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_pool_raw(x, boxes, boxes_num, output_size=tuple(output_size),
                         spatial_scale=float(spatial_scale))


def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Reference ``vision/ops.py nms``: greedy suppression, optionally
    per-category; returns kept indices sorted by score."""
    bv = boxes._value if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    n = bv.shape[0]
    sv = (scores._value if isinstance(scores, Tensor)
          else (jnp.asarray(scores) if scores is not None
                else jnp.arange(n, 0, -1, dtype=jnp.float32)))

    iou = _iou_matrix(bv)
    if category_idxs is not None:
        cv = (category_idxs._value if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs))
        same = cv[:, None] == cv[None, :]
        iou = jnp.where(same, iou, 0.0)  # suppress only within a category

    order = jnp.argsort(-sv)

    def body(i, keep):
        bi = order[i]
        # kept higher-scoring boxes that overlap bi too much suppress it
        sup = jnp.any(keep & (iou[bi, order] > iou_threshold)
                      & (jnp.arange(n) < i))
        return keep.at[i].set(~sup)

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones(n, bool))
    kept = order[jnp.nonzero(keep_sorted, size=n, fill_value=-1)[0]]
    kept = kept[keep_sorted.sum().astype(jnp.int32) > jnp.arange(n)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(kept.astype(jnp.int64))


@op("deform_conv2d")
def _deform_conv2d_raw(x, offset, weight, bias=None, mask=None, stride=1,
                       padding=0, dilation=1):
    """Deformable conv v1/v2 (mask=None → v1). x [N,C,H,W]; offset
    [N, 2*kh*kw, Ho, Wo]; weight [Co, C, kh, kw]; mask [N, kh*kw, Ho, Wo]."""
    N, C, H, W = x.shape
    Co, _, kh, kw = weight.shape
    s, p, dil = stride, padding, dilation
    Ho = (H + 2 * p - dil * (kh - 1) - 1) // s + 1
    Wo = (W + 2 * p - dil * (kw - 1) - 1) // s + 1

    xp = jnp.pad(x, ((0, 0), (0, 0), (p, p), (p, p)))

    base_y = jnp.arange(Ho) * s
    base_x = jnp.arange(Wo) * s
    ky = jnp.arange(kh) * dil
    kx = jnp.arange(kw) * dil

    def one_image(img, off, mk):
        # off [2*kh*kw, Ho, Wo] ordered (y0,x0,y1,x1,...) per kernel position
        off = off.reshape(kh * kw, 2, Ho, Wo)

        def one_kpos(kidx):
            i, j = kidx // kw, kidx % kw
            gy = base_y[:, None] + ky[i] + off[kidx, 0]
            gx = base_x[None, :] + kx[j] + off[kidx, 1]
            v = _bilinear(img, gy, gx)                  # [C, Ho, Wo]
            if mk is not None:
                v = v * mk[kidx]
            return v

        cols = jax.vmap(one_kpos)(jnp.arange(kh * kw))  # [kh*kw, C, Ho, Wo]
        return cols

    cols = jax.vmap(one_image)(xp, offset,
                               mask if mask is not None else
                               jnp.ones((N, kh * kw, Ho, Wo), x.dtype))
    # [N, kh*kw, C, Ho, Wo] x [Co, C, kh, kw] -> [N, Co, Ho, Wo]
    w2 = weight.transpose(0, 2, 3, 1).reshape(Co, kh * kw, C)
    out = jnp.einsum("nkchw,okc->nohw", cols, w2)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Reference ``vision/ops.py deform_conv2d`` (v1 without mask, v2 with).
    deformable_groups/groups > 1 are not supported yet (raises)."""
    if deformable_groups != 1 or groups != 1:
        raise NotImplementedError(
            "deform_conv2d: deformable_groups/groups > 1 not supported")

    def _square(v, what):
        if isinstance(v, int):
            return v
        v = tuple(v)
        if len(set(v)) != 1:
            raise NotImplementedError(
                f"deform_conv2d: non-square {what}={v} not supported")
        return v[0]

    s = _square(stride, "stride")
    p = _square(padding, "padding")
    d = _square(dilation, "dilation")
    args = (x, offset, weight) + ((bias,) if bias is not None else ())
    if bias is None and mask is None:
        return _deform_conv2d_raw(x, offset, weight, stride=s, padding=p,
                                  dilation=d)
    return _deform_conv2d_raw(x, offset, weight, bias, mask, stride=s,
                              padding=p, dilation=d)


from ..nn.layer.layers import Layer as _Layer


class DeformConv2D(_Layer):
    """Layer form (reference ``vision/ops.py DeformConv2D``)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        kw = kernel_size if isinstance(kernel_size, int) else kernel_size[-1]
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self.weight = self.create_parameter(
            [out_channels, in_channels, kh, kw], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation, mask=mask)
